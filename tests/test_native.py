"""Cross-language golden check: native C++ core vs the jnp oracle.

The reference never compared implementations against each other (SURVEY.md
§4); here the C++ host implementation and the JAX/Pallas stack must agree on
the same inputs — one correctness contract across languages."""

import numpy as np
import pytest

from ntxent_tpu.ops import oracle

native = pytest.importorskip("ntxent_tpu.native")

if not native.native_available():
    pytest.skip("no cmake/compiler available", allow_module_level=True)

try:
    native.load_library()
except Exception as e:  # build failure environment-gates the module
    pytest.skip(f"native build failed: {e}", allow_module_level=True)

from conftest import make_embeddings  # noqa: E402
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


@pytest.mark.parametrize("two_n,dim", [(16, 8), (64, 32), (128, 64)])
def test_native_forward_matches_oracle(rng, two_n, dim):
    z = np.asarray(make_embeddings(rng, two_n, dim))
    got = native.forward_cpu(z, 0.07)
    want = float(oracle.ntxent_loss(jnp.asarray(z), 0.07))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_native_lse_matches_oracle(rng):
    z = np.asarray(make_embeddings(rng, 32, 16))
    _, lse = native.forward_cpu(z, 0.07, return_lse=True)
    logits, _ = oracle._masked_logits(jnp.asarray(z), 0.07)
    want = np.asarray(jax.nn.logsumexp(logits, axis=-1))
    np.testing.assert_allclose(lse, want, rtol=1e-5)


def test_native_backward_matches_oracle(rng):
    z = np.asarray(make_embeddings(rng, 32, 16))
    got = native.backward_cpu(z, 0.07)
    want = np.asarray(oracle.ntxent_grad_oracle(jnp.asarray(z), 0.07))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_native_grad_output_scaling(rng):
    z = np.asarray(make_embeddings(rng, 16, 8))
    g1 = native.backward_cpu(z, 0.07, grad_output=1.0)
    g2 = native.backward_cpu(z, 0.07, grad_output=2.0)
    np.testing.assert_allclose(g2, 2.0 * g1, rtol=1e-5)


def test_native_rejects_bad_inputs(rng):
    z = np.asarray(make_embeddings(rng, 16, 8))
    with pytest.raises(ValueError):
        native.forward_cpu(z[:15], 0.07)  # odd rows
    with pytest.raises(ValueError):
        native.forward_cpu(z, -1.0)  # bad temperature
