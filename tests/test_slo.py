"""SLO engine + metric federation: burn rates, alerts, merged scrapes.

The ISSUE 10 observability-plane invariants that need no serving stack:
objective validation, the two-window burn-rate rule (fast catches
onset, slow confirms it is sustained), quantile objectives with
hysteresis, alert lifecycle (fire once per incident, resolve with
hysteresis, typed ``alert`` events + flight dump on firing), and the
federation merge rules (counters sum, gauges instance-label,
histogram windows pool through the exact quantile rule; a mid-scrape
worker death yields a partial-but-valid view, never a 500).
"""

from __future__ import annotations

import json

import pytest

from ntxent_tpu import obs
from ntxent_tpu.obs.aggregate import FleetAggregator, merge_states
from ntxent_tpu.obs.registry import MetricsRegistry
from ntxent_tpu.obs.slo import (
    AlertStore,
    Objective,
    SLOEngine,
    counter_total,
    histogram_quantile,
)

pytestmark = [pytest.mark.obs, pytest.mark.slo]


# ---------------------------------------------------------------------------
# objective declaration


class TestObjective:
    def test_unknown_kind_rejected(self):
        with pytest.raises(ValueError):
            Objective(name="x", kind="latency", target=1.0)

    def test_availability_needs_counters_and_sane_target(self):
        with pytest.raises(ValueError):
            Objective(name="a", kind="availability", target=0.99)
        with pytest.raises(ValueError):
            Objective(name="a", kind="availability", target=1.5,
                      total_metric="t", bad_metric="b")

    def test_quantile_needs_a_metric(self):
        with pytest.raises(ValueError):
            Objective(name="q", kind="quantile", target=1.0)

    def test_duplicate_names_rejected(self):
        o = Objective(name="q", kind="quantile", target=1.0, metric="m")
        with pytest.raises(ValueError):
            SLOEngine([o, o])


# ---------------------------------------------------------------------------
# federated-registry readers


class TestReaders:
    def test_counter_total_sums_label_sets_with_exclusion(self):
        r = MetricsRegistry()
        r.counter("rej", labels={"reason": "worker_error"}).inc(3)
        r.counter("rej", labels={"reason": "saturated"}).inc(10)
        r.counter("rej", labels={"reason": "unreachable"}).inc(2)
        assert counter_total(r, "rej") == 15
        assert counter_total(
            r, "rej", exclude={"reason": "saturated"}) == 5
        assert counter_total(r, "absent") == 0

    def test_histogram_quantile_pools_matching_label_sets(self):
        r = MetricsRegistry()
        a = r.histogram("lat", labels={"stage": "total"})
        b = r.histogram("lat", labels={"stage": "forward"})
        for v in range(10):
            a.observe(float(v))
            b.observe(1000.0)
        value, n = histogram_quantile(r, "lat", 0.5,
                                      labels={"stage": "total"})
        assert n == 10 and value == 5.0
        # No filter pools BOTH stages.
        _, n_all = histogram_quantile(r, "lat", 0.5)
        assert n_all == 20
        assert histogram_quantile(r, "lat", 0.5,
                                  labels={"stage": "x"}) == (None, 0)


# ---------------------------------------------------------------------------
# alert store


class TestAlertStore:
    def test_fire_once_per_incident_then_resolve(self):
        r = MetricsRegistry()
        store = AlertStore(registry=r)
        first = store.fire("lat", reason="p99 over bound", value=3.0,
                           threshold=2.0)
        refreshed = store.fire("lat", reason="still over", value=4.0)
        assert refreshed["since"] == first["since"]
        snap = store.snapshot()
        assert snap["firing"] == ["lat"]
        assert len(snap["history"]) == 1  # ONE incident, not two
        assert 'slo_alerts_total{slo="lat"} 1' \
            in r.render_prometheus()
        resolved = store.resolve("lat")
        assert resolved["state"] == "resolved"
        assert store.snapshot()["firing"] == []
        assert [h["state"] for h in store.snapshot()["history"]] \
            == ["firing", "resolved"]

    def test_resolving_nothing_is_a_noop(self):
        assert AlertStore().resolve("ghost") is None


# ---------------------------------------------------------------------------
# burn-rate availability objective


def _avail_engine(**kw):
    clock = {"t": 0.0}
    obj = Objective(name="avail", kind="availability", target=0.9,
                    total_metric="req", bad_metric="bad",
                    fast_window_s=10.0, slow_window_s=40.0,
                    burn_factor=2.0, breach_ticks=1, clear_ticks=1,
                    **kw)
    engine = SLOEngine([obj], clock=lambda: clock["t"])
    return engine, clock


def _reg(total: float, bad: float) -> MetricsRegistry:
    r = MetricsRegistry()
    r.counter("req").inc(total)
    r.counter("bad").inc(bad)
    return r


class TestBurnRate:
    def test_sustained_burn_fires_and_recovery_resolves(self):
        engine, clock = _avail_engine()
        # Budget = 0.1; burn_factor 2 -> page at windowed error rate
        # >= 0.2. Feed 50% errors for 45 s: both windows burn hot.
        total = bad = 0.0
        fired = []
        for _ in range(9):
            clock["t"] += 5.0
            total += 10
            bad += 5
            fired += engine.evaluate(_reg(total, bad))
        assert any(t["state"] == "firing" for t in fired), fired
        assert engine.store.snapshot()["firing"] == ["avail"]
        # Clean traffic long enough to flush both windows: resolves.
        resolved = []
        for _ in range(12):
            clock["t"] += 5.0
            total += 10
            resolved += engine.evaluate(_reg(total, bad))
        assert any(t["state"] == "resolved" for t in resolved)
        assert engine.store.snapshot()["firing"] == []

    def test_short_blip_does_not_page(self):
        # The slow window is the blip filter: one bad tick inside an
        # otherwise clean run must not fire.
        engine, clock = _avail_engine()
        total = bad = 0.0
        fired = []
        for i in range(12):
            clock["t"] += 5.0
            total += 10
            if i == 6:
                bad += 5  # one 50%-error tick
            fired += engine.evaluate(_reg(total, bad))
        assert not fired, fired

    def test_no_traffic_is_not_an_outage(self):
        engine, clock = _avail_engine()
        fired = []
        for _ in range(10):
            clock["t"] += 5.0
            fired += engine.evaluate(_reg(0.0, 0.0))
        assert not fired


# ---------------------------------------------------------------------------
# quantile objective: hysteresis + side effects


def _lat_reg(*values: float) -> MetricsRegistry:
    r = MetricsRegistry()
    h = r.histogram("lat", labels={"stage": "total"})
    for v in values:
        h.observe(v)
    return r


class TestQuantileObjective:
    def _engine(self, **kw):
        kw.setdefault("breach_ticks", 2)
        kw.setdefault("clear_ticks", 2)
        obj = Objective(name="lat_p99", kind="quantile", target=100.0,
                        metric="lat", labels={"stage": "total"},
                        q=0.99, **kw)
        return SLOEngine([obj])

    def test_breach_ticks_filter_single_bad_scrapes(self):
        engine = self._engine()
        bad = _lat_reg(*([50.0] * 5 + [500.0] * 5))
        good = _lat_reg(*([50.0] * 10))
        assert engine.evaluate(bad) == []      # 1st breach: held
        assert engine.evaluate(good) == []     # streak reset
        assert engine.evaluate(bad) == []
        fired = engine.evaluate(bad)           # 2nd consecutive: fires
        assert fired and fired[0]["state"] == "firing"
        assert fired[0]["value"] == 500.0
        # Still breaching: no duplicate incident.
        assert engine.evaluate(bad) == []
        # Two clean ticks resolve.
        assert engine.evaluate(good) == []
        resolved = engine.evaluate(good)
        assert resolved and resolved[0]["state"] == "resolved"

    def test_min_samples_gates_judgement(self):
        engine = self._engine(min_samples=8, breach_ticks=1)
        assert engine.evaluate(_lat_reg(500.0, 600.0)) == []
        fired = engine.evaluate(_lat_reg(*([500.0] * 8)))
        assert fired and fired[0]["state"] == "firing"

    def test_firing_emits_alert_event_and_flight_dump(self, tmp_path):
        log = obs.EventLog(str(tmp_path / "events.jsonl"))
        previous = obs.install(log)
        try:
            log.emit("span", name="context")  # something for the tail
            engine = self._engine(breach_ticks=1)
            fired = engine.evaluate(_lat_reg(*([500.0] * 4)))
            assert fired
            log.flush()
            alerts = obs.read_events(str(tmp_path / "events.jsonl"),
                                     event="alert")
            assert len(alerts) == 1
            assert alerts[0]["slo"] == "lat_p99"
            assert alerts[0]["state"] == "firing"
            assert alerts[0]["value"] == 500.0
            flights = list(tmp_path.glob("flight_*.jsonl"))
            assert len(flights) == 1
            header = json.loads(
                flights[0].read_text().splitlines()[0])
            assert header["reason"] == "slo_breach:lat_p99"
        finally:
            obs.install(previous)
            log.close()


# ---------------------------------------------------------------------------
# federation merge rules (no HTTP)


def _worker_registry(requests: float, depth: float,
                     latencies: list[float]) -> MetricsRegistry:
    r = MetricsRegistry()
    r.counter("serving_requests_total").inc(requests)
    r.gauge("serving_queue_depth").set(depth)
    h = r.histogram("serving_latency_ms", labels={"stage": "total"})
    for v in latencies:
        h.observe(v)
    return r


class TestMergeStates:
    def test_counters_sum_gauges_label_histograms_pool(self):
        w0 = _worker_registry(10, 3, [1.0, 2.0, 3.0])
        w1 = _worker_registry(32, 7, [100.0, 200.0])
        merged = merge_states({"w0": w0.dump_state(),
                               "w1": w1.dump_state()})
        c = merged.collect()
        # Counters: the fleet total IS the sum of the per-worker
        # scrapes (the acceptance equality).
        assert c["serving_requests_total"] == 42
        # Gauges: per-instance series, never summed.
        assert c["serving_queue_depth"]['{instance="w0"}'] == 3
        assert c["serving_queue_depth"]['{instance="w1"}'] == 7
        # Histograms: windows pooled, exact quantile over the union.
        h = merged.histogram("serving_latency_ms",
                             labels={"stage": "total"})
        assert h.count == 5
        assert sorted(h._window) == [1.0, 2.0, 3.0, 100.0, 200.0]
        value, n = histogram_quantile(merged, "serving_latency_ms",
                                      0.99, labels={"stage": "total"})
        assert n == 5 and value == 200.0
        # Both views stay renderable.
        prom = merged.render_prometheus()
        assert "serving_requests_total 42" in prom
        assert 'fleet_fed_instance_up{instance="w0"} 1' in prom

    def test_stale_instance_marked_down_but_included(self):
        w0 = _worker_registry(10, 3, [1.0])
        merged = merge_states({"w0": w0.dump_state(),
                               "w1": w0.dump_state()},
                              stale={"w1"})
        prom = merged.render_prometheus()
        assert 'fleet_fed_instance_up{instance="w0"} 1' in prom
        assert 'fleet_fed_instance_up{instance="w1"} 0' in prom
        assert merged.collect()["serving_requests_total"] == 20

    def test_malformed_state_skipped_not_fatal(self):
        w0 = _worker_registry(5, 1, [])
        merged = merge_states({
            "good": w0.dump_state(),
            "mid_restart": {"metrics": [{"name": "x"},  # no kind
                                        {"kind": "counter"},  # no name
                                        "not even a dict"]},
            "garbage": {"oops": True},
        })
        assert merged.collect()["serving_requests_total"] == 5


# ---------------------------------------------------------------------------
# the aggregator over real scrape endpoints (MetricsServer workers)


class TestFleetAggregator:
    def test_scrape_merge_and_partial_on_death(self):
        r0 = _worker_registry(11, 1, [5.0])
        r1 = _worker_registry(31, 2, [7.0])
        s0 = obs.MetricsServer(r0).start()
        s1 = obs.MetricsServer(r1).start()
        local = MetricsRegistry()
        local.counter("fleet_requests_total").inc(40)
        targets = {"w0": f"http://127.0.0.1:{s0.port}",
                   "w1": f"http://127.0.0.1:{s1.port}"}
        agg = FleetAggregator(lambda: targets,
                              local={"router": local},
                              timeout_s=2.0, stale_after=3)
        try:
            merged = agg.scrape_once()
            c = merged.collect()
            assert c["serving_requests_total"] == 42
            assert c["fleet_requests_total"] == 40
            assert c["fleet_fed_instances"] == 3
            # w1 dies MID-SCRAPE: the next tick is partial but valid —
            # last-good state retained, instance marked down, no
            # exception, the router's local view still merged.
            s1.close()
            merged = agg.scrape_once()
            c = merged.collect()
            assert c["serving_requests_total"] == 42  # last-good kept
            assert c["fleet_fed_instance_up"]['{instance="w0"}'] == 1
            assert c["fleet_fed_instance_up"]['{instance="w1"}'] == 0
            assert agg.failures == 1
            assert agg.snapshot()["stale"] == ["w1"]
            # Past stale_after consecutive failures the dead
            # incarnation's counters drop (a restarted worker must not
            # be double-counted against its ghost).
            agg.scrape_once()
            merged = agg.scrape_once()
            c = merged.collect()
            assert c["serving_requests_total"] == 11
        finally:
            s0.close()
            s1.close()

    def test_merged_scrapes_on_demand_when_cold(self):
        r0 = _worker_registry(3, 0, [])
        s0 = obs.MetricsServer(r0).start()
        try:
            agg = FleetAggregator(
                lambda: {"w0": f"http://127.0.0.1:{s0.port}"})
            merged = agg.merged()  # never ticked: must scrape now
            assert merged.collect()["serving_requests_total"] == 3
        finally:
            s0.close()

    def test_on_merge_hooks_run_per_tick_and_survive_errors(self):
        r0 = _worker_registry(3, 0, [])
        s0 = obs.MetricsServer(r0).start()
        try:
            agg = FleetAggregator(
                lambda: {"w0": f"http://127.0.0.1:{s0.port}"})
            seen = []

            def bad_hook(_reg):
                raise RuntimeError("boom")

            agg.on_merge.append(bad_hook)
            agg.on_merge.append(
                lambda reg:
                seen.append(reg.collect()["serving_requests_total"]))
            agg.scrape_once()
            agg.scrape_once()
            assert seen == [3, 3]
        finally:
            s0.close()

    def test_slo_engine_rides_federation_ticks(self):
        r0 = _worker_registry(0, 0, [500.0] * 8)
        s0 = obs.MetricsServer(r0).start()
        try:
            agg = FleetAggregator(
                lambda: {"w0": f"http://127.0.0.1:{s0.port}"})
            store = AlertStore()
            engine = SLOEngine(
                [Objective(name="lat", kind="quantile", target=100.0,
                           metric="serving_latency_ms",
                           labels={"stage": "total"}, q=0.99,
                           breach_ticks=2, clear_ticks=1)],
                store=store)
            agg.on_merge.append(engine.evaluate)
            agg.scrape_once()
            assert store.snapshot()["firing"] == []
            agg.scrape_once()
            assert store.snapshot()["firing"] == ["lat"]
        finally:
            s0.close()
