"""Memory-bound retrieval at scale (ISSUE 17): PQ codes, fused
batched scan, sharded index plane, durable state.

Four proofs ride here:
 - PQ never costs correctness: codes select candidates, exact re-rank
   scores them, so recall stays >= 0.95 at a fraction of the bytes.
 - The fused batched scan is row-for-row IDENTICAL to per-query scans
   (fusion is an economy, not an approximation) and provably shares
   list passes across the batch.
 - A dead shard degrades recall, never availability — proven THROUGH
   the router, not against a bare fanout.
 - A rooted manager reopens TRAINED (zero k-means on restart) and
   replays its docstore log, truncated tails included.

JAX-free by construction, like everything on the router's import
surface (the tripwire in test_fleet pins it).
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from ntxent_tpu.obs.registry import MetricsRegistry
from ntxent_tpu.retrieval import (
    CodedLists,
    IndexManager,
    IndexShard,
    PQCodec,
    ShardFanout,
    ShardServer,
    VectorIndex,
    batched_scan,
    brute_force_topk,
    kmeans,
)
from ntxent_tpu.retrieval import shard as shard_mod
from ntxent_tpu.serving import FleetRouter, WorkerPool

pytestmark = pytest.mark.retrieval


def clustered(n, dim=16, k=8, noise=0.15, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(k, dim).astype(np.float32)
    x = centers[rng.randint(k, size=n)] \
        + noise * rng.randn(n, dim).astype(np.float32)
    return x / np.linalg.norm(x, axis=1, keepdims=True)


def recall_at_k(got_ids, true_ids):
    hit = sum(len(set(g) & set(t)) for g, t in zip(got_ids, true_ids))
    return hit / float(np.asarray(true_ids).size)


# ---------------------------------------------------------------------------
# PQ codec


class TestPQCodec:
    def test_roundtrip_codes_are_bytes_and_decode_close(self):
        x = clustered(2000, dim=32, seed=1)
        codec = PQCodec(32, m=8, seed=0)
        codec.train(x)
        codes = codec.encode(x)
        assert codes.dtype == np.uint8 and codes.shape == (2000, 8)
        approx = codec.decode(codes)
        # Rows are unit-norm; reconstruction must land well inside the
        # unit ball of its source (8 bytes standing in for 128).
        err = np.linalg.norm(approx - x, axis=1)
        assert float(err.mean()) < 0.35

    def test_adc_tables_score_like_decoded_dot(self):
        # ADC is exactly "query . decode(code)" factored into m table
        # lookups — the identity the fused scan kernel relies on.
        x = clustered(512, dim=16, seed=2)
        q = clustered(4, dim=16, seed=3)
        codec = PQCodec(16, m=4, seed=0)
        codec.train(x)
        codes = codec.encode(x)
        tables = codec.adc_tables(q)  # [Q, m, ksub]
        adc = np.zeros((4, 512), np.float32)
        for qi in range(4):
            for sub in range(4):
                adc[qi] += tables[qi, sub, codes[:, sub]]
        want = q @ codec.decode(codes).T
        np.testing.assert_allclose(adc, want, rtol=1e-4, atol=1e-5)

    def test_wire_roundtrip_is_exact(self):
        x = clustered(800, dim=16, seed=4)
        codec = PQCodec(16, m=4, seed=0)
        codec.train(x)
        again = PQCodec.from_wire(codec.to_wire())
        np.testing.assert_array_equal(again.codebooks, codec.codebooks)
        np.testing.assert_array_equal(again.encode(x), codec.encode(x))
        # An untrained codec has nothing to ship.
        with pytest.raises(RuntimeError):
            PQCodec(16, m=4).to_wire()

    def test_index_recall_floor_at_an_eighth_of_the_bytes(self):
        # The acceptance bar, in miniature: PQ-coded search >= 0.95
        # recall@10 against exact, while the scanned bytes/row sit at
        # <= 1/8 of the raw float32 row.
        dim, n, nq = 64, 6000, 64
        x = clustered(n, dim=dim, k=16, seed=5)
        idx = VectorIndex(dim, train_rows=2048, n_centroids=32,
                          nprobe=8, pq_m=8)
        idx.insert(np.arange(n), x)
        assert idx.maintain() and idx.trained
        assert idx._codec is not None
        q = clustered(nq, dim=dim, k=16, seed=6)
        true_ids, _ = brute_force_topk(q, np.arange(n), x, 10)
        got_ids, got_scores = idx.search(q, k=10)
        assert recall_at_k(got_ids, true_ids) >= 0.95
        assert idx.scan_bytes_per_row() <= dim * 4 / 8.0
        # Returned scores are EXACT inner products (the PQ
        # approximation only selects candidates, never scores them).
        for qi in range(4):
            for j, rid in enumerate(got_ids[qi]):
                assert got_scores[qi][j] == pytest.approx(
                    float(q[qi] @ x[rid]), abs=1e-5)


# ---------------------------------------------------------------------------
# fused batched scan


def _coded_fixture(n=1500, dim=16, n_lists=8, m=4, seed=7):
    x = clustered(n, dim=dim, k=n_lists, seed=seed)
    centroids = kmeans(x, n_lists, seed=0)
    codec = PQCodec(dim, m=m, seed=0)
    codec.train(x)
    coded = CodedLists(centroids, codec)
    src = coded.add_source(x)
    coded.add(np.arange(n), x, src, np.arange(n, dtype=np.int32))
    return coded, x


class TestBatchedScan:
    def test_batch_is_row_identical_to_per_query(self):
        coded, x = _coded_fixture()
        q = clustered(32, dim=16, k=8, seed=8)
        bids, bscores = batched_scan(coded, q, k=10, nprobe=3,
                                     rerank=128)
        for qi in range(32):
            sids, sscores = batched_scan(coded, q[qi], k=10, nprobe=3,
                                         rerank=128)
            np.testing.assert_array_equal(bids[qi], sids[0])
            np.testing.assert_array_equal(bscores[qi], sscores[0])

    def test_fusion_shares_list_passes_and_scores_exactly(self):
        coded, x = _coded_fixture()
        # Identical queries probe identical lists: the fused pass must
        # walk each probed list ONCE for the whole batch.
        q = np.tile(clustered(1, dim=16, k=8, seed=9), (16, 1))
        batched = {}
        batched_scan(coded, q, k=5, nprobe=3, rerank=64, stats=batched)
        single = {}
        for qi in range(16):
            batched_scan(coded, q[qi], k=5, nprobe=3, rerank=64,
                         stats=single)
        assert batched["list_passes"] == single["list_passes"] // 16
        assert batched["code_bytes"] < single["code_bytes"]
        # rows_scored counts query-row pairs, so fusion leaves it
        # unchanged — the economy is bytes gathered, not rows scored.
        assert batched["rows_scored"] == single["rows_scored"]
        ids, scores = batched_scan(coded, q[:1], k=5, nprobe=3,
                                   rerank=64)
        for j, rid in enumerate(ids[0]):
            assert scores[0][j] == pytest.approx(
                float(q[0] @ x[rid]), abs=1e-5)

    def test_widens_when_probed_lists_run_short(self):
        coded, x = _coded_fixture(n=60, n_lists=16)
        q = clustered(2, dim=16, k=8, seed=10)
        # k near the corpus with one probed list: the scan must widen
        # to every list rather than pad a short answer with -1.
        ids, _ = batched_scan(coded, q, k=32, nprobe=1, rerank=64)
        assert (ids >= 0).all()


# ---------------------------------------------------------------------------
# shard plane (unit level)


class TestIndexShard:
    def test_owner_partition_rejects_misrouted_rows(self):
        dim, n = 16, 400
        x = clustered(n, dim=dim, seed=11)
        centroids = kmeans(x, 8, seed=0)
        codec = PQCodec(dim, m=4, seed=0)
        codec.train(x)
        s = IndexShard(dim)
        s.init_plane(centroids, codec, shard_id=1, n_shards=3)
        stored = s.insert(np.arange(n), x)
        owned = int(np.sum(
            shard_mod.shard_owner(
                np.argmax(x @ centroids.T, axis=1), 3) == 1))
        assert stored == owned and s.misrouted == n - owned
        assert 0 < stored < n  # the partition is real on this data


# ---------------------------------------------------------------------------
# kill-a-shard, THROUGH the router


class _EmbedStub:
    """Deterministic /embed worker: emb = normalize(flatten(row)[:4])
    — same input, same embedding, so a search for an inserted input
    must retrieve that row's id."""

    def __init__(self, step=1, dim=4):
        self.step = step
        self.dim = dim
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # noqa: N802
                pass

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length", 0))
                req = json.loads(self.rfile.read(n) or b"{}")
                emb = []
                for r in req.get("inputs", []):
                    v = np.asarray(r, np.float32).ravel()[:stub.dim]
                    emb.append((v / np.linalg.norm(v)).tolist())
                body = json.dumps({"embeddings": emb, "dim": stub.dim,
                                   "rows": len(emb)}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.send_header("X-Checkpoint-Step", str(stub.step))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _post(router, path, payload):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}{path}",
        data=json.dumps(payload).encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestShardedRouter:
    def test_dead_shard_degrades_recall_never_availability(self):
        dim, n = 4, 96
        worker = _EmbedStub(step=1, dim=dim)
        pool = WorkerPool(canary_min_requests=4, canary_fraction=1.0)
        pool.upsert("w0", worker.url)
        pool.set_health("w0", alive=True, ready=True,
                        checkpoint_step=1)
        servers = [ShardServer(dim).start() for _ in range(3)]
        fanout = ShardFanout([s.url for s in servers], dim=dim,
                             train_rows=64, n_centroids=8, nprobe=8,
                             pq_m=2, seed=0)
        router = FleetRouter(pool, cache=None, example_shape=(2, 2),
                             port=0)
        router.attach_shards(fanout)
        router.start()
        try:
            rows = np.random.RandomState(12).rand(n, 2, 2).astype(
                np.float32).tolist()
            code, res = _post(router, "/index/insert",
                              {"inputs": rows})
            assert code == 200 and res["stored"] == n
            assert fanout.trained  # past train_rows: plane is live
            snap = fanout.snapshot()
            per_shard = [s["rows"] for s in snap["shards"]]
            assert sum(per_shard) == n and min(per_shard) > 0

            def search_recall(k=3):
                hits, answered = 0, 0
                for i in range(0, n, 4):
                    code, res = _post(router, "/search",
                                      {"inputs": [rows[i]], "k": k})
                    assert code == 200  # availability, always
                    answered += 1
                    if i in res["ids"][0]:
                        hits += 1
                return hits / answered, res

            full, res = search_recall()
            assert res["shards"]["ok"] == 3
            assert res["shards"]["degraded"] is False
            assert full >= 0.9  # every shard probes the same lists

            servers[1].stop()  # kill one shard mid-flight
            degraded, res = search_recall()
            assert res["shards"]["ok"] == 2
            assert res["shards"]["degraded"] is True
            # Exactly the dead shard's rows went dark: recall drops by
            # about its share of the corpus, and not more.
            dead_share = per_shard[1] / float(n)
            assert degraded < full
            assert degraded >= full - dead_share - 0.15
            # /index snapshot carries the plane's health.
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/index")
            with urllib.request.urlopen(req, timeout=15) as r:
                snap = json.loads(r.read())
            alive = sum(1 for s in snap["shard_plane"]["shards"]
                        if s["alive"])
            assert alive == 2
        finally:
            router.close()
            fanout.close()
            for s in servers:
                s.stop()
            worker.close()


# ---------------------------------------------------------------------------
# durable state


class TestDurableState:
    def test_reopen_restores_trained_with_zero_clustering(
            self, tmp_path, monkeypatch):
        dim = 8
        x = clustered(700, dim=dim, seed=13)
        m = IndexManager(dim, root=tmp_path, train_rows=512,
                         n_centroids=8, seal_rows=256, pq_m=4)
        m.insert(x, x, step=1)
        m.maintain()  # train + seal + snapshot centroids/codebooks
        assert m.active().trained
        m.stop()

        # A restarted manager must come up TRAINED from the snapshot:
        # any k-means on the reopen path is the regression this test
        # exists to catch (rebuild-on-restart at 100M rows is an
        # outage, not a warmup).
        def _boom(*a, **kw):
            raise AssertionError("reopen ran k-means")

        import ntxent_tpu.retrieval.index as index_mod
        import ntxent_tpu.retrieval.pq as pq_mod
        monkeypatch.setattr(index_mod, "kmeans", _boom)
        monkeypatch.setattr(pq_mod, "kmeans_l2", _boom)
        again = IndexManager(dim, root=tmp_path, train_rows=512,
                             n_centroids=8, seal_rows=256, pq_m=4)
        again.activate(1)
        idx = again.active()
        assert idx.trained and idx.trained_from_snapshot
        got = again.search(x[:8], k=1)
        assert [r[0] for r in got["ids"]] == list(range(8))
        snap = again.snapshot()
        assert snap["docstore_durable"] is True
        assert snap["versions"]["1"]["from_snapshot"] is True
        again.stop()

    def test_docstore_log_replays_compacts_and_survives_garbage(
            self, tmp_path):
        dim = 4
        m = IndexManager(dim, root=tmp_path, docstore_rows=16,
                         train_rows=10_000)
        m._doc_compact_floor = 8  # make dead-record pressure cheap
        x = clustered(40, dim=dim, seed=14)
        m.insert(x, x, step=1)  # 24 evictions > max(16, 8)
        m.maintain()            # heavy tick: fsync + compact the log
        ids0, rows0 = m.docstore_inputs()
        assert ids0 == list(range(24, 40))
        assert float(m.metrics._ops["docstore_compact"].value) >= 1
        m.stop()

        # Torn tail: a crash mid-append leaves garbage. Replay must
        # keep every whole record, drop the tail, AND truncate it off
        # so post-restart appends stay readable forever after.
        log = tmp_path / "docstore.log"
        good = log.stat().st_size
        with open(log, "ab") as f:
            f.write(b"\x07garbage")
        again = IndexManager(dim, root=tmp_path, docstore_rows=16,
                             train_rows=10_000)
        ids1, rows1 = again.docstore_inputs()
        assert ids1 == ids0
        assert log.stat().st_size == good
        np.testing.assert_array_equal(np.asarray(rows1),
                                      np.asarray(rows0))
        again.insert(clustered(2, dim=dim, seed=15),
                     clustered(2, dim=dim, seed=15), step=1)
        again.stop()
        third = IndexManager(dim, root=tmp_path, docstore_rows=16,
                             train_rows=10_000)
        ids2, _ = third.docstore_inputs()
        assert len(ids2) == 16 and max(ids2) == 41
        third.stop()

    def test_heavy_gate_defers_then_forces(self):
        reg = MetricsRegistry()
        m = IndexManager(4, registry=reg)
        m.insert(clustered(8, dim=4, seed=16),
                 clustered(8, dim=4, seed=16), step=1)
        m.heavy_gate = lambda: False
        m.heavy_defer_ticks = 3
        for _ in range(3):
            m.maintain()
        ops = m.metrics._ops
        assert float(ops["heavy_defer"].value) == 3
        # The 4th consecutive busy tick forces heavy work through —
        # a fleet that is never idle still gets its compactions.
        m.maintain()
        assert float(ops["heavy_forced"].value) == 1
        assert float(ops["heavy_defer"].value) == 3
        # A broken gate fails OPEN (maintenance proceeds).
        def _broken():
            raise RuntimeError("gate source gone")
        m.heavy_gate = _broken
        m.maintain()
        assert float(ops["heavy_forced"].value) == 1
        assert float(ops["heavy_defer"].value) == 3
