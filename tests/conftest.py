"""Test configuration: run the whole suite on a virtual 8-device CPU mesh.

The reference's tests hard-require a physical GPU and cannot run otherwise
(/root/reference/tests/test_forward.cpp:8-11) — a gap this suite closes
(SURVEY.md §4.3): JAX's forced host-platform device count gives 8 virtual CPU
devices, so single-chip kernels run in Pallas interpret mode and the
distributed mesh/collective paths run for real, with no TPU needed. The same
tests run unchanged on a real ICI mesh.
"""

import faulthandler
import os

# Native-death forensics (ISSUE 5): the suite has a pre-existing
# deterministic SIGABRT in native code at ~item 337 on some hosts (the
# persistent-cache reload hazard below) that dies with NO Python frame.
# faulthandler turns SIGSEGV/SIGABRT/SIGBUS/SIGILL into all-thread stack
# dumps, and the watchdog timer dumps (without killing) a run that hangs
# past the tier-1 timeout's margin — so the next silent die names its
# test instead of costing a bisection. NTXENT_TEST_HANG_DUMP_S=0 disables
# the timer.
faulthandler.enable(all_threads=True)
_HANG_DUMP_S = float(os.environ.get("NTXENT_TEST_HANG_DUMP_S", "840"))
if _HANG_DUMP_S > 0:
    faulthandler.dump_traceback_later(_HANG_DUMP_S, repeat=True)

# One suite, every backend (SURVEY.md §4): default is the 8-device virtual
# CPU mesh; NTXENT_TEST_PLATFORM=tpu runs the same tests on real hardware
# (single-chip kernels compile natively; mesh tests need >= 8 chips or skip).
_PLATFORM = os.environ.get("NTXENT_TEST_PLATFORM", "cpu")

flags = os.environ.get("XLA_FLAGS", "")
if _PLATFORM == "cpu" and "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# NTXENT_TEST_PLATFORM=tpu means "run on the accelerator, whatever JAX calls
# it here": a real host registers the platform as 'tpu', the tunneled chip
# registers as 'axon'. Forcing JAX_PLATFORMS=tpu would fail init on the
# tunnel, so in tpu mode we leave platform selection to JAX (accelerators
# outrank cpu) and fail fast below if none answered.
if _PLATFORM != "tpu":
    os.environ["JAX_PLATFORMS"] = _PLATFORM
else:
    # A stale JAX_PLATFORMS (e.g. exported by a prior cpu-tier run) would
    # silently pin the backend and turn a healthy chip into a confusing
    # "no accelerator" failure below.
    os.environ.pop("JAX_PLATFORMS", None)

import jax  # noqa: E402  (import after env setup)

if _PLATFORM != "tpu":
    # A site plugin may have forced another platform at interpreter startup
    # (jax_platforms config wins over the env var) — force it back for tests.
    jax.config.update("jax_platforms", _PLATFORM)
else:
    # A site plugin may have pinned jax_platforms at interpreter startup
    # (config wins over env); restore auto-selection so the accelerator
    # can win, tolerating jax versions that reject a None/'' update.
    try:
        jax.config.update("jax_platforms", None)
    except Exception:
        pass
    _backend = jax.default_backend()
    if _backend not in ("tpu", "axon"):
        raise RuntimeError(
            "NTXENT_TEST_PLATFORM=tpu but no accelerator backend initialized "
            f"(got {_backend!r}) — is the chip/tunnel alive?")

# Persistent XLA compilation cache: OFF BY DEFAULT since ISSUE 5. The
# reload-abort hazard below stopped being an isolated curiosity this
# round: with a warm cache the suite deterministically died with heap
# corruption (SIGSEGV/SIGABRT, varying detonation site — bisected to
# test_api's reloaded executables corrupting the heap and any later
# allocation-heavy test crashing), at suite item ~63 this round and ~337
# in round 4. A fresh checkout always runs cold anyway (the tier-1
# driver never sees a warm cache), and the cold tier now measures ~5 min
# against the 870 s budget — so warmth only ever served repeat local
# runs, which are exactly the runs that crashed. Opt back in on a host
# whose XLA build reloads cleanly by pointing NTXENT_JAX_CACHE at a
# directory; the host-tagging below still applies.
#
# The cache dir is suffixed with a hash of the host's CPU feature flags:
# XLA:CPU persists AOT machine code, and this workspace migrates across a
# heterogeneous host fleet — an executable compiled for another machine's
# features loads with a cpu_aot_loader feature-mismatch warning and XLA
# itself says it "could lead to execution errors such as SIGILL".
# Per-host-type subdirs remove that class entirely; each machine type
# warms its own cache. (Self-written entries also warn, about XLA's own
# "+prefer-no-scatter" pseudo-features — that one is benign.)
#
# RELOAD-ABORT HAZARD (root-caused 2026-07-31 after three incidents):
# certain programs' serialized XLA:CPU executables deterministically
# SIGABRT with no error text when RELOADED from this cache in a later
# process (fatal at the first block_until_ready), while fresh compiles
# of the same program are always green. Known instance: the
# GSPMD-sharded oracle-InfoNCE step (GSPMD emits scatter; the
# cpu_aot_loader "+prefer-no-scatter" pseudo-feature mismatch is the
# suspected class) — its test opts out of the cache via the
# no_persistent_compilation_cache fixture (tests/test_fsdp.py). If the
# suite starts dying with a bare "Fatal Python error: Aborted" inside
# jax Array._value: identify the test (dots count vs collection order),
# reproduce it ALONE against the warm cache, and give it the fixture;
# `rm -rf .jax_cache` only hides the problem until the next warm run.


def _host_cpu_tag() -> str:
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    import hashlib

                    return hashlib.sha1(line.encode()).hexdigest()[:10]
    except OSError:
        pass
    import platform

    return platform.machine() or "unknown"


_JAX_CACHE = os.environ.get("NTXENT_JAX_CACHE", "")
if _JAX_CACHE:
    _JAX_CACHE = os.path.join(_JAX_CACHE, _host_cpu_tag())
    jax.config.update("jax_compilation_cache_dir", _JAX_CACHE)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import jax.numpy as jnp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    return jax.devices()


@pytest.fixture()
def rng():
    return jax.random.PRNGKey(42)


def make_embeddings(key, rows, dim, dtype=jnp.float32, scale=1.0):
    """randn + L2-normalize, mirroring tests/test_utils.hpp:7-14."""
    from ntxent_tpu.ops.oracle import cosine_normalize

    z = jax.random.normal(key, (rows, dim), jnp.float32)
    return (cosine_normalize(z) * scale).astype(dtype)
