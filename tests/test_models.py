"""Model families: shapes, dtypes, and SimCLR embedding contracts."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ntxent_tpu.models import (
    CLIPModel,
    ProjectionHead,
    ResNet,
    SimCLRModel,
    TextTransformer,
    ViT_Ti16,
)

TinyResNet = functools.partial(ResNet, stage_sizes=(1, 1), small_images=True,
                               dtype=jnp.float32)
TinyText = functools.partial(TextTransformer, vocab_size=64, max_len=16,
                             hidden_dim=32, depth=1, num_heads=2,
                             dtype=jnp.float32)
TinyViT = functools.partial(ViT_Ti16, dtype=jnp.float32)


def test_resnet_feature_shape(rng):
    model = TinyResNet()
    vars_ = model.init(rng, jnp.zeros((2, 32, 32, 3)), train=False)
    h = model.apply(vars_, jnp.ones((4, 32, 32, 3)), train=False)
    assert h.shape == (4, 64 * 2 * 4)  # width*2^(stages-1)*expansion
    assert h.dtype == jnp.float32


def test_resnet_params_are_fp32(rng):
    model = ResNet(stage_sizes=(1,), small_images=True)  # bf16 activations
    vars_ = model.init(rng, jnp.zeros((1, 32, 32, 3)), train=False)
    for leaf in jax.tree.leaves(vars_["params"]):
        assert leaf.dtype == jnp.float32


def test_vit_cls_features(rng):
    model = TinyViT()
    vars_ = model.init(rng, jnp.zeros((2, 32, 32, 3)), train=False)
    h = model.apply(vars_, jnp.ones((2, 32, 32, 3)), train=False)
    assert h.shape == (2, 192)
    assert bool(jnp.all(jnp.isfinite(h)))


def test_simclr_model_outputs_normalized(rng):
    model = SimCLRModel(encoder=TinyResNet, proj_hidden_dim=32, proj_dim=16)
    vars_ = model.init(rng, jnp.zeros((2, 32, 32, 3)), train=False)
    z, _ = model.apply(vars_, jax.random.uniform(rng, (8, 32, 32, 3)),
                       train=True, mutable=["batch_stats"])
    assert z.shape == (8, 16)
    np.testing.assert_allclose(np.asarray(jnp.linalg.norm(z, axis=1)), 1.0,
                               rtol=1e-5)


def test_projection_head_shapes(rng):
    head = ProjectionHead(hidden_dim=32, out_dim=8, dtype=jnp.float32)
    vars_ = head.init(rng, jnp.zeros((2, 64)), train=False)
    out = head.apply(vars_, jnp.ones((4, 64)), train=False)
    assert out.shape == (4, 8)


def test_clip_dual_encoder(rng):
    model = CLIPModel(image_encoder=TinyViT, text_encoder=TinyText,
                      embed_dim=16)
    imgs = jnp.ones((2, 32, 32, 3))
    toks = jnp.array([[1, 2, 3, 0, 0, 0, 0, 0]] * 2, jnp.int32)
    vars_ = model.init(rng, imgs, toks, train=False)
    zi, zt, scale = model.apply(vars_, imgs, toks, train=False)
    assert zi.shape == (2, 16) and zt.shape == (2, 16)
    np.testing.assert_allclose(np.asarray(jnp.linalg.norm(zi, axis=1)), 1.0,
                               rtol=1e-5)
    np.testing.assert_allclose(float(scale), 1.0 / 0.07, rtol=1e-5)


def test_clip_text_eot_pooling_ignores_padding(rng):
    """Causal attention + EOT pooling: trailing pad length must not change
    the pooled embedding (position 2 only attends to positions <= 2)."""
    model = TinyText()
    short = jnp.array([[5, 7, 9, 0, 0]], jnp.int32)
    long = jnp.array([[5, 7, 9, 0, 0, 0, 0, 0]], jnp.int32)
    vars_ = model.init(rng, jnp.zeros((1, 8), jnp.int32), train=False)
    e_short = model.apply(vars_, short, train=False)
    e_long = model.apply(vars_, long, train=False)
    np.testing.assert_allclose(np.asarray(e_short), np.asarray(e_long),
                               rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("train", [True, False])
def test_resnet_train_eval_modes(rng, train):
    model = TinyResNet()
    vars_ = model.init(rng, jnp.zeros((2, 32, 32, 3)), train=False)
    x = jax.random.uniform(rng, (4, 32, 32, 3))
    if train:
        h, updates = model.apply(vars_, x, train=True, mutable=["batch_stats"])
        assert "batch_stats" in updates
    else:
        h = model.apply(vars_, x, train=False)
    assert bool(jnp.all(jnp.isfinite(h)))


def test_s2d_stem_equivalence(rng):
    """space_to_depth stem computes EXACTLY the plain 7x7/s2 stem's map.

    Same parameter tree (7,7,C,width kernel under stem_conv), same
    function: init the plain-stem model, apply both stems with those
    weights on the same input, compare features. fp32 end to end so the
    only tolerance needed is reduction-order noise.
    """
    plain = ResNet(stage_sizes=(1,), stem="conv", dtype=jnp.float32)
    s2d = ResNet(stage_sizes=(1,), stem="space_to_depth", dtype=jnp.float32)
    x = jax.random.normal(rng, (2, 32, 32, 3), jnp.float32)
    vars_ = plain.init(jax.random.PRNGKey(0), x, train=False)
    # identical param trees => the plain init applies to the s2d model
    assert (jax.tree.map(jnp.shape, vars_["params"]["stem_conv"])
            == jax.tree.map(jnp.shape,
                            s2d.init(jax.random.PRNGKey(0), x,
                                     train=False)["params"]["stem_conv"]))
    h_plain = plain.apply(vars_, x, train=False)
    h_s2d = s2d.apply(vars_, x, train=False)
    np.testing.assert_allclose(np.asarray(h_plain), np.asarray(h_s2d),
                               rtol=1e-5, atol=1e-5)


def test_s2d_stem_odd_size_rejected(rng):
    s2d = ResNet(stage_sizes=(1,), stem="space_to_depth", dtype=jnp.float32)
    with pytest.raises(ValueError, match="even"):
        s2d.init(rng, jnp.zeros((1, 31, 31, 3)), train=False)


def test_vit_flash_attention_weight_compatible(rng):
    """attention_impl='flash' (the ViT MFU lever, models/vit.py): same
    param tree as the XLA path — the flash module claims the name and
    projection layout flax gives nn.MultiHeadDotProductAttention — and
    the same numbers on the same weights (flash resolves to the exact
    oracle off-TPU, the fused kernel on-chip). Also: gradients flow."""
    from ntxent_tpu.models import VisionTransformer

    kw = dict(hidden_dim=32, depth=2, num_heads=4, mlp_dim=64,
              patch_size=8, dtype=jnp.float32)
    x = jax.random.uniform(rng, (2, 16, 16, 3))
    m_xla = VisionTransformer(**kw)
    m_flash = VisionTransformer(attention_impl="flash", **kw)

    v = m_xla.init(jax.random.PRNGKey(1), x, train=False)
    assert jax.tree_util.tree_structure(v) == jax.tree_util.tree_structure(
        m_flash.init(jax.random.PRNGKey(1), x, train=False))

    y_xla = m_xla.apply(v, x, train=False)
    y_flash = m_flash.apply(v, x, train=False)  # same weights
    np.testing.assert_allclose(np.asarray(y_flash), np.asarray(y_xla),
                               rtol=2e-5, atol=2e-5)

    g = jax.grad(lambda p: jnp.sum(m_flash.apply({"params": p}, x,
                                                 train=False) ** 2))(
        v["params"])
    assert all(bool(jnp.all(jnp.isfinite(leaf)))
               for leaf in jax.tree.leaves(g))


def test_vit_flash_attention_rejects_unknown_impl(rng):
    from ntxent_tpu.models import VisionTransformer

    model = VisionTransformer(hidden_dim=32, depth=1, num_heads=2,
                              mlp_dim=64, patch_size=8,
                              attention_impl="nope")
    with pytest.raises(ValueError, match="unknown attention_impl"):
        model.init(rng, jnp.zeros((1, 16, 16, 3)), train=False)
