"""GPipe pipeline parallelism vs the sequential oracle on the CPU mesh.

Beyond-reference subsystem (SURVEY.md §2.2 marks PP N/A for the reference):
the pipelined forward must equal applying the stages in sequence, and the
AD-derived backward pipeline must equal the sequential gradients — weights
and activations alike. Shapes are tiny; the schedule logic, ppermute hops,
and psum replication are what is under test.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ntxent_tpu.parallel import create_mesh
from ntxent_tpu.parallel.pp import (
    make_gpipe,
    pipeline_stage_params,
    stack_stage_params,
)

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs an 8-device mesh")

S, M, B, D = 4, 4, 8, 16


@pytest.fixture(scope="module")
def mesh():
    return create_mesh(devices=jax.devices()[:S], axis_names=("stage",))


def _dense_stage(p, x):
    return jax.nn.relu(x @ p["w"] + p["b"])


def _make_stages(key, n=S, d=D):
    ps = []
    for i in range(n):
        k = jax.random.fold_in(key, i)
        ps.append({
            "w": jax.random.normal(k, (d, d)) / np.sqrt(d),
            "b": jnp.zeros((d,)),
        })
    return ps


def _sequential(params_list, x):
    for p in params_list:
        x = _dense_stage(p, x)
    return x


def test_forward_matches_sequential(mesh, rng):
    params_list = _make_stages(rng)
    x = jax.random.normal(jax.random.fold_in(rng, 99), (B, D))
    want = _sequential(params_list, x)
    pipe = make_gpipe(_dense_stage, mesh, num_microbatches=M)
    got = jax.jit(pipe)(stack_stage_params(params_list), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("remat", [False, True])
def test_grads_match_sequential(mesh, rng, remat):
    params_list = _make_stages(rng)
    stacked = stack_stage_params(params_list)
    x = jax.random.normal(jax.random.fold_in(rng, 7), (B, D))

    def loss_seq(ps, x):
        return jnp.sum(_sequential(ps, x) ** 2)

    pipe = make_gpipe(_dense_stage, mesh, num_microbatches=M, remat=remat)

    def loss_pipe(stacked, x):
        return jnp.sum(pipe(stacked, x) ** 2)

    want_p, want_x = jax.grad(loss_seq, argnums=(0, 1))(params_list, x)
    got_p, got_x = jax.jit(jax.grad(loss_pipe, argnums=(0, 1)))(stacked, x)
    np.testing.assert_allclose(np.asarray(got_x), np.asarray(want_x),
                               rtol=1e-4, atol=1e-5)
    want_stacked = stack_stage_params(want_p)
    for a, b in zip(jax.tree.leaves(got_p), jax.tree.leaves(want_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


def test_microbatch_count_one_and_uneven_batch(mesh, rng):
    params_list = _make_stages(rng)
    x = jax.random.normal(rng, (B, D))
    pipe1 = make_gpipe(_dense_stage, mesh, num_microbatches=1)
    got = jax.jit(pipe1)(stack_stage_params(params_list), x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params_list, x)),
                               rtol=1e-5, atol=1e-5)
    bad = make_gpipe(_dense_stage, mesh, num_microbatches=3)
    with pytest.raises(ValueError, match="microbatch"):
        jax.jit(bad)(stack_stage_params(params_list), x)


def test_dp_pp_composed(rng):
    """2-D (data, stage) mesh: batch stays data-sharded through the pipe."""
    mesh2 = create_mesh(shape=(2, S), axis_names=("data", "stage"))
    params_list = _make_stages(rng)
    x = jax.random.normal(jax.random.fold_in(rng, 3), (B, D))
    pipe = make_gpipe(_dense_stage, mesh2, num_microbatches=2,
                      data_axis="data")
    got = jax.jit(pipe)(stack_stage_params(params_list), x)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_sequential(params_list, x)),
                               rtol=1e-5, atol=1e-5)


def test_transformer_blocks_pipelined(mesh, rng):
    """Real EncoderBlock stages (2 blocks/stage via scan) == sequential."""
    from ntxent_tpu.models.vit import EncoderBlock

    blk = EncoderBlock(num_heads=2, mlp_dim=32, dtype=jnp.float32)
    x = jax.random.normal(rng, (4, 6, D))
    blocks = []
    for i in range(2 * S):
        blocks.append(blk.init(jax.random.fold_in(rng, i), x)["params"])

    want = x
    for p in blocks:
        want = blk.apply({"params": p}, want)

    # Stage-major stacking: (S, blocks_per_stage, ...) leaves.
    stages = [jax.tree.map(lambda *a: jnp.stack(a, 0),
                           *blocks[2 * s:2 * s + 2]) for s in range(S)]

    def stage_fn(stage_params, acts):
        def one(a, p):
            return blk.apply({"params": p}, a), None
        out, _ = jax.lax.scan(one, acts, stage_params)
        return out

    pipe = make_gpipe(stage_fn, mesh, num_microbatches=2)
    got = jax.jit(pipe)(stack_stage_params(stages), x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-4)


def test_pipeline_stage_params_split():
    p = {f"block_{i}": {"w": jnp.full((3,), float(i))} for i in range(6)}
    p["final_ln"] = {"scale": jnp.ones((3,))}
    stacked, rest = pipeline_stage_params(p, num_stages=3)
    assert stacked["w"].shape == (3, 2, 3)
    np.testing.assert_allclose(np.asarray(stacked["w"][1, 0]), 2.0)
    np.testing.assert_allclose(np.asarray(stacked["w"][2, 1]), 5.0)
    assert list(rest) == ["final_ln"]
    with pytest.raises(ValueError, match="split"):
        pipeline_stage_params(p, num_stages=4)
    with pytest.raises(ValueError, match="block"):
        pipeline_stage_params({"x": 1}, num_stages=1)


class TestPipelinedLongContext:
    """make_pipelined_apply: the real tower under GPipe == plain forward."""

    @pytest.fixture(scope="class")
    def setup(self, rng=jax.random.PRNGKey(42)):
        from ntxent_tpu.models import LongContextTransformer
        from ntxent_tpu.parallel.ring_attention import attention_oracle

        model = LongContextTransformer(
            vocab_size=64, hidden_dim=16, depth=4, num_heads=2, mlp_dim=32,
            max_len=32, dtype=jnp.float32, attention_fn=attention_oracle)
        tokens = jax.random.randint(rng, (4, 8), 0, 64)
        variables = model.init(rng, tokens)
        return model, variables, tokens

    def test_forward_matches_plain(self, setup):
        from ntxent_tpu.models import make_pipelined_apply
        from ntxent_tpu.parallel import create_mesh

        model, variables, tokens = setup
        mesh = create_mesh(devices=jax.devices()[:4],
                           axis_names=("stage",))
        pipe = make_pipelined_apply(model, mesh, num_microbatches=2)
        want = model.apply(variables, tokens)
        got = jax.jit(pipe)(variables, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)

    def test_grads_match_plain(self, setup):
        from ntxent_tpu.models import make_pipelined_apply
        from ntxent_tpu.parallel import create_mesh

        model, variables, tokens = setup
        mesh = create_mesh(devices=jax.devices()[:4],
                           axis_names=("stage",))
        pipe = make_pipelined_apply(model, mesh, num_microbatches=4,
                                    remat=True)
        want = jax.grad(
            lambda v: jnp.sum(model.apply(v, tokens) ** 2))(variables)
        got = jax.jit(jax.grad(
            lambda v: jnp.sum(pipe(v, tokens) ** 2)))(variables)
        # atol 5e-5: the pipelined backward reassociates fp32 sums
        # (psum over stages + scan order), a few-ulp difference.
        for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(want)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=5e-5)

    def test_depth_must_split(self, setup):
        from ntxent_tpu.models import make_pipelined_apply
        from ntxent_tpu.parallel import create_mesh

        model, _, _ = setup
        mesh3 = create_mesh(devices=jax.devices()[:3],
                            axis_names=("stage",))
        with pytest.raises(ValueError, match="split"):
            make_pipelined_apply(model, mesh3, num_microbatches=2)

    def test_one_train_step_improves_loss(self, setup):
        """A pipelined contrastive train step: grads flow end to end."""
        import optax

        from ntxent_tpu.models import make_pipelined_apply
        from ntxent_tpu.ops.oracle import ntxent_loss
        from ntxent_tpu.parallel import create_mesh

        model, variables, tokens = setup
        mesh = create_mesh(devices=jax.devices()[:4],
                           axis_names=("stage",))
        pipe = make_pipelined_apply(model, mesh, num_microbatches=2)
        # lr 0.02, not 0.1: this tiny contrastive surface is steep enough
        # that sgd(0.1) overshoots past the minimum (loss RISES 0.38 ->
        # 0.90 even for the plain un-pipelined model, jax-version-
        # dependent ulps deciding which side of the cliff the step lands
        # on). The property under test is grads-flow-end-to-end, so the
        # step must be small enough that a correct descent direction
        # provably decreases the loss.
        tx = optax.sgd(0.02)

        def loss_fn(v, toks):
            z = jnp.mean(pipe(v, toks), axis=1)  # (B, hidden) pooled
            return ntxent_loss(jnp.concatenate([z, z + 0.01]), 0.5)

        @jax.jit
        def step(v, opt_state, toks):
            loss, g = jax.value_and_grad(loss_fn)(v, toks)
            updates, opt_state = tx.update(g, opt_state)
            return optax.apply_updates(v, updates), opt_state, loss

        opt_state = tx.init(variables)
        v1, opt_state, l0 = step(variables, opt_state, tokens)
        _, _, l1 = step(v1, opt_state, tokens)
        assert np.isfinite(float(l0)) and np.isfinite(float(l1))
        assert float(l1) < float(l0)
