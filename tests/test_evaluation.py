"""SSL evaluation protocol: linear probe and kNN on frozen features."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from ntxent_tpu.training.evaluation import (
    extract_features,
    knn_accuracy,
    linear_probe,
)


def separable_features(center_key, draw_key, n_per_class=64, classes=4,
                       dim=16, spread=0.3):
    """Gaussian blobs: linearly separable by construction.

    ``center_key`` fixes the class centers; ``draw_key`` varies the samples —
    so train and test sets share geometry but not points.
    """
    centers = jax.random.normal(center_key, (classes, dim)) * 2.0
    draw_keys = jax.random.split(draw_key, classes)
    feats, labels = [], []
    for c in range(classes):
        f = centers[c] + spread * jax.random.normal(draw_keys[c],
                                                    (n_per_class, dim))
        feats.append(f)
        labels.append(jnp.full((n_per_class,), c, jnp.int32))
    return jnp.concatenate(feats), jnp.concatenate(labels)


@pytest.fixture()
def blobs(rng):
    kc, ktr, kte, kp = jax.random.split(rng, 4)
    xtr, ytr = separable_features(kc, ktr)
    xte, yte = separable_features(kc, kte)  # same centers, disjoint draws
    assert not np.allclose(np.asarray(xtr), np.asarray(xte))
    perm = jax.random.permutation(kp, xtr.shape[0])
    return xtr[perm], ytr[perm], xte, yte


def test_linear_probe_learns_separable(blobs):
    xtr, ytr, xte, yte = blobs
    res = linear_probe(xtr, ytr, xte, yte, num_classes=4, steps=300)
    assert res["train_accuracy"] > 0.95
    assert res["test_accuracy"] > 0.9
    assert np.isfinite(res["final_loss"])


def test_knn_accuracy_separable(blobs):
    xtr, ytr, xte, yte = blobs
    acc = knn_accuracy(xtr, ytr, xte, yte, k=10)
    assert acc > 0.9


def test_knn_chance_on_random_labels(rng):
    k1, k2, k3 = jax.random.split(rng, 3)
    xtr = jax.random.normal(k1, (128, 16))
    ytr = jax.random.randint(k2, (128,), 0, 4)
    xte = jax.random.normal(k3, (64, 16))
    yte = jax.random.randint(jax.random.fold_in(k3, 1), (64,), 0, 4)
    acc = knn_accuracy(xtr, ytr, xte, yte, k=10)
    assert acc < 0.6  # near chance (0.25), certainly far from separable


def test_extract_features_batched_matches_direct(rng):
    """Padding of the tail partial batch must not change the features."""
    import flax.linen as nn

    class Enc(nn.Module):
        @nn.compact
        def __call__(self, x):
            return nn.Dense(8)(x.reshape(x.shape[0], -1))

    model = Enc()
    images = jax.random.uniform(rng, (70, 8, 8, 3))  # 70 % 32 != 0
    variables = model.init(jax.random.PRNGKey(0), images[:1])
    apply = lambda x: model.apply(variables, x)  # noqa: E731
    feats = extract_features(apply, images, batch_size=32)
    direct = apply(images)
    assert feats.shape == (70, 8)
    np.testing.assert_allclose(np.asarray(feats), np.asarray(direct),
                               rtol=1e-5, atol=1e-6)


def test_linear_probe_end_to_end_with_encoder(rng):
    """Probe through a real (untrained) tiny encoder's features."""
    import functools as ft

    from ntxent_tpu.models import ResNet, SimCLRModel

    enc = ft.partial(ResNet, stage_sizes=(1, 1), small_images=True,
                     dtype=jnp.float32)
    model = SimCLRModel(encoder=enc, proj_hidden_dim=32, proj_dim=16,
                        dtype=jnp.float32)
    variables = model.init(rng, jnp.zeros((1, 32, 32, 3)), train=False)
    images = jax.random.uniform(rng, (48, 32, 32, 3))
    labels = jnp.arange(48) % 3

    feats = extract_features(
        lambda x: model.apply(variables, x, train=False, method="features"),
        images, batch_size=16)
    assert feats.ndim == 2 and feats.shape[0] == 48
    res = linear_probe(feats, labels, feats, labels, num_classes=3, steps=50)
    assert np.isfinite(res["final_loss"])


@pytest.mark.slow
def test_finetune_learns_separable_classes(rng):
    """End-to-end fine-tuning (the SimCLR paper's third protocol): the
    whole encoder + fresh head trains on a linearly-separable toy set and
    must beat chance decisively; BatchNorm stats update through the scan."""
    import functools as ft

    from ntxent_tpu.models import ResNet, SimCLRModel
    from ntxent_tpu.training import finetune

    enc = ft.partial(ResNet, stage_sizes=(1,), small_images=True,
                     dtype=jnp.float32)
    model = SimCLRModel(encoder=enc, proj_hidden_dim=16, proj_dim=8,
                        dtype=jnp.float32)
    variables = model.init(rng, jnp.zeros((1, 16, 16, 3)), train=False)

    # Two classes distinguished by channel dominance — separable from raw
    # pixels, so a trainable encoder must pick it up quickly.
    k1, k2 = jax.random.split(rng)
    n = 64
    base = jax.random.uniform(k1, (n, 16, 16, 3)) * 0.2
    labels = jnp.arange(n) % 2
    mark = jnp.where(labels[:, None, None, None] == 1, 0.8, 0.0)
    images = base.at[:, :, :, 0].add(mark[..., 0])

    res = finetune(model, variables, images, labels, images, labels,
                   num_classes=2, steps=60, batch_size=32,
                   learning_rate=3e-3, key=k2)
    assert np.isfinite(res["final_loss"])
    assert res["train_accuracy"] > 0.9, res
    assert res["test_accuracy"] > 0.9, res
