"""Crash-safe checkpointing subsystem (ISSUE 5).

The native checkpoint path's durability invariants, asserted in-process
on tiny CPU states: atomic writes leave no debris on failure, the
retention policy never collects the only restorable state, the mirror
serves restores when the primary is corrupt or missing, the async writer
keeps the skip-a-checkpoint contract, and the emergency path writes
synchronously. ``scripts/crash_audit.sh`` proves the same properties
against real SIGKILLs; these tests keep each mechanism green in tier-1.
"""

from __future__ import annotations

import errno
import functools
import json

import jax
import numpy as np
import pytest

from ntxent_tpu.models import ResNet, SimCLRModel
from ntxent_tpu.resilience import FaultInjector, FaultPlan
from ntxent_tpu.resilience.crashsim import (
    checkpoint_fingerprint,
    scan_checkpoint_dir,
)
from ntxent_tpu.training import TrainerConfig, create_train_state
from ntxent_tpu.training.checkpoint import (
    AsyncCheckpointer,
    CheckpointManager,
    RetentionPolicy,
    snapshot_state,
)

pytestmark = pytest.mark.crashsafe

TinyEnc = functools.partial(ResNet, stage_sizes=(1,), small_images=True)


@pytest.fixture(scope="module", autouse=True)
def _no_persistent_compilation_cache():
    """Run this file against cold compiles only.

    With the warm persistent cache, one of this file's tiny programs
    dies with heap corruption ("malloc(): invalid next size") when its
    serialized XLA:CPU executable RELOADS in a later process — the
    reload-abort hazard tests/conftest.py documents (same class as
    test_fsdp's no_persistent_compilation_cache fixture; the crash audit
    reproduced it independently through the CLI). Everything here is a
    sub-second compile, so opting the whole file out removes the failure
    mode for ~1 s.

    NOTE this fixture cannot protect against the IN-PROCESS jit cache:
    a program another test file already compiled (possibly reloading a
    poisoned persistent-cache entry) is reused without consulting this
    config. That is why every model/step in this file uses shapes no
    other file compiles (proj 24/12, batch 12) — shared shapes here
    reproduced a deterministic abort inside the step whenever
    tests/test_api.py ran first against a warm cache.
    """
    import jax

    old = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", None)
    yield
    jax.config.update("jax_compilation_cache_dir", old)


def _tiny_state(seed=0, steps=10):
    # proj 24/12 (not the suite-wide 16/8): see the cache fixture's NOTE.
    model = SimCLRModel(encoder=TinyEnc, proj_hidden_dim=24, proj_dim=12)
    cfg = TrainerConfig(batch_size=12, total_steps=steps, warmup_steps=1)
    return create_train_state(model, jax.random.PRNGKey(seed),
                              (1, 8, 8, 3), cfg)


@pytest.fixture(scope="module")
def tiny_state():
    return _tiny_state()


def _params_equal(a, b):
    for la, lb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(la), np.asarray(lb))


# ---------------------------------------------------------------------------
# RetentionPolicy
# ---------------------------------------------------------------------------

def test_retention_keep_last():
    policy = RetentionPolicy(keep_last=2)
    assert policy.keep([1, 2, 3, 4, 5], lambda s: True) == {4, 5}


def test_retention_keep_every_boundary():
    """keep-every-n keeps exactly the steps divisible by n — including
    when the anchor IS the newest or oldest step — alongside keep-last."""
    policy = RetentionPolicy(keep_last=1, keep_every=4)
    assert policy.keep(list(range(1, 10)), lambda s: True) == {4, 8, 9}
    # Anchor == newest step: no duplicate-keep confusion.
    assert policy.keep([2, 4, 6, 8], lambda s: True) == {4, 8}
    # All steps below the first anchor: only keep-last applies.
    assert policy.keep([1, 2, 3], lambda s: True) == {3}


def test_retention_never_drops_newest_valid():
    """Newer-but-corrupt steps must not starve the only restorable one."""
    policy = RetentionPolicy(keep_last=2)
    valid = {3}.__contains__
    assert policy.keep([1, 2, 3, 4, 5], valid) == {3, 4, 5}


def test_retention_disabled_keeps_everything():
    policy = RetentionPolicy(keep_last=None)
    steps = list(range(1, 8))
    assert policy.keep(steps, lambda s: True) == set(steps)
    assert RetentionPolicy(keep_last=0).keep(steps, lambda s: True) \
        == set(steps)


def test_gc_applies_policy_and_prunes_manifests(tmp_path, tiny_state):
    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=2,
                            keep_every=4)
    for step in range(1, 7):
        assert mgr.save(step, tiny_state, force=True)
    assert mgr.all_steps() == [4, 5, 6]  # keep-last 2 + the step-4 anchor
    manifests = json.loads((tmp_path / "ckpt" / "manifests.json")
                           .read_text())
    assert sorted(manifests) == ["4", "5", "6"]
    mgr.close()


def test_gc_never_removes_only_valid_step(tmp_path, tiny_state):
    """keep_last=1 with the newest steps corrupted: GC must keep the
    older VALID step the restore fallback needs."""
    from ntxent_tpu.resilience import truncate_checkpoint_file

    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=2)
    assert mgr.save(2, tiny_state, force=True)
    assert mgr.save(4, tiny_state, force=True)
    assert mgr.all_steps() == [2, 4]
    assert truncate_checkpoint_file(tmp_path / "ckpt", step=4) is not None
    mgr.close()
    # A tighter policy arrives (e.g. a restarted run with keep_last=1):
    # its GC must still keep step 2 — the only VALID state left.
    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=1)
    deleted = mgr.gc()
    assert 2 not in deleted
    assert 2 in mgr.all_steps()  # newest VALID survived keep_last=1
    assert mgr.latest_valid_step() == 2
    restored = mgr.restore(_tiny_state(seed=9))
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


# ---------------------------------------------------------------------------
# Atomic writes + diskfull injection (satellites 1 & 2)
# ---------------------------------------------------------------------------

def test_faultplan_parses_kill_and_diskfull():
    plan = FaultPlan.parse("kill@4,diskfull@2,nan@3")
    assert plan.kill_batches == (4,)
    assert plan.diskfull_writes == (2,)
    assert not plan.empty()
    with pytest.raises(ValueError, match="valid actions.*killworker"):
        FaultPlan.parse("killl@4")


def test_diskfull_injection_keeps_skip_contract(tmp_path, tiny_state):
    """ENOSPC in the writer: save returns False, bumps the failure
    counter, leaves NO partial step and NO staging debris, and the next
    write (disk 'freed') succeeds."""
    from ntxent_tpu.obs.registry import default_registry

    injector = FaultInjector(FaultPlan.parse("diskfull@1"))
    mgr = CheckpointManager(tmp_path / "ckpt",
                            fault_hook=injector.on_checkpoint_write)
    failures = default_registry().counter("checkpoint_save_failures_total")
    before = failures.value
    assert mgr.save(1, tiny_state, force=True) is False
    assert injector.fired == ["diskfull@1"]
    assert failures.value == before + 1
    scan = scan_checkpoint_dir(tmp_path / "ckpt")
    assert scan == {"torn": [], "tmp": []}
    assert mgr.all_steps() == []
    # Write 2 is past the plan: the cadence recovers.
    assert mgr.save(2, tiny_state, force=True) is True
    assert mgr.verify(2)
    mgr.close()


def test_failed_write_leaves_no_debris_mid_file(tmp_path, tiny_state):
    """An OSError AFTER files are partially staged (not just at the
    hook) must clean its staging dir — a torn step is impossible."""
    calls = []

    def hook():
        calls.append(1)
        if len(calls) == 1:
            raise OSError(errno.ENOSPC, "no space")

    mgr = CheckpointManager(tmp_path / "ckpt", fault_hook=hook)
    assert mgr.save(3, tiny_state, force=True) is False
    assert scan_checkpoint_dir(tmp_path / "ckpt") == {"torn": [],
                                                      "tmp": []}
    mgr.close()


def test_first_save_of_fresh_directory_always_lands(tmp_path, tiny_state):
    mgr = CheckpointManager(tmp_path / "ckpt", save_interval_steps=100)
    assert mgr.should_save(1)
    assert mgr.save(1, tiny_state)
    assert not mgr.should_save(2)  # cadence owns it from here
    assert mgr.save(2, tiny_state) is False
    assert mgr.all_steps() == [1]
    mgr.close()
    # A resumed manager over a non-empty dir keeps cadence-only.
    mgr2 = CheckpointManager(tmp_path / "ckpt", save_interval_steps=100)
    assert not mgr2.should_save(3)
    mgr2.close()


def test_init_purges_abandoned_staging_dirs(tmp_path, tiny_state):
    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    debris = ckpt / ".tmp-5-deadbeef"
    debris.mkdir()
    (debris / "state.msgpack").write_bytes(b"partial")
    mgr = CheckpointManager(ckpt)
    assert not debris.exists()
    assert mgr.all_steps() == []  # debris never enumerates as a step
    mgr.close()


# ---------------------------------------------------------------------------
# Mirror replication
# ---------------------------------------------------------------------------

def test_mirror_replicates_and_serves_corrupt_primary(tmp_path,
                                                      tiny_state):
    from ntxent_tpu.resilience import truncate_checkpoint_file

    mgr = CheckpointManager(tmp_path / "ckpt",
                            mirror_dir=tmp_path / "mirror")
    assert mgr.save(2, tiny_state, force=True,
                    data_state={"epoch": 0, "offset": 2, "seed": 5})
    assert (tmp_path / "mirror" / "2" / "state.msgpack").exists()
    assert mgr.mirror_verify(2)

    assert truncate_checkpoint_file(tmp_path / "ckpt", step=2) is not None
    assert not mgr.verify(2)
    assert mgr.latest_valid_step() == 2  # the mirror copy still counts
    restored, data_state = mgr.restore_with_data_state(_tiny_state(seed=9))
    _params_equal(restored.params, tiny_state.params)
    assert data_state == {"epoch": 0, "offset": 2, "seed": 5}
    mgr.close()


def test_mirror_serves_when_primary_manifest_corrupt(tmp_path,
                                                     tiny_state):
    """Garbage manifests.json + a truncated primary payload: the primary
    can neither verify nor be trusted, and restore must fall through to
    the mirror copy."""
    from ntxent_tpu.resilience import truncate_checkpoint_file

    mgr = CheckpointManager(tmp_path / "ckpt",
                            mirror_dir=tmp_path / "mirror")
    assert mgr.save(3, tiny_state, force=True)
    (tmp_path / "ckpt" / "manifests.json").write_text("{not json")
    assert truncate_checkpoint_file(tmp_path / "ckpt", step=3) is not None
    # With the manifest gone the truncated primary would verify as
    # "unverifiable == valid" — the mirror's CRCs are what catch it.
    restored, _ = mgr.restore_with_data_state(_tiny_state(seed=9))
    # The restore must carry the TRUE bytes (mirror), not the torn ones:
    # a successful from_bytes over truncated msgpack would have raised.
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


def test_mirror_serves_when_primary_step_missing(tmp_path, tiny_state):
    import shutil

    mgr = CheckpointManager(tmp_path / "ckpt",
                            mirror_dir=tmp_path / "mirror")
    assert mgr.save(5, tiny_state, force=True)
    shutil.rmtree(tmp_path / "ckpt" / "5")
    assert mgr.latest_valid_step() == 5
    restored = mgr.restore(_tiny_state(seed=9))
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


# ---------------------------------------------------------------------------
# AsyncCheckpointer
# ---------------------------------------------------------------------------

def test_async_save_roundtrip(tmp_path, tiny_state):
    mgr = AsyncCheckpointer(CheckpointManager(tmp_path / "ckpt"))
    assert mgr.save(1, tiny_state, force=True)
    mgr.wait_until_finished()
    assert mgr.all_steps() == [1]
    assert mgr.verify(1)
    restored = mgr.restore(_tiny_state(seed=9))
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


def test_async_snapshot_is_immune_to_buffer_reuse(tmp_path, tiny_state):
    """The host snapshot must be a REAL copy: on CPU ``device_get``
    returns zero-copy views of the device buffers, and a donated train
    step overwriting them under the background writer serialized a LATER
    step's params under this step's label (the crash audit caught it).
    """
    from flax import serialization

    snap = snapshot_state(tiny_state)
    views = jax.device_get(serialization.to_state_dict(tiny_state))
    for copied, view in zip(jax.tree.leaves(snap.state_dict),
                            jax.tree.leaves(views)):
        if isinstance(copied, np.ndarray) \
                and isinstance(view, np.ndarray) and copied.size:
            assert not np.shares_memory(copied, view), \
                "snapshot aliases the live device buffer"
    mgr = AsyncCheckpointer(CheckpointManager(tmp_path / "ckpt"))
    assert mgr.save(1, snap, force=True)
    mgr.wait_until_finished()
    restored = mgr.restore(_tiny_state(seed=9))
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


def test_async_writer_failure_keeps_contract(tmp_path, tiny_state):
    """A writer-thread OSError must not raise on the train loop; it
    lands in the failure counter + last_error and later saves recover."""
    from ntxent_tpu.obs.registry import default_registry

    injector = FaultInjector(FaultPlan.parse("diskfull@1"))
    mgr = AsyncCheckpointer(CheckpointManager(
        tmp_path / "ckpt", fault_hook=injector.on_checkpoint_write))
    failures = default_registry().counter("checkpoint_save_failures_total")
    before = failures.value
    assert mgr.save(1, tiny_state, force=True)  # accepted
    mgr.wait_until_finished()
    assert failures.value == before + 1
    assert mgr.last_error is not None
    assert mgr.all_steps() == []
    assert mgr.save(2, tiny_state, force=True)
    mgr.wait_until_finished()
    assert mgr.all_steps() == [2]
    mgr.close()


def test_emergency_save_is_synchronous(tmp_path, tiny_state):
    mgr = AsyncCheckpointer(CheckpointManager(tmp_path / "ckpt"))
    assert mgr.emergency_save(7, tiny_state,
                              data_state={"epoch": 1, "offset": 3,
                                          "seed": 0})
    # No wait_until_finished: the write must already be durable.
    assert (tmp_path / "ckpt" / "7" / "state.msgpack").exists()
    assert mgr.manager.verify(7)
    _, data_state = mgr.restore_with_data_state(_tiny_state(seed=9))
    assert data_state == {"epoch": 1, "offset": 3, "seed": 0}
    mgr.close()


def test_async_queue_depth_is_bounded(tmp_path, tiny_state,
                                      monkeypatch):
    """With a slow writer, a second save blocks until the first lands —
    the queue never grows past max_pending (the bounded-writer
    contract), and every accepted save is eventually durable."""
    monkeypatch.setenv("NTXENT_CKPT_SLOW_MS", "50")
    mgr = AsyncCheckpointer(CheckpointManager(tmp_path / "ckpt"),
                            max_pending=1)
    for step in (1, 2, 3):
        assert mgr.save(step, tiny_state, force=True)
        assert mgr._queue.qsize() <= 1
    mgr.wait_until_finished()
    assert mgr.all_steps() == [1, 2, 3]
    mgr.close()


def test_async_first_save_claim_leaves_no_phantom_error(tmp_path,
                                                        tiny_state,
                                                        monkeypatch):
    """Review regression: with a slow writer and a wide cadence, the
    empty-dir first-save rule must fire ONCE — a second accepted 'first
    save' would later be cadence-filtered in the writer and misread as a
    write failure (phantom last_error on a healthy run)."""
    monkeypatch.setenv("NTXENT_CKPT_SLOW_MS", "100")
    mgr = AsyncCheckpointer(CheckpointManager(tmp_path / "ckpt",
                                              save_interval_steps=100))
    assert mgr.save(1, tiny_state) is True  # first-save rule, claimed
    # Writer still sleeping on save 1: the probe must NOT re-fire.
    assert mgr.save(2, tiny_state) is False
    mgr.wait_until_finished()
    assert mgr.last_error is None
    assert mgr.all_steps() == [1]
    mgr.close()


def test_purge_keeps_live_writers_staging(tmp_path):
    """Staging dirs embed the writer PID: purge must remove a dead
    writer's debris but keep another LIVE process's in-flight save."""
    import subprocess

    ckpt = tmp_path / "ckpt"
    ckpt.mkdir()
    live = subprocess.Popen(["sleep", "60"])
    try:
        live_tmp = ckpt / f".tmp-5-{live.pid}-abcd1234"
        live_tmp.mkdir()
        dead = subprocess.Popen(["true"])
        dead.wait()
        dead_tmp = ckpt / f".tmp-6-{dead.pid}-abcd1234"
        dead_tmp.mkdir()
        legacy_tmp = ckpt / ".tmp-7-deadbeef"  # pre-PID naming
        legacy_tmp.mkdir()
        mgr = CheckpointManager(ckpt)
        assert live_tmp.exists(), "live writer's staging dir was purged"
        assert not dead_tmp.exists()
        assert not legacy_tmp.exists()
        mgr.close()
    finally:
        live.kill()
        live.wait()


def test_explicit_step_restore_reads_mirror_when_primary_gone(
        tmp_path, tiny_state):
    """An explicitly requested step whose primary dir is gone and whose
    mirror copy fails verification is still restored from the mirror —
    the caller asked for that exact step."""
    import shutil

    mgr = CheckpointManager(tmp_path / "ckpt",
                            mirror_dir=tmp_path / "mirror")
    assert mgr.save(4, tiny_state, force=True)
    shutil.rmtree(tmp_path / "ckpt" / "4")
    # Poison the mirror's manifest entry so mirror_verify fails while
    # the copied bytes stay restorable.
    manifests = json.loads((tmp_path / "mirror" / "manifests.json")
                           .read_text())
    manifests["4"]["files"]["state.msgpack"][1] ^= 0xFFFF
    (tmp_path / "mirror" / "manifests.json").write_text(
        json.dumps(manifests))
    assert not mgr.verify(4) and not mgr.mirror_verify(4)
    restored = mgr.restore(_tiny_state(seed=9), step=4)
    _params_equal(restored.params, tiny_state.params)
    mgr.close()


def test_restore_never_deletes_unreadable_foreign_steps(tmp_path,
                                                        tiny_state):
    """Review regression: a CRC-clean step that cannot be deserialized
    (e.g. a directory written by the old orbax backend) must not be
    deleted by the restore fallback — destroying every older-format
    checkpoint one candidate at a time before raising."""
    ckpt = tmp_path / "ckpt"
    (ckpt / "3").mkdir(parents=True)
    (ckpt / "3" / "checkpoint").write_bytes(b"some-other-format bytes")
    mgr = CheckpointManager(ckpt)
    mgr._record_manifest(3)
    assert mgr.verify(3)
    with pytest.raises(Exception, match="cannot be deserialized"):
        mgr.restore_with_data_state(_tiny_state(seed=9))
    assert (ckpt / "3" / "checkpoint").exists(), \
        "foreign-format checkpoint was destroyed"
    mgr.close()


# ---------------------------------------------------------------------------
# fit() integration: async + emergency on preemption
# ---------------------------------------------------------------------------

def _fit_setup(steps=6):
    from ntxent_tpu.training import make_train_step

    state = _tiny_state(steps=steps)
    step = make_train_step(0.1, use_fused=False)

    def gen():
        key = jax.random.PRNGKey(7)
        i = 0
        while True:
            k1, k2 = jax.random.split(jax.random.fold_in(key, i))
            yield (jax.random.uniform(k1, (12, 8, 8, 3)),
                   jax.random.uniform(k2, (12, 8, 8, 3)))
            i += 1

    return state, step, gen()


def test_fit_async_checkpointing_saves_and_resumes(tmp_path):
    from ntxent_tpu.training import fit

    state, step, it = _fit_setup()
    state, _ = fit(state, it, step, num_steps=4,
                   checkpoint_dir=str(tmp_path), checkpoint_every=2,
                   log_every=100, flops_per_step=None,
                   async_checkpointing=True)
    assert int(state.step) == 4
    mgr = CheckpointManager(tmp_path)
    assert mgr.latest_step() == 4
    assert mgr.verify(4)
    mgr.close()
    # Resume: the same dir continues to the full step count.
    state2, step2, it2 = _fit_setup()
    state2, _ = fit(state2, it2, step2, num_steps=6,
                    checkpoint_dir=str(tmp_path), checkpoint_every=2,
                    log_every=100, flops_per_step=None,
                    async_checkpointing=True)
    assert int(state2.step) == 6


def test_fit_preemption_takes_emergency_path(tmp_path, monkeypatch):
    """A stop_fn trip under async checkpointing routes the final save
    through emergency_save (synchronous, emergency-tagged event)."""
    from ntxent_tpu.training import fit
    from ntxent_tpu.training.checkpoint import AsyncCheckpointer as AC

    calls = []
    real = AC.emergency_save

    def spying(self, step, state, data_state=None):
        calls.append(int(step))
        return real(self, step, state, data_state=data_state)

    monkeypatch.setattr(AC, "emergency_save", spying)
    state, step, it = _fit_setup()
    stops = {"n": 0}

    def stop():
        stops["n"] += 1
        return stops["n"] > 3  # trip after step 3

    state, _ = fit(state, it, step, num_steps=6,
                   checkpoint_dir=str(tmp_path), checkpoint_every=100,
                   log_every=100, flops_per_step=None, stop_fn=stop,
                   async_checkpointing=True)
    assert calls, "emergency_save was not used on the preemption path"
    mgr = CheckpointManager(tmp_path)
    assert mgr.latest_step() == int(state.step)
    mgr.close()


# ---------------------------------------------------------------------------
# crashsim helpers
# ---------------------------------------------------------------------------

def test_scan_detects_torn_step_and_tmp_debris(tmp_path, tiny_state):
    from ntxent_tpu.resilience import truncate_checkpoint_file

    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(1, tiny_state, force=True)
    assert mgr.save(2, tiny_state, force=True)
    mgr.close()
    assert scan_checkpoint_dir(tmp_path / "ckpt") == {"torn": [],
                                                      "tmp": []}
    assert truncate_checkpoint_file(tmp_path / "ckpt", step=2) is not None
    (tmp_path / "ckpt" / ".tmp-3-feedface").mkdir()
    scan = scan_checkpoint_dir(tmp_path / "ckpt")
    assert scan["torn"] and "2" in scan["torn"][0]
    assert scan["tmp"] == [".tmp-3-feedface"]


def test_fingerprint_tracks_payload_bytes(tmp_path, tiny_state):
    mgr = CheckpointManager(tmp_path / "a")
    mgr2 = CheckpointManager(tmp_path / "b")
    assert mgr.save(1, tiny_state, force=True,
                    data_state={"epoch": 0, "offset": 1, "seed": 0})
    assert mgr2.save(1, tiny_state, force=True,
                     data_state={"epoch": 0, "offset": 1, "seed": 0})
    fp_a = checkpoint_fingerprint(tmp_path / "a", 1)
    fp_b = checkpoint_fingerprint(tmp_path / "b", 1)
    assert fp_a == fp_b  # deterministic serialization, CRC for CRC
    assert mgr2.save(1, _tiny_state(seed=9), force=True)
    assert checkpoint_fingerprint(tmp_path / "b", 1) != fp_a
    with pytest.raises(Exception, match="no checkpoint"):
        checkpoint_fingerprint(tmp_path / "a", 99)
    mgr.close()
    mgr2.close()
