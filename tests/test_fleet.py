"""Serving-fleet tier: router failover, cache bounds, canary rollout.

The router edge cases ISSUE 8 pins are all here: all-workers-down is an
immediate 503 (never a hang), an exhausted retry budget surfaces the
WORKER's status code, a cache TTL expiry re-dispatches to a worker, and
a canary error-rate breach rolls the fleet back to old-checkpoint
routing. Router tests run against fake HTTP workers (no JAX in the
loop — behavior and bookkeeping are the subjects); engine-swap and
readiness tests run the real ``InferenceEngine``/``EmbeddingServer``
over a linear model; supervision tests drive ``ServingFleet.tick()``
against a real (but JAX-free) worker subprocess.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import textwrap
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import jax.numpy as jnp

from ntxent_tpu.resilience import FaultInjector, FaultPlan, RetryPolicy
from ntxent_tpu.serving import (
    EmbeddingCache,
    EmbeddingServer,
    FleetRouter,
    InferenceEngine,
    ServingFleet,
    WorkerPool,
)

pytestmark = pytest.mark.fleet


# ---------------------------------------------------------------------------
# fakes / helpers


class FakeClock:
    def __init__(self, t: float = 1000.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def advance(self, dt: float) -> None:
        self.t += dt


class FakeWorker:
    """One scriptable worker replica: answers /embed per ``mode`` and
    records everything the router sends it."""

    def __init__(self, dim: int = 4, step: int | None = 1):
        self.dim = dim
        self.step = step
        # When set, every reply carries X-Checkpoint-Step (the reply-
        # time label a real EmbeddingServer stamps) — lets tests make
        # the served step DISAGREE with the pool's routing-table view.
        self.step_header: int | None = None
        # ok | err500 | busy429 | bad400 | garbage200 | scalar500 |
        # scalar429 (the scalar modes answer with valid-JSON NON-OBJECT
        # bodies — what a recycled port's foreign service might say).
        self.mode = "ok"
        # When set, /embed requests over this row count 413 — the real
        # server's --max-request-rows cap (cache warming must chunk
        # under it).
        self.max_rows: int | None = None
        self.embed_calls: list[int] = []   # row count per /embed
        self.rollbacks: list[dict] = []
        self.request_ids: list[str] = []
        # Called with the row count before each /embed reply — lets a
        # test interleave router-side events (e.g. a cache flush) with
        # an in-flight forward deterministically.
        self.on_embed = None
        self.rollback_delay_s = 0.0
        worker = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # noqa: N802
                pass

            def _reply_raw(self, code, body: bytes):
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if worker.step_header is not None:
                    self.send_header("X-Checkpoint-Step",
                                     str(worker.step_header))
                self.end_headers()
                self.wfile.write(body)

            def _reply(self, code, payload):
                self._reply_raw(code, json.dumps(payload).encode())

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                req = json.loads(body or b"{}")
                if self.path == "/rollback":
                    if worker.rollback_delay_s:
                        time.sleep(worker.rollback_delay_s)
                    worker.rollbacks.append(req)
                    self._reply(200, {"rolled_back": True})
                    return
                worker.request_ids.append(
                    self.headers.get("X-Request-Id"))
                rows = len(req.get("inputs", []))
                worker.embed_calls.append(rows)
                if worker.on_embed is not None:
                    worker.on_embed(rows)
                if worker.max_rows is not None \
                        and rows > worker.max_rows:
                    self._reply(413, {"error": f"{rows} rows exceed "
                                               f"cap {worker.max_rows}"})
                    return
                if worker.mode == "err500":
                    self._reply(500, {"error": "injected worker error"})
                elif worker.mode == "busy429":
                    self._reply(429, {"error": "queue full",
                                      "retry_after_s": 0.25})
                elif worker.mode == "bad400":
                    self._reply(400, {"error": "injected bad request"})
                elif worker.mode == "garbage200":
                    self._reply_raw(200, b"not json {")
                elif worker.mode == "scalar500":
                    self._reply_raw(500, b'"busy"')
                elif worker.mode == "scalar429":
                    self._reply_raw(429, b'"try later"')
                elif worker.mode == "deadline504":
                    self._reply(504, {"error": "deadline exceeded "
                                               "in queue"})
                else:
                    emb = [[float(worker.step or 0)] * worker.dim
                           for _ in range(rows)]
                    self._reply(200, {"embeddings": emb,
                                      "dim": worker.dim, "rows": rows})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def _pool_with(workers: dict[str, FakeWorker], **kw) -> WorkerPool:
    pool = WorkerPool(**kw)
    for wid, w in workers.items():
        pool.upsert(wid, w.url)
        pool.set_health(wid, alive=True, ready=True,
                        checkpoint_step=w.step)
    return pool


def _post_router(router, payload, path="/embed"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{router.port}{path}",
        data=json.dumps(payload).encode(), method="POST")
    try:
        with urllib.request.urlopen(req, timeout=15) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _rows(n, value=0.5):
    return [[value, value] for _ in range(n)]


# ---------------------------------------------------------------------------
# embedding cache


class TestEmbeddingCache:
    def test_row_level_hits_and_misses_split_mixed_requests(self):
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60)
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        hits, misses = cache.lookup(x)
        assert hits == {} and misses == [0, 1, 2]
        cache.insert(x, np.ones((3, 4), np.float32))
        # A new request repeating rows 0 and 2 hits on exactly those.
        mixed = np.stack([x[0], np.full(2, 9.0, np.float32), x[2]])
        hits, misses = cache.lookup(mixed)
        assert sorted(hits) == [0, 2] and misses == [1]
        np.testing.assert_array_equal(hits[0], np.ones(4))
        assert cache.hits == 2 and cache.misses == 4
        assert cache.hit_rate() == pytest.approx(2 / 6)

    def test_ttl_expiry_is_a_miss_and_evicts(self):
        clock = FakeClock()
        cache = EmbeddingCache(capacity_rows=8, ttl_s=10, clock=clock)
        x = np.ones((1, 2), np.float32)
        cache.insert(x, np.zeros((1, 4), np.float32))
        hits, misses = cache.lookup(x)
        assert misses == []
        clock.advance(10.001)
        hits, misses = cache.lookup(x)
        assert hits == {} and misses == [0]
        assert len(cache) == 0
        assert cache.snapshot()["evictions"] == {"ttl": 1}

    def test_lru_capacity_evicts_coldest_first(self):
        cache = EmbeddingCache(capacity_rows=2, ttl_s=60)
        rows = np.arange(6, dtype=np.float32).reshape(3, 2)
        cache.insert(rows[:2], np.zeros((2, 4), np.float32))
        # Touch row 0 so row 1 is the coldest when row 2 arrives.
        cache.lookup(rows[:1])
        cache.insert(rows[2:], np.zeros((1, 4), np.float32))
        hits, misses = cache.lookup(rows)
        assert sorted(hits) == [0, 2] and misses == [1]
        assert cache.snapshot()["evictions"] == {"lru": 1}

    def test_shape_and_dtype_guard_the_content_key(self):
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60)
        flat = np.zeros((1, 4), np.float32)
        cache.insert(flat, np.ones((1, 4), np.float32))
        # Same bytes, different trailing shape: must NOT alias.
        square = np.zeros((1, 2, 2), np.float32)
        hits, misses = cache.lookup(square)
        assert hits == {} and misses == [0]

    def test_insert_copies_rows_instead_of_pinning_the_batch(self):
        # Regression: caching a VIEW of the worker's response batch
        # keeps the whole (N, D) array alive per cached row — and a
        # later caller mutating its buffer would corrupt the cache.
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60)
        x = np.arange(4, dtype=np.float32).reshape(2, 2)
        batch = np.ones((2, 4), np.float32)
        cache.insert(x, batch)
        hits, _ = cache.lookup(x)
        assert not np.shares_memory(hits[0], batch)
        batch[:] = 99.0
        hits, _ = cache.lookup(x)
        np.testing.assert_array_equal(hits[1], np.ones(4))

    def test_clear_reports_reason_and_counts(self):
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60)
        cache.insert(np.arange(4, dtype=np.float32).reshape(2, 2),
                     np.ones((2, 4), np.float32))
        assert cache.clear(reason="promote") == 2
        assert len(cache) == 0
        assert cache.snapshot()["evictions"] == {"promote": 2}

    def test_clear_bumps_the_generation(self):
        # The generation is how a reader detects a model change that
        # landed between its lookup and its merge (clear() is only ever
        # called for model changes: adopt/promote/rollback).
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60)
        g0 = cache.generation
        cache.insert(np.zeros((1, 2), np.float32),
                     np.ones((1, 4), np.float32))
        assert cache.generation == g0  # inserts don't bump
        cache.clear(reason="promote")
        assert cache.generation == g0 + 1


# ---------------------------------------------------------------------------
# worker pool (selection + canary state machine, no sockets)


class TestWorkerPool:
    def test_first_observed_step_becomes_trusted(self):
        pool = WorkerPool()
        pool.upsert("w0", "http://127.0.0.1:1")
        assert pool.trusted_step is None
        pool.set_health("w0", alive=True, ready=True, checkpoint_step=5)
        assert pool.trusted_step == 5

    def test_pick_is_least_in_flight_and_honors_exclude(self):
        pool = WorkerPool()
        for wid in ("w0", "w1"):
            pool.upsert(wid, f"http://127.0.0.1:{1 + int(wid[1])}")
            pool.set_health(wid, alive=True, ready=True,
                            checkpoint_step=1)
        first = pool.pick()
        assert first.worker_id == "w0"  # tie broken by id
        second = pool.pick()            # w0 now has 1 in flight
        assert second.worker_id == "w1"
        assert pool.pick(exclude={"w0", "w1"}) is None
        pool.done("w0")
        pool.done("w1")

    def test_no_ready_worker_picks_none(self):
        pool = WorkerPool()
        pool.upsert("w0", "http://127.0.0.1:1")
        pool.set_health("w0", alive=True, ready=False)
        assert pool.pick() is None

    def test_canary_fraction_routes_one_in_period(self):
        pool = WorkerPool(canary_fraction=0.25)
        for wid, step in (("w0", 1), ("w1", 1), ("w2", 2)):
            pool.upsert(wid, "http://127.0.0.1:9")
            pool.set_health(wid, alive=True, ready=True,
                            checkpoint_step=step)
        assert pool.trusted_step == 1
        picks = []
        for _ in range(20):
            entry = pool.pick()
            picks.append(entry.worker_id)
            pool.done(entry.worker_id)
        assert picks.count("w2") == 5  # exactly 1 in 4
        assert pool.snapshot()["canary_step"] == 2

    def test_observe_promotes_on_clean_canary(self):
        pool = WorkerPool(canary_min_requests=4,
                          canary_max_error_rate=0.25)
        for wid, step in (("w0", 1), ("w1", 2)):
            pool.upsert(wid, "http://127.0.0.1:9")
            pool.set_health(wid, alive=True, ready=True,
                            checkpoint_step=step)
        entry = pool.pick()
        pool.done(entry.worker_id)  # arms the canary state
        decisions = [pool.observe("w1", 2, ok=True) for _ in range(4)]
        assert decisions[:3] == [None, None, None]
        assert decisions[3] == ("promote", 2)
        assert pool.trusted_step == 2

    def test_observe_rolls_back_on_error_rate_breach(self):
        pool = WorkerPool(canary_min_requests=4,
                          canary_max_error_rate=0.25)
        for wid, step in (("w0", 1), ("w1", 2)):
            pool.upsert(wid, "http://127.0.0.1:9")
            pool.set_health(wid, alive=True, ready=True,
                            checkpoint_step=step)
        entry = pool.pick()
        pool.done(entry.worker_id)
        for _ in range(3):
            assert pool.observe("w1", 2, ok=False) is None
        assert pool.observe("w1", 2, ok=True) == ("rollback", 2)
        assert pool.trusted_step == 1 and 2 in pool.bad_steps
        # A bad-step worker is never a canary again; with old workers
        # ready, routing is old-cohort-only.
        picks = {pool.pick().worker_id for _ in range(8)}
        assert picks == {"w0"}

    def test_healthy_probe_does_not_wipe_forward_failures(self):
        # Regression: the fleet tick probes (set_health) immediately
        # before its eject check — if a passing /readyz reset the
        # shared counter, router-reported forward failures could NEVER
        # reach the threshold and a worker 500ing every /embed while
        # answering probes would live forever.
        pool = WorkerPool()
        pool.upsert("w0", "http://127.0.0.1:9")
        pool.set_health("w0", alive=True, ready=True, checkpoint_step=1)
        pool.report_failure("w0", "http 500")          # forward kind
        pool.report_failure("w0", "http 500")
        pool.set_health("w0", alive=True, ready=True)  # healthy probe
        assert pool.workers()[0].consecutive_failures == 2
        # Only a successful FORWARD is evidence /embed works.
        pool.report_success("w0")
        assert pool.workers()[0].consecutive_failures == 0
        # A probe-originated streak IS closed by a passing probe.
        pool.report_failure("w0", "timeout", kind="probe")
        pool.report_failure("w0", "timeout", kind="probe")
        pool.set_health("w0", alive=True, ready=True)
        assert pool.workers()[0].consecutive_failures == 0


# ---------------------------------------------------------------------------
# router edge cases (real sockets, fake workers)


class TestFleetRouter:
    def _router(self, pool, cache=None, example_shape=(2,), retries=2):
        # warm_rows=0: these tests pin the FLUSH semantics (and count
        # worker calls exactly) — the promote-time warm replay has its
        # own suite (TestCacheWarming) and would race the counts here.
        router = FleetRouter(pool, cache=cache,
                             example_shape=example_shape, port=0,
                             retries=retries, forward_timeout_s=10.0,
                             control_timeout_s=2.0, warm_rows=0)
        router.start()
        return router

    def test_all_workers_down_is_an_immediate_503_not_a_hang(self):
        pool = WorkerPool()
        pool.upsert("w0", "http://127.0.0.1:9")
        pool.set_health("w0", alive=False, ready=False)
        router = self._router(pool)
        try:
            t0 = time.monotonic()
            status, resp, _ = _post_router(router,
                                           {"inputs": _rows(1)})
            assert status == 503 and "no ready workers" in resp["error"]
            assert time.monotonic() - t0 < 5.0
        finally:
            router.close()

    def test_unreachable_workers_yield_503_with_attempts(self):
        # Ready in the table but nothing listening: connection refused
        # on every attempt -> 503 naming the last worker tried.
        pool = WorkerPool()
        pool.upsert("w0", "http://127.0.0.1:1")
        pool.set_health("w0", alive=True, ready=True, checkpoint_step=1)
        router = self._router(pool)
        try:
            status, resp, _ = _post_router(router, {"inputs": _rows(1)})
            assert status == 503 and "no worker reachable" in resp["error"]
            assert pool.workers()[0].consecutive_failures >= 1
        finally:
            router.close()

    def test_failover_hides_a_dead_worker_from_the_client(self):
        good = FakeWorker()
        pool = _pool_with({"w1": good})
        pool.upsert("w0", "http://127.0.0.1:1")  # dead, tried first
        pool.set_health("w0", alive=True, ready=True, checkpoint_step=1)
        router = self._router(pool)
        try:
            # w0 sorts first on the in-flight tie, so every request
            # must fail over; the client must never see it.
            for _ in range(4):
                status, resp, _ = _post_router(router,
                                               {"inputs": _rows(2)})
                assert status == 200 and resp["rows"] == 2
            assert int(router._retries_ctr.value) >= 1
        finally:
            router.close()
            good.close()

    def test_retry_budget_exhausted_surfaces_worker_status(self):
        workers = {f"w{i}": FakeWorker() for i in range(2)}
        for w in workers.values():
            w.mode = "err500"
        pool = _pool_with(workers)
        router = self._router(pool, retries=1)
        try:
            status, resp, _ = _post_router(router, {"inputs": _rows(1)})
            assert status == 500  # the WORKER's code, not a synthetic 502
            assert resp["worker_error"] == "injected worker error"
            assert resp["attempts"] == 2  # budget: first + 1 retry
        finally:
            router.close()
            for w in workers.values():
                w.close()

    def test_all_saturated_aggregates_429_with_retry_after(self):
        workers = {f"w{i}": FakeWorker() for i in range(2)}
        for w in workers.values():
            w.mode = "busy429"
        pool = _pool_with(workers)
        router = self._router(pool)
        try:
            status, resp, headers = _post_router(router,
                                                 {"inputs": _rows(1)})
            assert status == 429
            assert resp["retry_after_s"] == pytest.approx(0.25)
            assert float(headers["Retry-After"]) == pytest.approx(0.25)
            # Saturation is not failure: nobody's ejection counter moved.
            assert all(w.consecutive_failures == 0
                       for w in pool.workers())
        finally:
            router.close()
            for w in workers.values():
                w.close()

    def test_worker_4xx_passes_through_without_retry(self):
        workers = {f"w{i}": FakeWorker() for i in range(2)}
        for w in workers.values():
            w.mode = "bad400"
        pool = _pool_with(workers)
        router = self._router(pool)
        try:
            status, resp, _ = _post_router(router, {"inputs": _rows(1)})
            assert status == 400 and "bad request" in resp["error"]
            # First worker answered; no failover happened for a 4xx.
            assert sum(len(w.embed_calls)
                       for w in workers.values()) == 1
        finally:
            router.close()
            for w in workers.values():
                w.close()

    def test_cache_hit_answers_without_any_worker(self):
        worker = FakeWorker()
        pool = _pool_with({"w0": worker})
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            payload = {"inputs": _rows(3)}
            status, resp, h = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 0
            assert h.get("X-Request-Id")
            assert worker.embed_calls == [3]
            status, resp, _ = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 3
            assert worker.embed_calls == [3]  # nothing new dispatched
            assert int(router._cache_only.value) == 1
            # Mixed request: repeated rows hit, the new row dispatches.
            mixed = {"inputs": _rows(2) + _rows(1, value=9.0)}
            status, resp, _ = _post_router(router, mixed)
            assert status == 200 and resp["cache_hits"] == 2
            assert worker.embed_calls == [3, 1]
        finally:
            router.close()
            worker.close()

    def test_cache_ttl_expiry_re_dispatches(self):
        worker = FakeWorker()
        pool = _pool_with({"w0": worker})
        clock = FakeClock()
        cache = EmbeddingCache(capacity_rows=16, ttl_s=5, clock=clock)
        router = self._router(pool, cache=cache)
        try:
            payload = {"inputs": [[0.0, 0.0], [1.0, 1.0]]}
            _post_router(router, payload)
            _post_router(router, payload)
            assert worker.embed_calls == [2]  # second was a pure hit
            clock.advance(5.01)
            status, resp, _ = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 0
            assert worker.embed_calls == [2, 2]  # expired -> re-dispatch
            assert cache.snapshot()["evictions"]["ttl"] == 2
        finally:
            router.close()
            worker.close()

    def test_canary_rollback_restores_old_checkpoint_routing(self):
        old0, old1 = FakeWorker(step=1), FakeWorker(step=1)
        canary = FakeWorker(step=2)
        canary.mode = "err500"
        pool = _pool_with({"w0": old0, "w1": old1, "w2": canary},
                          canary_fraction=0.5, canary_min_requests=2,
                          canary_max_error_rate=0.1)
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache, retries=2)
        try:
            assert pool.trusted_step == 1
            # Distinct inputs defeat the cache so every request routes;
            # the canary's 500s fail over to old workers -> clients
            # still see 200 while the breach is being counted.
            for i in range(12):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=float(i))})
                assert status == 200
                if 2 in pool.bad_steps:
                    break
            assert 2 in pool.bad_steps and pool.trusted_step == 1
            # The breached step's worker was told to roll back.
            deadline = time.monotonic() + 5.0
            while not canary.rollbacks and time.monotonic() < deadline:
                time.sleep(0.05)
            assert canary.rollbacks and canary.rollbacks[0]["step"] == 2
            assert int(pool._rollbacks.value) == 1
            # Old-checkpoint routing is restored: the canary worker
            # receives NO further /embed traffic.
            seen = len(canary.embed_calls)
            for i in range(8):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=100.0 + i)})
                assert status == 200
            assert len(canary.embed_calls) == seen
        finally:
            router.close()
            for w in (old0, old1, canary):
                w.close()

    def test_canary_promote_flushes_stale_embeddings(self):
        old, canary = FakeWorker(step=1), FakeWorker(step=2)
        pool = _pool_with({"w0": old}, canary_fraction=0.5,
                          canary_min_requests=2,
                          canary_max_error_rate=0.5)
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            # Pre-rollout: an OLD-model embedding enters the cache.
            stale = {"inputs": _rows(1, value=77.0)}
            _post_router(router, stale)
            _post_router(router, stale)
            assert old.embed_calls == [1]  # second was a hit
            # The rollout begins: a step-2 worker joins; while its
            # canary is undecided, nothing new may be inserted.
            pool.upsert("w1", canary.url)
            pool.set_health("w1", alive=True, ready=True,
                            checkpoint_step=2)
            for i in range(10):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=float(i))})
                assert status == 200
                if pool.trusted_step == 2:
                    break
            assert pool.trusted_step == 2
            assert int(pool._promotions.value) == 1
            # Promote flushed: the old model's embedding must not
            # outlive it — the stale payload re-dispatches to a worker.
            calls_before = len(old.embed_calls) + len(canary.embed_calls)
            status, resp, _ = _post_router(router, stale)
            assert status == 200 and resp["cache_hits"] == 0
            assert len(old.embed_calls) + len(canary.embed_calls) == \
                calls_before + 1
        finally:
            router.close()
            for w in (old, canary):
                w.close()

    def test_canary_verdict_decided_on_a_4xx_takes_effect(self):
        # Regression: a promote/rollback decision returned by observe()
        # on the 4xx passthrough path was silently dropped — the pool
        # promoted but the cache kept the OLD model's embeddings.
        old = FakeWorker(step=1)
        canary = FakeWorker(step=2)
        canary.mode = "bad400"
        pool = _pool_with({"w0": old}, canary_fraction=1.0,
                          canary_min_requests=2,
                          canary_max_error_rate=0.5)
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            stale = {"inputs": _rows(1, value=77.0)}
            _post_router(router, stale)
            _post_router(router, stale)
            assert old.embed_calls == [1]  # cached
            pool.upsert("w1", canary.url)
            pool.set_health("w1", alive=True, ready=True,
                            checkpoint_step=2)
            # fraction 1.0: every routed request goes to the canary,
            # whose 400s are healthy-worker outcomes (ok=True) — the
            # SECOND one decides the promote.
            for i in range(2):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=float(i))})
                assert status == 400
            assert pool.trusted_step == 2
            assert int(pool._promotions.value) == 1
            # The decision must have flushed the cache.
            canary.mode = "ok"
            status, resp, _ = _post_router(router, stale)
            assert status == 200 and resp["cache_hits"] == 0
        finally:
            router.close()
            for w in (old, canary):
                w.close()

    def test_unparseable_200_counts_as_a_canary_error(self):
        # Regression: a 200 whose body does not parse marked the worker
        # failed but never reached canary accounting — a canary model
        # emitting garbage was failed over forever, never rolled back.
        old = FakeWorker(step=1)
        canary = FakeWorker(step=2)
        canary.mode = "garbage200"
        pool = _pool_with({"w0": old, "w1": canary},
                          canary_fraction=1.0, canary_min_requests=2,
                          canary_max_error_rate=0.1)
        router = self._router(pool, retries=2)
        try:
            # Each request hits the canary first (fraction 1.0), fails
            # over to the old worker: clients see 200 throughout.
            for i in range(2):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=float(i))})
                assert status == 200
            assert 2 in pool.bad_steps and pool.trusted_step == 1
            assert int(pool._rollbacks.value) == 1
            deadline = time.monotonic() + 5.0
            while not canary.rollbacks and time.monotonic() < deadline:
                time.sleep(0.05)
            assert canary.rollbacks and canary.rollbacks[0]["step"] == 2
        finally:
            router.close()
            for w in (old, canary):
                w.close()

    def test_reply_step_label_overrides_the_routing_table(self):
        # Regression: the served step was snapshotted from the routing
        # table at pick time — a worker that hot-swapped between health
        # probe and forward had its NEW model's embeddings cached as if
        # the trusted model produced them. The worker's reply-time
        # X-Checkpoint-Step label is authoritative.
        worker = FakeWorker(step=1)
        worker.step_header = 2  # already swapped; the table still says 1
        pool = _pool_with({"w0": worker})
        assert pool.trusted_step == 1
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            payload = {"inputs": _rows(1)}
            status, _, _ = _post_router(router, payload)
            assert status == 200
            # Served step 2 != trusted 1: the insert must be refused,
            # so the repeat re-dispatches instead of serving a wrong-
            # model embedding from the cache.
            status, resp, _ = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 0
            assert worker.embed_calls == [1, 1]
        finally:
            router.close()
            worker.close()

    def test_first_trusted_adoption_flushes_random_init_cache(self):
        # Regression: the None -> step trusted transition (first valid
        # checkpoint observed) is a model change with no canary verdict
        # — without a flush, embeddings computed from random init
        # weights kept serving after the fleet adopted a real model.
        worker = FakeWorker(step=None)  # serving random init
        pool = _pool_with({"w0": worker})
        assert pool.trusted_step is None
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            payload = {"inputs": _rows(1)}
            _post_router(router, payload)
            status, resp, _ = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 1
            # The first checkpoint lands and is adopted as trusted.
            pool.set_health("w0", alive=True, ready=True,
                            checkpoint_step=3)
            assert pool.trusted_step == 3
            assert len(cache) == 0
            worker.step = 3
            status, resp, _ = _post_router(router, payload)
            assert status == 200 and resp["cache_hits"] == 0
            assert worker.embed_calls == [1, 1]
        finally:
            router.close()
            worker.close()

    def test_scalar_json_error_bodies_never_crash_the_handler(self):
        # Regression: a 429/5xx body that is valid JSON but NOT an
        # object (a recycled port's foreign service answering "busy")
        # hit detail.get() and raised AttributeError out of forward(),
        # dropping the client's connection with no response at all.
        w500 = {f"w{i}": FakeWorker() for i in range(2)}
        for w in w500.values():
            w.mode = "scalar500"
        router = self._router(_pool_with(w500), retries=1)
        try:
            status, resp, _ = _post_router(router, {"inputs": _rows(1)})
            assert status == 500 and resp["attempts"] == 2
            assert "busy" in resp["worker_error"]
        finally:
            router.close()
            for w in w500.values():
                w.close()
        w429 = {f"w{i}": FakeWorker() for i in range(2)}
        for w in w429.values():
            w.mode = "scalar429"
        router = self._router(_pool_with(w429))
        try:
            status, resp, headers = _post_router(router,
                                                 {"inputs": _rows(1)})
            assert status == 429  # default retry-after, not a crash
            assert resp["retry_after_s"] == pytest.approx(0.05)
            assert "Retry-After" in headers
        finally:
            router.close()
            for w in w429.values():
                w.close()

    def test_flush_mid_flight_never_mixes_models(self):
        # Regression: rows cached before a promote/rollback flush were
        # merged with rows fetched AFTER it — one response mixing two
        # models' embedding spaces. A generation change between lookup
        # and merge must re-forward the whole request instead.
        worker = FakeWorker(step=1)
        pool = _pool_with({"w0": worker})
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            row_a = _rows(1, value=1.0)
            _post_router(router, {"inputs": row_a})  # caches row A
            assert worker.embed_calls == [1]

            def flush_once(rows):
                worker.on_embed = None
                cache.clear(reason="promote")

            worker.on_embed = flush_once
            # Row A hits, row B forwards; the flush lands while B's
            # forward is in flight.
            status, resp, _ = _post_router(
                router, {"inputs": row_a + _rows(1, value=2.0)})
            assert status == 200
            # No stale merge: the response reports zero cache hits and
            # the whole request was re-dispatched (1-row sub-request,
            # then the full 2-row one).
            assert resp["cache_hits"] == 0
            assert worker.embed_calls == [1, 1, 2]
        finally:
            router.close()
            worker.close()

    def test_worker_504_passes_through_without_retry_or_ejection(self):
        # Regression: 504 sat in the `>= 500` failure class, so a
        # client-chosen timeout_ms expiring under load retried on other
        # workers (burning another full deadline each) and counted
        # toward ejection and canary breach — healthy workers got
        # SIGKILLed for their clients' impatience. The module contract
        # lists 504 with the 4xx pass-throughs.
        workers = {f"w{i}": FakeWorker() for i in range(2)}
        for w in workers.values():
            w.mode = "deadline504"
        pool = _pool_with(workers)
        router = self._router(pool, retries=2)
        try:
            status, resp, _ = _post_router(router, {"inputs": _rows(1)})
            assert status == 504 and "deadline" in resp["error"]
            # One attempt total, and nobody's ejection counter moved.
            assert sum(len(w.embed_calls)
                       for w in workers.values()) == 1
            assert all(w.consecutive_failures == 0
                       for w in pool.workers())
        finally:
            router.close()
            for w in workers.values():
                w.close()

    def test_laggard_fetch_never_merges_with_a_newer_models_cache(self):
        # Regression: post-promote, the cache holds the NEW trusted
        # model's rows while staggered laggards still serve the old
        # step in the same routing cohort — a partial-hit request whose
        # misses landed on a laggard merged two models' embeddings into
        # one response. served-step vs trusted-step gates the merge,
        # not just the insert.
        new = FakeWorker(step=2)
        new.step_header = 2
        lag = FakeWorker(step=1)
        lag.step_header = 1
        pool = _pool_with({"w0": new})
        assert pool.trusted_step == 2
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = self._router(pool, cache=cache)
        try:
            row = _rows(1, value=7.0)
            _post_router(router, {"inputs": row})  # caches the new
            assert new.embed_calls == [1]          # model's embedding
            # The laggard joins (old cohort) and becomes the only
            # routable worker — the stagger window, concentrated.
            pool.upsert("w1", lag.url)
            pool.set_health("w1", alive=True, ready=True,
                            checkpoint_step=1)
            pool.set_health("w0", alive=True, ready=False)
            status, resp, _ = _post_router(
                router, {"inputs": row + _rows(1, value=8.0)})
            assert status == 200
            # No mixed merge: the cached step-2 row was refused and the
            # whole request re-forwarded to the laggard (1-row sub-
            # request, then the full 2-row one).
            assert resp["cache_hits"] == 0
            assert lag.embed_calls == [1, 2]
        finally:
            router.close()
            new.close()
            lag.close()

    def test_rollback_broadcast_is_off_the_request_thread(self):
        # Regression: the breach-deciding client's own request ran the
        # serial /rollback broadcast inline — with a wedged worker that
        # is up to workers x control_timeout_s of added latency on one
        # unlucky response. The pool blocklists synchronously, so the
        # broadcast can be async.
        worker = FakeWorker(step=2)
        worker.rollback_delay_s = 1.0
        pool = _pool_with({"w0": worker})
        router = self._router(pool)
        try:
            t0 = time.monotonic()
            router._handle_decision(("rollback", 2))
            assert time.monotonic() - t0 < 0.5
            deadline = time.monotonic() + 5.0
            while not worker.rollbacks and time.monotonic() < deadline:
                time.sleep(0.05)
            assert worker.rollbacks and worker.rollbacks[0]["step"] == 2
        finally:
            router.close()
            worker.close()

    def test_router_healthz_and_metrics_surface_the_pool(self):
        worker = FakeWorker()
        pool = _pool_with({"w0": worker})
        # One registry, two views: the cache shares the pool's so its
        # counters render in the router's Prometheus exposition.
        router = self._router(
            pool, cache=EmbeddingCache(registry=pool.registry))
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{router.port}/healthz",
                    timeout=10) as r:
                health = json.loads(r.read())
            assert r.status == 200 and health["workers_ready"] == 1
            _post_router(router, {"inputs": _rows(2)})
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{router.port}/metrics",
                    timeout=10) as r:
                m = json.loads(r.read())
            assert m["requests"] == 1 and m["forwards"] == 1
            assert m["workers"]["w0"]["ready"] is True
            assert m["cache"]["misses"] == 2
            # Prometheus negotiation serves the shared registry.
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{router.port}"
                    "/metrics?format=prometheus", timeout=10) as r:
                prom = r.read().decode()
            assert "fleet_requests_total 1" in prom
            assert "fleet_cache_misses_total 2" in prom
        finally:
            router.close()
            worker.close()


# ---------------------------------------------------------------------------
# engine warm swap + server readiness (real JAX, linear model)


def _linear_engine(buckets=(1, 2), dim=3):
    w = jnp.asarray(np.random.RandomState(0).rand(2, dim), jnp.float32)
    return InferenceEngine(lambda v, x: x @ v, w, example_shape=(2,),
                           buckets=buckets)


class TestSwapVariables:
    def test_same_structure_swap_reuses_the_compiled_ladder(self):
        eng = _linear_engine()
        eng.warmup()
        compiles = eng.metrics.compiles
        x = np.ones((1, 2), np.float32)
        out0 = eng.embed(x)
        new_w = jnp.asarray(np.asarray(eng.variables) + 1.0)
        assert eng.swap_variables(new_w) == "reused"
        out1 = eng.embed(x)
        assert eng.metrics.compiles == compiles  # zero new compiles
        assert not np.allclose(out0, out1)
        np.testing.assert_allclose(out1, x @ np.asarray(new_w), rtol=1e-6)
        assert eng.metrics.model_swaps == 1

    def test_changed_structure_swap_warms_before_publishing(self):
        eng = _linear_engine(buckets=(1, 2), dim=3)
        eng.warmup()
        compiles = eng.metrics.compiles
        wider = jnp.asarray(np.random.RandomState(1).rand(2, 5),
                            jnp.float32)
        assert eng.swap_variables(wider) == "warmed"
        # The whole ladder compiled during the swap...
        assert eng.metrics.compiles == compiles + 2
        # ...so serving it costs no further compiles.
        out = eng.embed(np.ones((2, 2), np.float32))
        assert out.shape == (2, 5)
        assert eng.metrics.compiles == compiles + 2

    def test_changed_structure_swap_evicts_the_old_ladder(self):
        # Regression: structural swaps only ADDED executables under the
        # new hash — a long-lived worker adopting structure-changing
        # checkpoints grew the compile cache (and its pinned device
        # allocations) without bound.
        eng = _linear_engine(buckets=(1, 2), dim=3)
        eng.warmup()
        assert len(eng._cache) == 2
        wider = jnp.asarray(np.random.RandomState(1).rand(2, 5),
                            jnp.float32)
        eng.swap_variables(wider)
        assert len(eng._cache) == 2  # old structure's entries dropped
        assert all(key[2] == eng._hash for key in eng._cache)


class TestReadiness:
    def test_readyz_is_distinct_from_healthz_while_warming(self):
        eng = _linear_engine()
        eng.warmup()
        srv = EmbeddingServer(eng, port=0, max_delay_s=0.01,
                              queue_size=4)
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            srv.begin_warmup()
            with urllib.request.urlopen(base + "/healthz",
                                        timeout=10) as r:
                assert r.status == 200  # alive...
            try:
                urllib.request.urlopen(base + "/readyz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                body = json.loads(e.read())
                assert e.code == 503 and body["status"] == "warming"
                assert float(e.headers["Retry-After"]) > 0
            # /embed sheds while cold, with the same semantics.
            req = urllib.request.Request(
                base + "/embed",
                data=json.dumps({"inputs": _rows(1)}).encode(),
                method="POST")
            try:
                urllib.request.urlopen(req, timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert "retry_after_s" in json.loads(e.read())
            srv.end_warmup()
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=10) as r:
                body = json.loads(r.read())
                assert r.status == 200 and body["status"] == "ready"
        finally:
            srv.close()

    def test_begin_warmup_before_start_is_red_from_the_first_probe(self):
        # Regression: the fleet-worker CLI marked the ladder cold only
        # AFTER binding and publishing the port — a probe racing that
        # window saw ready=true on a cold worker. The supported order
        # is cold-before-bind.
        eng = _linear_engine()
        eng.warmup()
        srv = EmbeddingServer(eng, port=0, max_delay_s=0.01,
                              queue_size=4)
        srv.begin_warmup()
        srv.start()
        try:
            base = f"http://127.0.0.1:{srv.port}"
            try:
                urllib.request.urlopen(base + "/readyz", timeout=10)
                raise AssertionError("expected 503")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["status"] == "warming"
            srv.end_warmup()
            with urllib.request.urlopen(base + "/readyz",
                                        timeout=10) as r:
                assert r.status == 200
        finally:
            srv.close()

    def test_embed_replies_carry_the_checkpoint_step_label(self):
        # The reply-time X-Checkpoint-Step label is what the router
        # trusts over its own (hot-swap-lagged) routing table.
        eng = _linear_engine()
        eng.warmup()
        eng.metrics.set_checkpoint_step(4)
        srv = EmbeddingServer(eng, port=0, max_delay_s=0.01,
                              queue_size=4)
        srv.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{srv.port}/embed",
                data=json.dumps({"inputs": _rows(1)}).encode(),
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
                assert r.headers["X-Checkpoint-Step"] == "4"
        finally:
            srv.close()


# ---------------------------------------------------------------------------
# fault-plan fleet actions


class TestFleetFaults:
    def test_parse_fleet_actions(self):
        plan = FaultPlan.parse("killworker@3,slowworker@5,killworker@8")
        assert plan.killworker_ticks == (3, 8)
        assert plan.slowworker_ticks == (5,)
        assert not plan.empty()

    def test_unknown_action_error_lists_the_valid_set(self):
        with pytest.raises(ValueError) as exc:
            FaultPlan.parse("killwrker@3")
        msg = str(exc.value)
        assert "killwrker" in msg
        for kind in ("killworker", "slowworker", "nan", "sigterm",
                     "truncate"):
            assert kind in msg, f"{kind} missing from: {msg}"

    def test_on_fleet_tick_fires_at_the_named_ordinals(self):
        inj = FaultInjector(FaultPlan.parse("killworker@2,slowworker@2,"
                                            "killworker@4"))
        fired = [inj.on_fleet_tick() for _ in range(5)]
        assert fired == [[], ["killworker@2", "slowworker@2"], [],
                         ["killworker@4"], []]
        assert inj.fired == ["killworker@2", "slowworker@2",
                             "killworker@4"]


# ---------------------------------------------------------------------------
# checkpoint watcher (real CheckpointManager, fake engine)


class FakeSwapEngine:
    """Engine double for watcher tests: records swaps, no JAX."""

    def __init__(self):
        from ntxent_tpu.serving import ServingMetrics

        self.metrics = ServingMetrics()
        self.variables = {"w": np.zeros(2, np.float32)}
        self.swaps: list = []

    def swap_variables(self, variables, warm=True):
        self.swaps.append(variables)
        self.variables = variables
        return "reused"


def _save_step(ckpt_dir, step: int, value: float):
    from ntxent_tpu.training.checkpoint import CheckpointManager

    mgr = CheckpointManager(ckpt_dir, max_to_keep=None)
    try:
        assert mgr.save(step, {"w": np.full(2, value, np.float32)},
                        force=True)
    finally:
        mgr.close()


def _watcher(ckpt_dir, engine, **kw):
    from ntxent_tpu.serving import CheckpointWatcher

    return CheckpointWatcher(ckpt_dir, {"w": np.zeros(2, np.float32)},
                             engine, variables_fn=lambda s: s, **kw)


class TestCheckpointWatcher:
    def test_adopts_newest_valid_step_and_skips_corrupt(self, tmp_path):
        from ntxent_tpu.resilience.faults import truncate_checkpoint_file

        ckpt = tmp_path / "ckpt"
        _save_step(ckpt, 1, 1.0)
        _save_step(ckpt, 2, 2.0)
        truncate_checkpoint_file(ckpt, step=2)  # torn: must be invisible
        eng = FakeSwapEngine()
        watcher = _watcher(ckpt, eng)
        try:
            assert watcher.poll_once() is True
            assert watcher.current_step == 1
            np.testing.assert_array_equal(eng.variables["w"],
                                          np.full(2, 1.0))
            assert watcher.poll_once() is False  # nothing newer valid
            _save_step(ckpt, 3, 3.0)
            assert watcher.poll_once() is True
            assert watcher.current_step == 3
            assert eng.metrics.checkpoint_step == 3
        finally:
            watcher.stop()

    def test_delay_staggers_adoption(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        _save_step(ckpt, 1, 1.0)
        eng = FakeSwapEngine()
        watcher = _watcher(ckpt, eng, delay_s=0.4)
        try:
            assert watcher.poll_once() is False  # seen, not adopted yet
            time.sleep(0.45)
            assert watcher.poll_once() is True
            assert watcher.current_step == 1
        finally:
            watcher.stop()

    def test_rollback_reverts_and_blocklists(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        _save_step(ckpt, 1, 1.0)
        _save_step(ckpt, 2, 2.0)
        eng = FakeSwapEngine()
        watcher = _watcher(ckpt, eng)
        try:
            watcher.poll_once()  # adopts 2 directly
            assert watcher.current_step == 2
            assert watcher.rollback(2) is True
            # Reverted to the previously served weights (random init
            # here — step None) and the bad step can never come back.
            assert watcher.current_step is None
            assert 2 in watcher.blocked_steps
            assert watcher.poll_once() is True  # falls back to step 1
            assert watcher.current_step == 1
            assert watcher.poll_once() is False  # 2 stays blocked
            assert eng.metrics.to_dict()["checkpoint_step"] == 1
        finally:
            watcher.stop()

    def test_rollback_of_a_non_served_step_only_blocklists(self, tmp_path):
        ckpt = tmp_path / "ckpt"
        _save_step(ckpt, 1, 1.0)
        eng = FakeSwapEngine()
        watcher = _watcher(ckpt, eng)
        try:
            watcher.poll_once()
            assert watcher.current_step == 1
            swaps = len(eng.swaps)
            assert watcher.rollback(7) is False  # not what we serve
            assert 7 in watcher.blocked_steps
            assert len(eng.swaps) == swaps  # weights untouched
        finally:
            watcher.stop()


# ---------------------------------------------------------------------------
# fleet supervision (real subprocesses, JAX-free fake worker)


_FAKE_WORKER = textwrap.dedent("""
    import json, signal, sys
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    port_file = sys.argv[1]

    class H(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"
        def log_message(self, *a):
            pass
        def do_GET(self):
            body = json.dumps({"status": "ready",
                               "checkpoint_step": 1}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
    with open(port_file + ".tmp", "w") as f:
        f.write(str(httpd.server_address[1]))
    import os
    os.replace(port_file + ".tmp", port_file)
    httpd.serve_forever()
""")


def _fake_worker_cmd(worker_id, port_file):
    return [sys.executable, "-c", _FAKE_WORKER, str(port_file)]


def _fast_fleet(tmp_path, n=1, **kw):
    kw.setdefault("backoff", RetryPolicy(max_attempts=10,
                                         base_delay_s=0.05,
                                         multiplier=1.0, jitter=0.0))
    return ServingFleet(_fake_worker_cmd, n_workers=n,
                        workdir=tmp_path / "fleet", poll_s=0.1,
                        health_timeout_s=2.0, **kw)


def _tick_until(fleet, predicate, timeout_s=15.0, sleep_s=0.05):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        fleet.tick()
        if predicate():
            return True
        time.sleep(sleep_s)
    return False


class TestServingFleet:
    def test_spawns_and_reports_ready(self, tmp_path):
        fleet = _fast_fleet(tmp_path, n=2)
        for w in fleet.workers:
            fleet._spawn(w)
        try:
            assert _tick_until(
                fleet, lambda: sum(1 for w in fleet.pool.workers()
                                   if w.ready) == 2)
            assert {w.checkpoint_step
                    for w in fleet.pool.workers()} == {1}
        finally:
            fleet.stop()

    def test_sigkilled_worker_is_detected_and_restarted(self, tmp_path):
        fleet = _fast_fleet(tmp_path, n=1)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            first_pid = worker.pid
            import os

            os.kill(first_pid, signal.SIGKILL)
            worker.proc.wait(5.0)
            fleet.tick()  # detects death, marks not-ready, schedules
            entry = fleet.pool.workers()[0]
            assert not entry.ready and worker.restarts == 1
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            assert worker.pid != first_pid
            assert int(fleet._worker_restarts.value) == 1
        finally:
            fleet.stop()

    def test_restart_clears_the_dead_incarnations_failures(self, tmp_path):
        # Regression: a SIGKILL under load leaves router-observed
        # forward failures (>= eject_after) on the pool entry. The
        # replacement process must NOT inherit them — it would be
        # ejected while still booting, before its port file appears,
        # in an endless eject/backoff loop.
        fleet = _fast_fleet(tmp_path, n=1, eject_after=3)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            # The router saw the worker die mid-forward, three times.
            for _ in range(3):
                fleet.pool.report_failure(worker.worker_id,
                                          "connection reset")
            import os

            os.kill(worker.pid, signal.SIGKILL)
            worker.proc.wait(5.0)
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            assert worker.restarts == 1  # exactly one, not a loop
            assert int(fleet._ejections.value) <= 1
        finally:
            fleet.stop()

    def test_forward_failures_eject_a_probe_passing_worker(self, tmp_path):
        # Regression: the tick probes (healthy -> counter reset) right
        # before its eject check, so router-reported forward failures
        # were wiped before the check ever saw them — a worker that
        # answers /readyz but 500s every /embed was never ejected.
        fleet = _fast_fleet(tmp_path, n=1, eject_after=3)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            for _ in range(3):
                fleet.pool.report_failure(worker.worker_id, "http 500")
            fleet.tick()  # probe passes; the eject check must still fire
            assert int(fleet._ejections.value) == 1
            assert worker.restarts == 1
            # The replacement boots clean and serves again.
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
        finally:
            fleet.stop()

    def test_failed_spawn_reschedules_instead_of_stranding(self, tmp_path):
        # Regression: _spawn cleared restart_at before Popen — a launch
        # failure (exec ENOMEM, missing binary) left proc=None AND
        # restart_at=None, a state no later tick ever looks at: the
        # worker was silently lost forever. It must keep rescheduling
        # until the restart budget rules.
        fleet = ServingFleet(
            lambda wid, pf: ["/nonexistent-binary-xyzzy"],
            n_workers=1, workdir=tmp_path / "fleet", poll_s=0.05,
            max_restarts=2,
            backoff=RetryPolicy(max_attempts=10, base_delay_s=0.01,
                                multiplier=1.0, jitter=0.0))
        worker = fleet.workers[0]
        fleet._spawn(worker)  # fails, must not raise
        assert worker.proc is None and worker.restart_at is not None
        assert worker.restarts == 1
        deadline = time.monotonic() + 10.0
        while worker.restarts <= fleet.max_restarts \
                and time.monotonic() < deadline:
            fleet.tick()
            time.sleep(0.02)
        # Budget exhausted: gave up EXPLICITLY (restart_at cleared by
        # the budget check, not by the lost-worker bug).
        assert worker.restarts == fleet.max_restarts + 1
        assert worker.restart_at is None and worker.proc is None

    def test_router_tier_import_is_jax_free(self):
        # The ntxent-fleet router process must restart in milliseconds:
        # its entire import surface (cli + cache/router/fleet + obs +
        # faults) must not drag in JAX. Lazy package inits (PEP 562)
        # keep this true — this test is the END-TO-END proof, and since
        # ISSUE 13 no longer the only one: the static import-boundary
        # checker (ntxent_tpu/analysis) walks the same graph at lint
        # time and names the culprit file:line when it trips. The
        # agreement assertion below is what keeps the two from
        # drifting: every module the runtime actually loads must be in
        # the checker's statically reachable set, so a module that
        # sneaks onto the runtime chain without static coverage fails
        # HERE even while both proofs individually pass.
        import subprocess
        r = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "import ntxent_tpu.cli\n"
             "from ntxent_tpu.serving import (EmbeddingCache, "
             "FleetRouter, ServingFleet, WorkerPool)\n"
             "from ntxent_tpu import obs\n"
             "from ntxent_tpu.resilience import FaultInjector, "
             "FaultPlan\n"
             # ISSUE 15: the retrieval tier rides the router process —
             # the whole index surface (manager, index, segments, IVF)
             # must stay importable without paying backend init.
             "from ntxent_tpu.retrieval import (IndexManager, "
             "VectorIndex, SegmentStore, IVFIndex)\n"
             # ISSUE 17: the PQ codec, fused batched scan, and shard
             # plane join the same surface — shard workers restart on
             # the router's schedule and must come up in milliseconds.
             "from ntxent_tpu.retrieval import (PQCodec, CodedLists, "
             "ScanBatcher, batched_scan, ShardFanout, ShardServer, "
             "IndexShard)\n"
             # ISSUE 20: the insert journal + rendezvous placement are
             # the self-healing machinery — they load on every shard
             # worker boot, the path where restart latency IS repair
             # latency.
             "from ntxent_tpu.retrieval import (ShardJournal, "
             "shard_owner)\n"
             "assert 'jax' not in sys.modules, 'jax leaked'\n"
             "print('\\n'.join(sorted(m for m in sys.modules\n"
             "                        if m.startswith('ntxent_tpu'))))\n"],
            capture_output=True, text=True, timeout=120)
        assert r.returncode == 0, r.stderr
        from ntxent_tpu.analysis import reachable_modules

        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        static = set(reachable_modules(root=repo_root))
        loaded = {m for m in r.stdout.split() if m}
        assert loaded, "tripwire subprocess printed no module list"
        missing = loaded - static
        assert not missing, (
            "runtime router tier loaded modules the static "
            f"import-boundary checker does not reach: {sorted(missing)}"
            " — add them to LintConfig.boundary_roots (or fix the "
            "import that pulled them in)")

    def test_chaos_killworker_fires_on_the_named_tick(self, tmp_path):
        inj = FaultInjector(FaultPlan.parse("killworker@3"))
        fleet = _fast_fleet(tmp_path, n=1, injector=inj)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()),
                timeout_s=10.0)
            # _tick_until advanced an unknown number of ticks; drive
            # until the plan's ordinal passes and the kill lands.
            deadline = time.monotonic() + 10.0
            while not inj.fired and time.monotonic() < deadline:
                fleet.tick()
                time.sleep(0.05)
            assert inj.fired == ["killworker@3"]
            assert worker.proc is None or worker.proc.poll() is not None \
                or worker.restarts >= 1 or _tick_until(
                    fleet, lambda: worker.restarts >= 1, timeout_s=5.0)
        finally:
            fleet.stop()


# ---------------------------------------------------------------------------
# cache warming on promote (ROADMAP item 4 follow-up)


class TestCacheWarming:
    def test_hot_keys_tracks_hit_rows_most_recent_first(self):
        cache = EmbeddingCache(capacity_rows=8, ttl_s=60, hot_rows=2)
        rows = np.arange(8, dtype=np.float32).reshape(4, 2)
        cache.insert(rows, np.zeros((4, 4), np.float32))
        assert cache.hot_keys(4) == []  # inserts alone are not heat
        cache.lookup(rows[:1])   # row 0 hits
        cache.lookup(rows[1:3])  # rows 1, 2 hit -> row 0 falls off (cap 2)
        hot = cache.hot_keys(4)
        assert len(hot) == 2  # bounded by hot_rows
        np.testing.assert_array_equal(hot[0], rows[2])
        np.testing.assert_array_equal(hot[1], rows[1])
        # A model flush keeps the hot INPUTS (they carry no model state).
        cache.clear(reason="promote")
        assert len(cache) == 0 and len(cache.hot_keys(4)) == 2
        assert cache.snapshot()["hot_rows"] == 2

    def test_promote_replays_hot_rows_through_the_new_model(self):
        worker = FakeWorker(step=1)
        pool = _pool_with({"w0": worker}, canary_fraction=1.0,
                          canary_min_requests=2,
                          canary_max_error_rate=0.5)
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = FleetRouter(pool, cache=cache, example_shape=(2,),
                             port=0, retries=2, forward_timeout_s=10.0,
                             warm_rows=8)
        router.start()
        try:
            hot = {"inputs": _rows(1, value=77.0)}
            _post_router(router, hot)
            _post_router(router, hot)  # the hit marks the row hot
            assert len(cache.hot_keys(8)) == 1
            # The worker hot-swaps to step 2: it canaries (fraction 1.0
            # routes everything to it) and promotes on clean outcomes.
            worker.step = 2
            pool.set_health("w0", alive=True, ready=True,
                            checkpoint_step=2)
            for i in range(6):
                status, _, _ = _post_router(
                    router, {"inputs": _rows(1, value=float(i))})
                assert status == 200
                if pool.trusted_step == 2:
                    break
            assert pool.trusted_step == 2
            # Warming runs off the deciding request's thread.
            deadline = time.monotonic() + 10.0
            while int(router._cache_warmed.value) < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert int(router._cache_warmed.value) == 1
            assert router.metrics_dict()["cache_warmed"] == 1
            # The hot payload answers from the cache — with the NEW
            # model's embedding and no worker in the loop.
            calls = len(worker.embed_calls)
            status, resp, _ = _post_router(router, hot)
            assert status == 200 and resp["cache_hits"] == 1
            assert resp["embeddings"][0][0] == 2.0  # step-2 model
            assert len(worker.embed_calls) == calls
        finally:
            router.close()
            worker.close()

    def test_warm_rows_zero_boots_the_cache_cold(self):
        worker = FakeWorker(step=1)
        pool = _pool_with({"w0": worker}, canary_fraction=1.0,
                          canary_min_requests=2,
                          canary_max_error_rate=0.5)
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = FleetRouter(pool, cache=cache, example_shape=(2,),
                             port=0, retries=2, forward_timeout_s=10.0,
                             warm_rows=0)
        router.start()
        try:
            hot = {"inputs": _rows(1, value=77.0)}
            _post_router(router, hot)
            _post_router(router, hot)
            worker.step = 2
            pool.set_health("w0", alive=True, ready=True,
                            checkpoint_step=2)
            for i in range(6):
                _post_router(router, {"inputs": _rows(1, value=float(i))})
                if pool.trusted_step == 2:
                    break
            assert pool.trusted_step == 2
            time.sleep(0.2)  # any (buggy) warm thread would land here
            assert int(router._cache_warmed.value) == 0
            # Cold as before: the hot payload re-dispatches.
            status, resp, _ = _post_router(router, hot)
            assert status == 200 and resp["cache_hits"] == 0
        finally:
            router.close()
            worker.close()

    def test_warm_replay_chunks_under_the_worker_row_cap(self):
        # Production-sized hot sets exceed one request's body/row caps;
        # the replay must chunk — a 413 halves the chunk and retries —
        # so every hot row is still warmed, not silently dropped.
        worker = FakeWorker(step=1)
        worker.max_rows = 2
        pool = _pool_with({"w0": worker})
        cache = EmbeddingCache(capacity_rows=16, ttl_s=60)
        router = FleetRouter(pool, cache=cache, example_shape=(2,),
                             port=0, retries=2,
                             forward_timeout_s=10.0, warm_rows=8)
        try:
            rows = [np.full(2, float(i), np.float32) for i in range(7)]
            assert router._warm_cache(rows) == 7
            assert int(router._cache_warmed.value) == 7
            assert len(cache) == 7
            # The tiny rows made the byte-budget estimate admit all 7
            # at once; the worker's 413s walked the chunk size under
            # its cap and every successful replay fit it.
            assert worker.embed_calls[0] == 7
            served = [r for r in worker.embed_calls if r <= 2]
            assert sum(served) == 7
        finally:
            router.close()
            worker.close()


# ---------------------------------------------------------------------------
# router replication (ROADMAP item 4 follow-up)


class TestRouterReplication:
    def test_two_routers_one_worker_pool_converge(self):
        # The router is stateless by design; N of them over one worker
        # set must serve correctly AND reach the same canary verdict
        # independently (no split-brain on trusted_step).
        w0, w1 = FakeWorker(step=1), FakeWorker(step=1)
        pools = [_pool_with({"w0": w0, "w1": w1}, canary_fraction=1.0,
                            canary_min_requests=2,
                            canary_max_error_rate=0.5)
                 for _ in range(2)]
        routers = []
        try:
            for pool in pools:
                router = FleetRouter(pool, example_shape=(2,), port=0,
                                     retries=2, forward_timeout_s=10.0)
                routers.append(router.start())
            for router in routers:
                status, _, _ = _post_router(router, {"inputs": _rows(1)})
                assert status == 200
            assert [p.trusted_step for p in pools] == [1, 1]
            # A rollout lands: both routers observe w1 at step 2 and
            # each runs its own canary to a promote.
            w1.step = 2
            for pool in pools:
                pool.set_health("w1", alive=True, ready=True,
                                checkpoint_step=2)
            for router, pool in zip(routers, pools):
                for i in range(8):
                    status, _, _ = _post_router(
                        router, {"inputs": _rows(1, value=float(i))})
                    assert status == 200
                    if pool.trusted_step == 2:
                        break
            assert [p.trusted_step for p in pools] == [2, 2]
            # A worker dies under both routers: each fails over to the
            # survivor with zero client-visible errors.
            w0.close()
            for router in routers:
                status, resp, _ = _post_router(
                    router, {"inputs": _rows(1, value=500.0)})
                assert status == 200
                assert resp["embeddings"][0][0] == 2.0  # the survivor
        finally:
            for router in routers:
                router.close()
            w1.close()


class TestAttachMode:
    def test_attach_probes_without_owning_processes(self, tmp_path):
        import os

        primary = _fast_fleet(tmp_path, n=1)
        worker = primary.workers[0]
        primary._spawn(worker)
        try:
            assert _tick_until(
                primary, lambda: any(w.ready
                                     for w in primary.pool.workers()))
            replica = ServingFleet(_fake_worker_cmd, n_workers=1,
                                   workdir=tmp_path / "fleet",
                                   poll_s=0.1, attach=True)
            # Discovered the primary's worker from its port file.
            assert [w.worker_id for w in replica.workers] == ["w0"]
            assert _tick_until(
                replica, lambda: any(w.ready
                                     for w in replica.pool.workers()))
            assert int(replica._spawns.value) == 0
            # SIGKILL: the replica goes not-ready but must neither kill
            # nor restart — supervision belongs to the primary.
            first_pid = worker.pid
            os.kill(first_pid, signal.SIGKILL)
            worker.proc.wait(5.0)
            assert _tick_until(
                replica, lambda: not any(w.ready for w in
                                         replica.pool.workers()))
            assert replica.workers[0].restarts == 0
            assert replica.workers[0].proc is None
            # The primary restarts it on a NEW port; the replica
            # re-reads the republished port file and recovers.
            assert _tick_until(
                primary, lambda: any(w.ready
                                     for w in primary.pool.workers()))
            assert worker.pid != first_pid
            assert _tick_until(
                replica, lambda: any(w.ready
                                     for w in replica.pool.workers()))
            # Replica teardown leaves the primary's process alive.
            replica.stop()
            assert worker.alive()
        finally:
            primary.stop()


# ---------------------------------------------------------------------------
# fleet observability plane (ISSUE 10): federation endpoint, run_info,
# flight dumps on worker death/ejection


from ntxent_tpu import obs as _obs
from ntxent_tpu.obs.aggregate import FleetAggregator
from ntxent_tpu.obs.registry import MetricsRegistry


def _get_router(router, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{router.port}{path}", timeout=15) as r:
        ctype = r.headers.get("Content-Type", "")
        body = r.read()
    return ctype, body


class TestFleetMetricsEndpoint:
    def _worker_registry(self, n):
        r = MetricsRegistry()
        r.counter("serving_requests_total").inc(n)
        return r

    def test_metrics_fleet_equals_sum_of_worker_scrapes(self):
        # The acceptance equality: the federated counter total IS the
        # sum of the per-worker scrapes, served over the router's
        # /metrics/fleet without any worker in the serving path.
        regs = [self._worker_registry(n) for n in (11, 31)]
        servers = [_obs.MetricsServer(r).start() for r in regs]
        pool = WorkerPool()
        router = FleetRouter(pool, example_shape=(2,), port=0)
        router.aggregator = FleetAggregator(
            lambda: {f"w{i}": f"http://127.0.0.1:{s.port}"
                     for i, s in enumerate(servers)},
            local={"router": router.registry})
        router.start()
        try:
            ctype, body = _get_router(router, "/metrics/fleet")
            assert "text/plain" in ctype  # a scrape endpoint
            text = body.decode()
            assert "serving_requests_total 42" in text
            assert 'fleet_fed_instance_up{instance="w0"} 1' in text
            # The router's own registry federates alongside workers.
            assert "fleet_requests_total" in text
            # JSON view of the same merged registry.
            ctype, body = _get_router(router,
                                      "/metrics/fleet?format=json")
            assert json.loads(body)["serving_requests_total"] == 42
            # A worker dying mid-scrape yields partial-but-valid (the
            # satellite's not-a-500 clause) — stale marked, 200 served.
            # (In production the background tick refreshes the view;
            # here the test drives the tick itself.)
            servers[1].close()
            router.aggregator.scrape_once()
            ctype, body = _get_router(router, "/metrics/fleet")
            text = body.decode()
            assert "serving_requests_total 42" in text  # last-good
            assert 'fleet_fed_instance_up{instance="w1"} 0' in text
        finally:
            router.close()
            for s in servers:
                s.close()

    def test_metrics_fleet_without_aggregator_is_503(self):
        router = FleetRouter(WorkerPool(), example_shape=(2,), port=0)
        router.start()
        try:
            req = urllib.request.Request(
                f"http://127.0.0.1:{router.port}/metrics/fleet")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 503
        finally:
            router.close()

    def test_router_state_format_and_run_info(self):
        # ISSUE 10 satellite: the router serves the same raw-state
        # federation view as workers and stamps its own run identity.
        router = FleetRouter(WorkerPool(), example_shape=(2,), port=0)
        router.set_run_id("cafe1234")
        router.start()
        try:
            _, body = _get_router(router, "/metrics?format=state")
            state = json.loads(body)
            names = {m["name"] for m in state["metrics"]}
            assert "fleet_requests_total" in names
            info = [m for m in state["metrics"]
                    if m["name"] == "serving_run_info"]
            assert info and info[0]["labels"] == {"run_id": "cafe1234"}
            _, body = _get_router(router, "/metrics?format=prometheus")
            assert 'serving_run_info{run_id="cafe1234"} 1' \
                in body.decode()
            _, body = _get_router(router, "/metrics")
            assert json.loads(body)["run_id"] == "cafe1234"
        finally:
            router.close()

    def test_alerts_endpoint_serves_the_store(self):
        router = FleetRouter(WorkerPool(), example_shape=(2,), port=0)
        router.alerts.fire("availability", reason="burn", value=3.0)
        router.start()
        try:
            _, body = _get_router(router, "/alerts")
            snap = json.loads(body)
            assert snap["firing"] == ["availability"]
            assert snap["active"][0]["reason"] == "burn"
        finally:
            router.close()


class TestFleetFlightRecorder:
    def test_killworker_chaos_dumps_flight_with_restart_tail(
            self, tmp_path):
        # The ISSUE 10 satellite: a killworker@T round must leave a
        # flight-recorder file whose tail shows the death and the
        # scheduled restart — the postmortem captured AT the event.
        log = _obs.EventLog(str(tmp_path / "fleet.jsonl"))
        previous = _obs.install(log)
        injector = FaultInjector(FaultPlan.parse("killworker@1"))
        fleet = _fast_fleet(tmp_path, n=1, injector=injector)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            first_pid = worker.pid
            # Next ticks: chaos arms (all ready), kills, death detected.
            assert _tick_until(
                fleet, lambda: worker.restarts >= 1, timeout_s=20.0)
            assert injector.fired == ["killworker@1"]
            flights = sorted(tmp_path.glob("flight_*.jsonl"))
            assert flights, "no flight dump on worker death"
            records = [json.loads(line) for f in flights
                       for line in f.read_text().splitlines()]
            assert records[0]["reason"].startswith("worker_death:w0")
            fleet_recs = [r for r in records if r.get("event") == "fleet"]
            actions = [r["action"] for r in fleet_recs]
            assert "spawn" in actions
            assert "death" in actions
            assert "restart_scheduled" in actions
            # The replacement actually comes back.
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()),
                timeout_s=20.0)
            assert worker.pid != first_pid
        finally:
            _obs.install(previous)
            log.close()
            fleet.stop()

    def test_ejection_dumps_flight(self, tmp_path):
        log = _obs.EventLog(str(tmp_path / "fleet.jsonl"))
        previous = _obs.install(log)
        fleet = _fast_fleet(tmp_path, n=1, eject_after=2)
        worker = fleet.workers[0]
        fleet._spawn(worker)
        try:
            assert _tick_until(
                fleet, lambda: any(w.ready
                                   for w in fleet.pool.workers()))
            # Router-reported forward failures push the worker over the
            # eject threshold on the next tick.
            fleet.pool.report_failure("w0", "http 500")
            fleet.pool.report_failure("w0", "http 500")
            assert _tick_until(fleet, lambda: worker.restarts >= 1)
            flights = sorted(tmp_path.glob("flight_*.jsonl"))
            assert flights
            records = [json.loads(line) for f in flights
                       for line in f.read_text().splitlines()]
            assert any(r.get("reason", "").startswith("worker_eject:w0")
                       for r in records)
            eject = [r for r in records if r.get("event") == "fleet"
                     and r.get("action") == "eject"]
            assert eject and eject[0]["failures"] >= 2
        finally:
            _obs.install(previous)
            log.close()
            fleet.stop()
