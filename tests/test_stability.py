"""Numerical-stability envelope, re-hosting python/test.py:57-79.

Grid: input scale in {1e-5, 1, 1e5} x temperature in {0.01, 0.07, 1.0} at
B=128 (2N), D=256 — loss and gradients must be finite everywhere. Extended
beyond the reference with bf16 and non-normalized inputs.
"""

import jax
import jax.numpy as jnp
import pytest

from ntxent_tpu.ops import oracle
from ntxent_tpu.ops.ntxent_pallas import ntxent_loss_fused

from conftest import make_embeddings

SCALES = [1e-5, 1.0, 1e5]
TEMPS = [0.01, 0.07, 1.0]


@pytest.mark.parametrize("scale", SCALES)
@pytest.mark.parametrize("t", TEMPS)
def test_stability_grid(rng, scale, t):
    # Normalized embeddings scaled afterwards, as in python/test.py:64-66.
    z = make_embeddings(rng, 128, 256) * scale
    loss, grad = jax.value_and_grad(lambda zz: ntxent_loss_fused(zz, t))(z)
    assert bool(jnp.isfinite(loss)), f"NaN/Inf loss at scale={scale}, T={t}"
    assert bool(jnp.all(jnp.isfinite(grad))), f"NaN/Inf grad at scale={scale}, T={t}"
    l_ref = oracle.ntxent_loss(z, t)
    assert bool(jnp.isfinite(l_ref))


@pytest.mark.parametrize("t", TEMPS)
def test_stability_bf16(rng, t):
    z = make_embeddings(rng, 128, 256, dtype=jnp.bfloat16)
    loss = ntxent_loss_fused(z, t)
    assert bool(jnp.isfinite(loss))


def test_extreme_logit_range(rng):
    """Rows with one dominating similarity: online softmax must not overflow."""
    z = make_embeddings(rng, 64, 32)
    loss = ntxent_loss_fused(z, 1e-4)  # logits up to ~1e4
    assert bool(jnp.isfinite(loss))


@pytest.mark.parametrize("scale", SCALES)
@pytest.mark.parametrize("t", TEMPS)
def test_stability_grid_triangular(rng, scale, t):
    """Same envelope for the upper-triangle kernels: the transposed
    online-softmax folds and the shared-accumulator backward must stay
    finite over the whole reference grid."""
    z = make_embeddings(rng, 128, 256) * scale
    loss, grad = jax.value_and_grad(
        lambda zz: ntxent_loss_fused(zz, t, triangular=True))(z)
    assert bool(jnp.isfinite(loss)), f"loss at scale={scale}, T={t}"
    assert bool(jnp.all(jnp.isfinite(grad))), f"grad at scale={scale}, T={t}"


@pytest.mark.parametrize("scale", SCALES)
@pytest.mark.parametrize("t", TEMPS)
def test_stability_grid_infonce_dual(rng, scale, t):
    """Dual-direction InfoNCE kernels over the same envelope, gradients
    for both modalities and the logit scale included."""
    from ntxent_tpu.ops.infonce_pallas import info_nce_fused

    k1, k2 = jax.random.split(rng)
    za = make_embeddings(k1, 128, 256) * scale
    zb = make_embeddings(k2, 128, 256) * scale
    s0 = jnp.asarray(1.0 / t)
    loss, grads = jax.value_and_grad(
        lambda a, b, s: info_nce_fused(a, b, scale=s),
        argnums=(0, 1, 2))(za, zb, s0)
    assert bool(jnp.isfinite(loss)), f"loss at scale={scale}, T={t}"
    for g in grads:
        assert bool(jnp.all(jnp.isfinite(g))), f"grad at scale={scale}, T={t}"
