"""Numerical-stability envelope, re-hosting python/test.py:57-79.

Grid: input scale in {1e-5, 1, 1e5} x temperature in {0.01, 0.07, 1.0} at
B=128 (2N), D=256 — loss and gradients must be finite everywhere. Extended
beyond the reference with bf16 and non-normalized inputs.
"""

import jax
import jax.numpy as jnp
import pytest

from ntxent_tpu.ops import oracle
from ntxent_tpu.ops.ntxent_pallas import ntxent_loss_fused

from conftest import make_embeddings

SCALES = [1e-5, 1.0, 1e5]
TEMPS = [0.01, 0.07, 1.0]


@pytest.mark.parametrize("scale", SCALES)
@pytest.mark.parametrize("t", TEMPS)
def test_stability_grid(rng, scale, t):
    # Normalized embeddings scaled afterwards, as in python/test.py:64-66.
    z = make_embeddings(rng, 128, 256) * scale
    loss, grad = jax.value_and_grad(lambda zz: ntxent_loss_fused(zz, t))(z)
    assert bool(jnp.isfinite(loss)), f"NaN/Inf loss at scale={scale}, T={t}"
    assert bool(jnp.all(jnp.isfinite(grad))), f"NaN/Inf grad at scale={scale}, T={t}"
    l_ref = oracle.ntxent_loss(z, t)
    assert bool(jnp.isfinite(l_ref))


@pytest.mark.parametrize("t", TEMPS)
def test_stability_bf16(rng, t):
    z = make_embeddings(rng, 128, 256, dtype=jnp.bfloat16)
    loss = ntxent_loss_fused(z, t)
    assert bool(jnp.isfinite(loss))


def test_extreme_logit_range(rng):
    """Rows with one dominating similarity: online softmax must not overflow."""
    z = make_embeddings(rng, 64, 32)
    loss = ntxent_loss_fused(z, 1e-4)  # logits up to ~1e4
    assert bool(jnp.isfinite(loss))
