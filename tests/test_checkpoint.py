"""Checkpoint/resume roundtrip (SURVEY.md §5.4: absent in the reference)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ntxent_tpu.models import ResNet, SimCLRModel
from ntxent_tpu.training import (
    CheckpointManager,
    TrainerConfig,
    create_train_state,
)

TinyEnc = functools.partial(ResNet, stage_sizes=(1,), small_images=True,
                            dtype=jnp.float32)


def test_checkpoint_roundtrip(tmp_path, rng):
    model = SimCLRModel(encoder=TinyEnc, proj_hidden_dim=16, proj_dim=8)
    cfg = TrainerConfig(batch_size=4, total_steps=10, warmup_steps=1)
    state = create_train_state(model, rng, (1, 32, 32, 3), cfg)

    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=2)
    assert mgr.latest_step() is None
    assert mgr.save(0, state)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 0

    # Restore into a freshly-initialized template with different values.
    other = create_train_state(model, jax.random.PRNGKey(99),
                               (1, 32, 32, 3), cfg)
    restored = mgr.restore(other)
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()


@pytest.mark.slow
def test_elastic_resume_across_mesh_sizes(tmp_path, rng):
    """Elastic recovery (SURVEY.md §5.3): a checkpoint written while
    training on an 8-device data mesh restores onto a 4-device mesh and —
    with the same global batch — continues the exact loss curve of the
    uninterrupted 8-device run. Params/opt-state are replicated and the
    model's cross-replica BatchNorm syncs both moments over the axis, so
    the global computation is device-count-invariant by construction;
    this test pins that invariant through a save/restore boundary.
    """
    from ntxent_tpu.parallel import create_mesh, replicate_state
    from ntxent_tpu.training import make_sharded_train_step, shard_batch

    model = SimCLRModel(
        encoder=functools.partial(ResNet, stage_sizes=(1,),
                                  small_images=True, dtype=jnp.float32,
                                  axis_name="data"),
        proj_hidden_dim=16, proj_dim=8, axis_name="data")
    cfg = TrainerConfig(batch_size=8, total_steps=10, warmup_steps=1)

    def fresh_state():
        return create_train_state(model, jax.random.PRNGKey(0),
                                  (1, 32, 32, 3), cfg)

    def batch_for(step: int):
        k = jax.random.fold_in(jax.random.PRNGKey(7), step)
        k1, k2 = jax.random.split(k)
        v1 = jax.random.uniform(k1, (8, 32, 32, 3))
        v2 = jax.random.uniform(k2, (8, 32, 32, 3))
        return v1, v2

    mesh8 = create_mesh(axis_names=("data",))
    mesh4 = create_mesh(devices=jax.devices()[:4], axis_names=("data",))
    step8 = make_sharded_train_step(mesh8, temperature=0.1)
    step4 = make_sharded_train_step(mesh4, temperature=0.1)

    # Uninterrupted 8-device run: 4 steps.
    want = []
    state = fresh_state()
    for t in range(4):
        state, m = step8(state, *shard_batch(batch_for(t), mesh8))
        want.append(float(m["loss"]))

    # Interrupted run: 2 steps on 8 devices, checkpoint, resume on 4.
    state = fresh_state()
    for t in range(2):
        state, m = step8(state, *shard_batch(batch_for(t), mesh8))
        assert float(m["loss"]) == pytest.approx(want[t], rel=1e-5)
    mgr = CheckpointManager(tmp_path / "elastic", max_to_keep=1)
    assert mgr.save(2, state, force=True)
    mgr.wait_until_finished()

    # The template must be committed replicated on the TARGET mesh: orbax
    # restores onto the template's sharding, and a fresh (uncommitted)
    # template would land the arrays on one device, which the sharded
    # step then rejects (the bug replicate_state exists to prevent).
    restored = mgr.restore(replicate_state(fresh_state(), mesh4))
    mgr.close()
    for t in range(2, 4):
        restored, m = step4(restored, *shard_batch(batch_for(t), mesh4))
        assert float(m["loss"]) == pytest.approx(want[t], rel=1e-5), (
            f"step {t}: elastic-resumed loss diverged")


@pytest.mark.slow
def test_fsdp_elastic_resume_across_mesh_sizes(tmp_path, rng):
    """Elastic recovery for ZeRO-3 (round 4): a checkpoint written from an
    8-device FSDP mesh restores onto a 4-device FSDP mesh — different
    PartitionSpecs per leaf (the shape-driven rule keys on axis size), so
    orbax must reshard on restore.

    Slow tier (round 5 fast-floor budget, VERDICT r4 #9): two FSDP mesh
    compiles + orbax roundtrip is ~1 min of the fast tier; the fast tier
    keeps checkpoint_roundtrip and the FSDP equality tests.

    What this pins: (a) resharding moves bytes without changing them —
    every restored leaf equals its saved value bitwise; (b) the first
    post-restore step on the smaller mesh reproduces the 8-device loss to
    arithmetic noise; (c) training continues (finite losses). It does NOT
    pin the longer curve: GSPMD partitions matmuls differently at
    different mesh sizes, and the ~1e-7 reduction-order noise amplifies
    chaotically through LARS once warmup ends (measured: a from-scratch
    4-device run matches the 8-device run to 1e-7 for 3 steps, then
    diverges 0.8% at step 4 — with the restore machinery verified
    bit-exact by an 8->8 control).
    """
    from ntxent_tpu.parallel import (
        create_mesh,
        make_fsdp_train_step,
        shard_train_state_fsdp,
    )

    model = SimCLRModel(
        encoder=functools.partial(ResNet, stage_sizes=(1,),
                                  small_images=True, dtype=jnp.float32),
        proj_hidden_dim=16, proj_dim=8)
    cfg = TrainerConfig(batch_size=8, total_steps=10, warmup_steps=1)

    def fresh_state():
        return create_train_state(model, jax.random.PRNGKey(0),
                                  (1, 32, 32, 3), cfg)

    def batch_for(step: int):
        k = jax.random.fold_in(jax.random.PRNGKey(7), step)
        k1, k2 = jax.random.split(k)
        return (jax.random.uniform(k1, (8, 32, 32, 3)),
                jax.random.uniform(k2, (8, 32, 32, 3)))

    mesh8 = create_mesh(axis_names=("data",))
    mesh4 = create_mesh(devices=jax.devices()[:4], axis_names=("data",))
    step8 = make_fsdp_train_step(mesh8, temperature=0.1)
    step4 = make_fsdp_train_step(mesh4, temperature=0.1)

    want = []
    state = shard_train_state_fsdp(fresh_state(), mesh8)
    for t in range(3):
        state, m = step8(state, *batch_for(t))
        want.append(float(m["loss"]))

    state = shard_train_state_fsdp(fresh_state(), mesh8)
    for t in range(2):
        state, m = step8(state, *batch_for(t))
    saved_params = jax.device_get(state.params)
    mgr = CheckpointManager(tmp_path / "fsdp_elastic", max_to_keep=1)
    assert mgr.save(2, state, force=True)
    mgr.wait_until_finished()

    # Restore template carries the TARGET mesh's FSDP shardings (axis
    # size 4): orbax reshards each stored global array onto them.
    restored = mgr.restore(shard_train_state_fsdp(fresh_state(), mesh4))
    mgr.close()
    # (a) resharding is byte-exact
    for want_leaf, got_leaf in zip(
            jax.tree_util.tree_leaves(saved_params),
            jax.tree_util.tree_leaves(jax.device_get(restored.params))):
        np.testing.assert_array_equal(got_leaf, want_leaf)
    assert int(restored.step) == 2
    # (b) first post-restore step matches to arithmetic noise
    restored, m = step4(restored, *batch_for(2))
    assert float(m["loss"]) == pytest.approx(want[2], rel=1e-4), (
        "first post-restore FSDP step diverged beyond arithmetic noise")
    # (c) training continues
    restored, m = step4(restored, *batch_for(3))
    assert jnp.isfinite(m["loss"])
