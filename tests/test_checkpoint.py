"""Checkpoint/resume roundtrip (SURVEY.md §5.4: absent in the reference)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ntxent_tpu.models import ResNet, SimCLRModel
from ntxent_tpu.training import (
    CheckpointManager,
    TrainerConfig,
    create_train_state,
)

TinyEnc = functools.partial(ResNet, stage_sizes=(1,), small_images=True,
                            dtype=jnp.float32)


def test_checkpoint_roundtrip(tmp_path, rng):
    model = SimCLRModel(encoder=TinyEnc, proj_hidden_dim=16, proj_dim=8)
    cfg = TrainerConfig(batch_size=4, total_steps=10, warmup_steps=1)
    state = create_train_state(model, rng, (1, 32, 32, 3), cfg)

    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=2)
    assert mgr.latest_step() is None
    assert mgr.save(0, state)
    mgr.wait_until_finished()
    assert mgr.latest_step() == 0

    # Restore into a freshly-initialized template with different values.
    other = create_train_state(model, jax.random.PRNGKey(99),
                               (1, 32, 32, 3), cfg)
    restored = mgr.restore(other)
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    mgr.close()
