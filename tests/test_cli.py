"""ntxent-train CLI: end-to-end launch surface (SURVEY.md §5.6).

The reference shipped no way to launch the training its name promised; the
CLI is that missing runtime config surface. These tests drive it as a user
would: a real process, flags only, checkpoint out the other side.
"""

from __future__ import annotations

import os
import subprocess
import sys

import numpy as np
import pytest

from ntxent_tpu.training.datasets import ArraySource, StreamingLoader


class TestShardedLoader:
    def test_shards_are_disjoint_and_cover_the_global_batch(self):
        data = np.arange(64, dtype=np.float32).reshape(64, 1, 1, 1)
        src = ArraySource(data)
        batches = []
        for idx in range(4):
            loader = StreamingLoader(src, 4, seed=9, num_threads=1,
                                     shard_index=idx, shard_count=4)
            it = iter(loader)
            batches.append([next(it).ravel() for _ in range(4)])
        # Per global batch: 4 shards x 4 rows = 16 distinct samples.
        for b in range(4):
            rows = np.concatenate([batches[s][b] for s in range(4)])
            assert len(np.unique(rows)) == 16
        # An epoch (4 global batches) covers all 64 samples exactly once.
        seen = np.concatenate([batches[s][b] for s in range(4)
                               for b in range(4)])
        assert sorted(seen.tolist()) == list(range(64))

    def test_unsharded_equals_shard_count_one(self):
        data = np.random.RandomState(0).rand(32, 2, 2, 1).astype(np.float32)
        src = ArraySource(data)
        a = iter(StreamingLoader(src, 8, seed=3, num_threads=1))
        b = iter(StreamingLoader(src, 8, seed=3, num_threads=1,
                                 shard_index=0, shard_count=1))
        for _ in range(4):
            np.testing.assert_array_equal(next(a), next(b))

    def test_sharded_ragged_tail_rejected(self):
        src = ArraySource(np.zeros((8, 1, 1, 1), np.float32))
        with pytest.raises(ValueError, match="drop_remainder"):
            StreamingLoader(src, 2, shard_count=2, drop_remainder=False)


@pytest.mark.slow
def test_cli_synthetic_run_checkpoints_and_resumes(tmp_path):
    """Full launch: 8-device CPU mesh, sharded step, checkpoint, resume."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--dataset", "synthetic", "--model", "tiny",
           "--image-size", "8", "--synthetic-samples", "64",
           "--batch", "16", "--steps", "4", "--warmup-steps", "1",
           "--proj-hidden-dim", "16", "--proj-dim", "8",
           "--ckpt-dir", str(ckpt), "--ckpt-every", "100",
           "--log-every", "1", "--platform", "cpu",
           # failure-detection plumbing rides along: a healthy run with a
           # generous stall timeout must behave identically
           "--stall-timeout", "300"]
    first = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                           env=env)
    assert first.returncode == 0, first.stdout + first.stderr
    assert ckpt.exists() and any(ckpt.iterdir())

    # Relaunch with identical flags: must restore step 4 and do nothing.
    second = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                            env=env)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "nothing to do" in (second.stdout + second.stderr)


@pytest.mark.slow
@pytest.mark.parametrize("dcn_slices", [1, 2])
def test_cli_fsdp_run(tmp_path, dcn_slices):
    """--fsdp launch: params/optimizer sharded over the 8-device mesh,
    training proceeds, checkpoints against the SHARDED template, and a
    relaunch restores it; --objective clip rejects the flag. With
    --dcn-slices 2 the same launch builds the hybrid-ZeRO ('dcn', 'data')
    mesh (params on the ICI axis only — ADVICE r3 #1)."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--dataset", "synthetic", "--model", "tiny",
           "--image-size", "8", "--synthetic-samples", "64",
           "--batch", "16", "--steps", "2", "--warmup-steps", "1",
           "--proj-hidden-dim", "16", "--proj-dim", "8",
           "--ckpt-dir", str(ckpt), "--ckpt-every", "100",
           "--log-every", "1", "--platform", "cpu", "--fsdp",
           "--dcn-slices", str(dcn_slices)]
    run = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                         env=env)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "FSDP (ZeRO-3, strip loss) over 8 devices" \
        in (run.stdout + run.stderr)
    if dcn_slices > 1:
        assert "hybrid ZeRO: params sharded over ICI axis 'data' (size 4)" \
            in (run.stdout + run.stderr)
    assert "final: step 2" in (run.stdout + run.stderr)
    assert ckpt.exists() and any(ckpt.iterdir())

    # Relaunch: Orbax must restore the GSPMD-sharded checkpoint into the
    # sharded template (the FSDP analog of the DP replicate-then-restore
    # ordering) and conclude there is nothing left to do.
    second = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                            env=env)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "nothing to do" in (second.stdout + second.stderr)



@pytest.mark.slow
def test_cli_train_then_eval(tmp_path):
    """ntxent-eval restores the ntxent-train checkpoint and reports both
    SSL protocols on the synthetic labeled task."""
    import json

    common = ["--dataset", "synthetic", "--model", "tiny",
              "--image-size", "8", "--proj-hidden-dim", "16",
              "--proj-dim", "8", "--platform", "cpu"]
    _train_then_eval(
        tmp_path / "ckpt", common,
        train_extra=["--synthetic-samples", "64", "--batch", "16",
                     "--steps", "2"],
        eval_extra=["--probe-steps", "50", "--k", "5",
                    "--max-train", "256", "--max-test", "128"])

    # Third protocol on the same checkpoint: end-to-end fine-tuning.
    code = ("import sys; from ntxent_tpu.cli import eval_main;"
            "sys.exit(eval_main(sys.argv[1:]))")
    ev = subprocess.run(
        [sys.executable, "-c", code, "--ckpt-dir", str(tmp_path / "ckpt"),
         "--protocol", "finetune", "--finetune-steps", "20",
         "--batch", "16", "--max-train", "64", "--max-test", "32"] + common,
        capture_output=True, text=True, timeout=600,
        env=_cpu_subprocess_env())
    assert ev.returncode == 0, ev.stdout + ev.stderr
    result = json.loads(ev.stdout.strip().splitlines()[-1])
    assert 0.0 <= result["finetune_top1"] <= 1.0


class TestPairedArrayLoader:
    def _loader(self, **kw):
        from ntxent_tpu.training.datasets import PairedArrayLoader

        rng = np.random.RandomState(0)
        images = rng.rand(32, 4, 4, 3).astype(np.float32)
        tokens = np.arange(32 * 8, dtype=np.int32).reshape(32, 8)
        return PairedArrayLoader(images, tokens, 4, seed=7, **kw)

    def test_pairs_stay_aligned_and_resume_exactly(self):
        a = self._loader()
        for _ in range(3):
            imgs, toks = next(a)
            assert imgs.shape == (4, 4, 4, 3) and toks.shape == (4, 8)
        st = a.state()
        want = [next(a) for _ in range(3)]
        b = self._loader()
        b.restore(st)
        got = [next(b) for _ in range(3)]
        for (wi, wt), (gi, gt) in zip(want, got):
            np.testing.assert_array_equal(wi, gi)
            np.testing.assert_array_equal(wt, gt)

    def test_shards_disjoint(self):
        toks = []
        for i in range(2):
            loader = self._loader(shard_index=i, shard_count=2)
            toks.append(np.concatenate(
                [next(loader)[1][:, 0] for _ in range(2)]))  # 2 batches
        assert not set(toks[0].tolist()) & set(toks[1].tolist())


@pytest.mark.slow
def test_cli_clip_objective_runs_and_resumes(tmp_path):
    """--objective clip: dual-encoder InfoNCE on the 8-device mesh via the
    compiler-partitioned TP step, checkpoint + resume no-op."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--objective", "clip", "--model", "tiny",
           "--dataset", "synthetic", "--synthetic-samples", "64",
           "--image-size", "16", "--vocab-size", "64", "--token-len", "8",
           "--batch", "16", "--steps", "3", "--warmup-steps", "1",
           "--ckpt-dir", str(ckpt), "--ckpt-every", "100",
           "--log-every", "1", "--platform", "cpu"]
    first = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                           env=env)
    assert first.returncode == 0, first.stdout + first.stderr
    assert ckpt.exists() and any(ckpt.iterdir())
    second = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                            env=env)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "nothing to do" in (second.stdout + second.stderr)


def _cpu_subprocess_env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _train_then_eval(ckpt, common, train_extra, eval_extra, env=None,
                     expect_step=2):
    """Shared scaffold: ntxent-train to a checkpoint, ntxent-eval it, and
    return the parsed eval JSON (one copy of the subprocess plumbing for
    every dataset/objective variant)."""
    import json

    env = env or _cpu_subprocess_env()
    train = subprocess.run(
        [sys.executable, "-m", "ntxent_tpu.cli",
         "--warmup-steps", "1", "--ckpt-dir", str(ckpt),
         "--log-every", "1"] + train_extra + common,
        capture_output=True, text=True, timeout=600, env=env)
    assert train.returncode == 0, train.stdout + train.stderr

    code = ("import sys; from ntxent_tpu.cli import eval_main;"
            "sys.exit(eval_main(sys.argv[1:]))")
    ev = subprocess.run(
        [sys.executable, "-c", code, "--ckpt-dir", str(ckpt)]
        + eval_extra + common,
        capture_output=True, text=True, timeout=600, env=env)
    assert ev.returncode == 0, ev.stdout + ev.stderr
    result = json.loads(ev.stdout.strip().splitlines()[-1])
    assert result["step"] == expect_step
    assert 0.0 <= result["knn_top1"] <= 1.0
    if "probe_top1" in result:
        assert 0.0 <= result["probe_top1"] <= 1.0
    return result


def _write_pairs(path, image_size=16, n=32, token_len=8, vocab=64,
                 dtype=np.uint8, bad_token=None):
    rng = np.random.RandomState(0)
    images = rng.rand(n, image_size, image_size, 3)
    images = ((images * 255).astype(np.uint8) if dtype == np.uint8
              else images.astype(np.float32))
    tokens = rng.randint(1, vocab, (n, token_len)).astype(np.int32)
    tokens[:, -1] = 0  # pad sentinel: id 0 must be accepted
    if bad_token is not None:
        tokens[0, 0] = bad_token
    np.savez(path, images=images, tokens=tokens)
    return path


@pytest.mark.slow  # each case pays a subprocess JAX cold start
class TestClipNpzValidation:
    def _run(self, tmp_path, extra, **pairs_kw):
        npz = _write_pairs(tmp_path / "pairs.npz", **pairs_kw)
        cmd = [sys.executable, "-m", "ntxent_tpu.cli",
               "--objective", "clip", "--model", "tiny",
               "--data-dir", str(npz), "--vocab-size", "64",
               "--batch", "8", "--steps", "1", "--warmup-steps", "1",
               "--platform", "cpu"] + extra
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=300, env=_cpu_subprocess_env())

    def test_negative_token_id_rejected(self, tmp_path):
        p = self._run(tmp_path, [], bad_token=-1)
        assert p.returncode != 0
        assert "token ids span" in p.stdout + p.stderr

    def test_out_of_vocab_token_rejected(self, tmp_path):
        p = self._run(tmp_path, [], bad_token=99)
        assert p.returncode != 0
        assert "token ids span" in p.stdout + p.stderr

    def test_explicit_image_size_mismatch_rejected(self, tmp_path):
        p = self._run(tmp_path, ["--image-size", "32"], image_size=16)
        assert p.returncode != 0
        assert "--image-size 32 != images" in p.stdout + p.stderr


@pytest.mark.slow
def test_cli_clip_uint8_npz_trains(tmp_path):
    """Shapes derive from the npz (16px, 8 tokens) and uint8 images train
    after on-device normalization."""
    npz = _write_pairs(tmp_path / "pairs.npz", image_size=16, token_len=8)
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--objective", "clip", "--model", "tiny",
           "--data-dir", str(npz), "--vocab-size", "64",
           "--batch", "8", "--steps", "2", "--warmup-steps", "1",
           "--log-every", "1", "--platform", "cpu"]
    p = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                       env=_cpu_subprocess_env())
    assert p.returncode == 0, p.stdout + p.stderr
    assert "final: step 2" in p.stdout + p.stderr


@pytest.mark.slow
def test_cli_clip_train_then_eval(tmp_path):
    """ntxent-eval --objective clip restores a CLIP checkpoint and
    evaluates the image tower's embeddings on the synthetic task."""
    common = ["--objective", "clip", "--dataset", "synthetic",
              "--model", "tiny", "--image-size", "16",
              "--vocab-size", "64", "--token-len", "8",
              "--platform", "cpu"]
    _train_then_eval(
        tmp_path / "ckpt", common,
        train_extra=["--synthetic-samples", "64", "--batch", "8",
                     "--steps", "2"],
        eval_extra=["--probe-steps", "30", "--k", "5",
                    "--max-train", "128", "--max-test", "64"])

    # Zero-shot protocol on the same checkpoint: classes become
    # pre-tokenized prompt rows, test images classify to the nearest
    # text embedding in the shared space (no training on the task).
    import json

    rng = np.random.RandomState(3)
    toks = rng.randint(1, 64, size=(16, 8)).astype(np.int32)
    toks_path = tmp_path / "class_tokens.npy"
    np.save(toks_path, toks)
    code = ("import sys; from ntxent_tpu.cli import eval_main;"
            "sys.exit(eval_main(sys.argv[1:]))")
    zs = subprocess.run(
        [sys.executable, "-c", code, "--ckpt-dir", str(tmp_path / "ckpt"),
         "--protocol", "zeroshot", "--class-tokens", str(toks_path),
         "--max-test", "64"] + common,
        capture_output=True, text=True, timeout=600,
        env=_cpu_subprocess_env())
    assert zs.returncode == 0, zs.stdout + zs.stderr
    result = json.loads(zs.stdout.strip().splitlines()[-1])
    assert 0.0 <= result["zeroshot_top1"] <= 1.0, result

    # Fail-early contracts: zeroshot without clip / without prompts.
    bad = subprocess.run(
        [sys.executable, "-c", code, "--ckpt-dir", str(tmp_path / "ckpt"),
         "--protocol", "zeroshot", "--class-tokens", str(toks_path),
         "--dataset", "synthetic", "--model", "tiny", "--image-size",
         "16", "--platform", "cpu"],
        capture_output=True, text=True, timeout=120,
        env=_cpu_subprocess_env())
    assert bad.returncode != 0
    assert "needs a CLIP-objective checkpoint" in (bad.stdout + bad.stderr)
    bad2 = subprocess.run(
        [sys.executable, "-c", code, "--ckpt-dir", str(tmp_path / "ckpt"),
         "--protocol", "zeroshot"] + common,
        capture_output=True, text=True, timeout=120,
        env=_cpu_subprocess_env())
    assert bad2.returncode != 0
    assert "requires --class-tokens" in (bad2.stdout + bad2.stderr)


@pytest.mark.slow
def test_cli_imagefolder_train_then_eval(tmp_path):
    """ImageNet-layout folder: train streams decoded images; eval decodes
    only its capped index picks and reports both protocols."""
    from PIL import Image

    root = tmp_path / "data"
    rng = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        (root / cls).mkdir(parents=True)
        for i in range(12):
            arr = (rng.rand(8, 8, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(root / cls / f"{i}.png")

    common = ["--dataset", "imagefolder", "--data-dir", str(root),
              "--model", "tiny", "--image-size", "8",
              "--proj-hidden-dim", "16", "--proj-dim", "8",
              "--platform", "cpu"]
    _train_then_eval(
        tmp_path / "ckpt", common,
        train_extra=["--batch", "8", "--steps", "2"],
        eval_extra=["--probe-steps", "30", "--k", "3",
                    "--max-train", "8", "--max-test", "4"])


@pytest.mark.slow
def test_cli_cifar10_train_then_eval(tmp_path):
    """CIFAR-10 pickle layout end to end: train streams the batches_py
    files, eval reports both protocols on the train/test split."""
    import pickle

    # Fabricated CIFAR-10 layout (same shape the real pickles have).
    d = tmp_path / "cifar-10-batches-py"
    d.mkdir(parents=True)
    rng = np.random.default_rng(1)
    for name in [f"data_batch_{i}" for i in range(1, 6)] + ["test_batch"]:
        blob = {
            b"data": rng.integers(0, 256, (16, 3072), np.uint8),
            b"labels": rng.integers(0, 10, 16).tolist(),
        }
        with open(d / name, "wb") as f:
            pickle.dump(blob, f)

    common = ["--dataset", "cifar10", "--data-dir", str(tmp_path),
              "--model", "tiny", "--proj-hidden-dim", "16",
              "--proj-dim", "8", "--platform", "cpu"]
    _train_then_eval(
        tmp_path / "ckpt", common,
        train_extra=["--batch", "8", "--steps", "2"],
        eval_extra=["--probe-steps", "30", "--k", "3",
                    "--max-train", "32", "--max-test", "8"])


@pytest.mark.slow
@pytest.mark.parametrize("clip_parallel,expect", [
    ("dp", "CLIP FSDP (ZeRO-3, dual loss) over 8 devices"),
    # Megatron + ZeRO-3: TP shards the towers over 'model', the FSDP
    # shape rule shards the remaining dims over 'data'.
    ("tp", "CLIP GSPMD Megatron + ZeRO-3"),
])
def test_cli_clip_fsdp_run(tmp_path, clip_parallel, expect):
    """--objective clip --fsdp (round 4): ZeRO-3 dual towers with the
    fused partial InfoNCE inside the GSPMD step (dp), or composed with
    tensor parallelism (tp), checkpointed against the sharded template
    and restored on relaunch."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--objective", "clip", "--model", "tiny",
           "--dataset", "synthetic", "--synthetic-samples", "64",
           "--image-size", "16", "--vocab-size", "64", "--token-len", "8",
           "--batch", "16", "--steps", "2", "--warmup-steps", "1",
           "--ckpt-dir", str(ckpt), "--ckpt-every", "100",
           "--log-every", "1", "--platform", "cpu", "--fsdp",
           "--clip-parallel", clip_parallel]
    run = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                         env=env)
    assert run.returncode == 0, run.stdout + run.stderr
    assert expect in (run.stdout + run.stderr)
    assert ckpt.exists() and any(ckpt.iterdir())
    second = subprocess.run(cmd, capture_output=True, text=True,
                            timeout=600, env=env)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "nothing to do" in (second.stdout + second.stderr)


@pytest.mark.slow
@pytest.mark.parametrize("fsdp,expect", [
    (False, "SimCLR GSPMD (4, 2) (data, model) mesh"),
    (True, "SimCLR GSPMD Megatron + ZeRO-3"),
])
def test_cli_simclr_tp_run(tmp_path, fsdp, expect):
    """--parallel tp (round 4): the ViT-B/16 SimCLR workload
    (BASELINE.json configs[3]) gets a compiler-partitioned launch
    surface — Megatron sharding over the (data, model) mesh, optionally
    composed with ZeRO-3; checkpoints and resumes."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8").strip()
    repo = os.path.dirname(os.path.dirname(__file__))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    ckpt = tmp_path / "ckpt"
    cmd = [sys.executable, "-m", "ntxent_tpu.cli",
           "--dataset", "synthetic", "--model", "vit_t16",
           "--image-size", "16", "--synthetic-samples", "64",
           "--batch", "16", "--steps", "2", "--warmup-steps", "1",
           "--proj-hidden-dim", "16", "--proj-dim", "8",
           "--ckpt-dir", str(ckpt), "--ckpt-every", "100",
           "--log-every", "1", "--platform", "cpu", "--parallel", "tp"]
    if fsdp:
        cmd.append("--fsdp")
    run = subprocess.run(cmd, capture_output=True, text=True, timeout=600,
                         env=env)
    assert run.returncode == 0, run.stdout + run.stderr
    assert expect in (run.stdout + run.stderr)
    assert "final: step 2" in (run.stdout + run.stderr)
    assert ckpt.exists() and any(ckpt.iterdir())
    second = subprocess.run(cmd, capture_output=True, text=True,
                            timeout=600, env=env)
    assert second.returncode == 0, second.stdout + second.stderr
    assert "nothing to do" in (second.stdout + second.stderr)


def test_labeled_arrays_rejects_one_image_folder(tmp_path):
    """An imagefolder with a single image has an empty odd-index test
    half; _labeled_arrays must exit actionably instead of np.stack([])'s
    opaque ValueError (ADVICE r4 #2)."""
    import argparse

    from PIL import Image

    from ntxent_tpu.cli import _labeled_arrays

    d = tmp_path / "folder" / "cat"
    d.mkdir(parents=True)
    Image.fromarray(np.zeros((16, 16, 3), np.uint8)).save(d / "only.png")
    args = argparse.Namespace(dataset="imagefolder",
                              data_dir=str(tmp_path / "folder"),
                              image_size=16, max_train=0, max_test=0,
                              seed=0)
    with pytest.raises(SystemExit, match="no test images"):
        _labeled_arrays(args, test_only=True)
