"""Reference-compatible API surface (binding_new.cpp:4-21 parity)."""

import jax
import jax.numpy as jnp
import numpy as np

import ntxent_tpu
from ntxent_tpu import backward, check_tensor_core_support, forward, ntxent
from ntxent_tpu.ops import oracle

from conftest import make_embeddings


def test_forward_signature_and_value(rng):
    z = make_embeddings(rng, 64, 128)
    loss = forward(z, 0.07)
    np.testing.assert_allclose(float(loss), float(oracle.ntxent_loss(z, 0.07)),
                               rtol=1e-5)
    # positional use_mixed_precision like the pybind signature
    loss_amp = forward(z, 0.07, True)
    assert bool(jnp.isfinite(loss_amp))


def test_forward_returns_softmax_residual(rng):
    z = make_embeddings(rng, 32, 64)
    loss, softmax = forward(z, 0.07, return_softmax=True)
    assert softmax.shape == (32, 32)
    np.testing.assert_allclose(np.asarray(softmax.sum(axis=1)), 1.0, rtol=1e-5)


def test_forward_compat_mode(rng):
    z = make_embeddings(rng, 16, 32)
    got = forward(z, 0.07, compat="reference")
    np.testing.assert_allclose(float(got),
                               float(oracle.ntxent_loss_compat(z, 0.07)),
                               rtol=1e-6)


def test_backward_exact_grads(rng):
    z = make_embeddings(rng, 32, 64)
    grad_z, grad_logits = backward(z, None, 1.0, 0.07)
    g_ref = jax.grad(lambda zz: oracle.ntxent_loss(zz, 0.07))(z)
    np.testing.assert_allclose(np.asarray(grad_z), np.asarray(g_ref), rtol=1e-4,
                               atol=1e-6)
    assert grad_logits.shape == (32, 32)
    # grad_logits rows sum to ~0 (softmax minus one-hot)
    np.testing.assert_allclose(np.asarray(grad_logits.sum(axis=1)), 0.0,
                               atol=1e-6)


def test_backward_honors_grad_output(rng):
    z = make_embeddings(rng, 16, 32)
    g1, _ = backward(z, None, 1.0, 0.07)
    g2, _ = backward(z, None, 2.0, 0.07)
    np.testing.assert_allclose(np.asarray(g2), 2.0 * np.asarray(g1), rtol=1e-5)


def test_module_object_surface():
    assert callable(ntxent.forward)
    assert callable(ntxent.backward)
    assert isinstance(ntxent.check_tensor_core_support(), bool)
    assert isinstance(check_tensor_core_support(), bool)


def test_package_exports():
    for name in ntxent_tpu.__all__:
        assert hasattr(ntxent_tpu, name), name
