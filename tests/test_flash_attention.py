"""Fused flash-attention kernels vs the full-softmax oracle.

ops/attention_pallas.py runs here in interpret mode (exact, the debug
oracle); tests pin forward AND all three gradients against
attention_oracle, including causal masking, q/k position offsets, row
padding (L not a block multiple), cross-attention lengths, and bf16.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ntxent_tpu.ops import flash_attention
from ntxent_tpu.parallel import attention_oracle


def qkv(rng, lq=24, lk=24, h=2, d=8, b=2):
    kq, kk, kv = jax.random.split(rng, 3)
    return (jax.random.normal(kq, (b, lq, h, d)) * 0.5,
            jax.random.normal(kk, (b, lk, h, d)) * 0.5,
            jax.random.normal(kv, (b, lk, h, d)) * 0.5)


def assert_matches(fn, ref, args, rtol=1e-5, atol=1e-6):
    np.testing.assert_allclose(np.asarray(fn(*args)),
                               np.asarray(ref(*args)), rtol=rtol, atol=atol)
    gf = jax.grad(lambda *a: jnp.sum(fn(*a).astype(jnp.float32) ** 2),
                  argnums=(0, 1, 2))(*args)
    gr = jax.grad(lambda *a: jnp.sum(ref(*a).astype(jnp.float32) ** 2),
                  argnums=(0, 1, 2))(*args)
    for got, want in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_matches_oracle(rng, causal):
    fn = functools.partial(flash_attention, causal=causal,
                           block_q=8, block_kv=128)
    ref = functools.partial(attention_oracle, causal=causal)
    assert_matches(fn, ref, qkv(rng))


def test_padded_rows_and_default_blocks(rng):
    # L = 20 with the default block policy: q pads to the sublane multiple,
    # kv to the lane multiple — padded keys masked, padded queries sliced.
    assert_matches(flash_attention, attention_oracle, qkv(rng, lq=20, lk=20))


def test_cross_attention_lengths(rng):
    # Decoder-style: 16 queries over 40 keys (block-padded on both sides).
    assert_matches(flash_attention, attention_oracle,
                   qkv(rng, lq=16, lk=40))


def test_position_offsets_match_sliced_oracle(rng):
    """q_offset/k_offset reproduce a sequence-sharded causal slice: rows
    [8:16) of a length-24 causal attention, computed standalone —
    forward AND gradients (the backward kernels apply the offsets in
    their own _causal_mask calls, which only this test exercises)."""
    q, k, v = qkv(rng, lq=24, lk=24)
    full = attention_oracle(q, k, v, causal=True)
    part_fn = functools.partial(flash_attention, causal=True,
                                q_offset=8, k_offset=0,
                                block_q=8, block_kv=128)
    part = part_fn(q[:, 8:16], k, v)
    np.testing.assert_allclose(np.asarray(part), np.asarray(full[:, 8:16]),
                               rtol=1e-5, atol=1e-6)

    gp = jax.grad(lambda qs, kk, vv: jnp.sum(part_fn(qs, kk, vv) ** 2),
                  argnums=(0, 1, 2))(q[:, 8:16], k, v)
    go = jax.grad(
        lambda qq, kk, vv: jnp.sum(
            attention_oracle(qq, kk, vv, causal=True)[:, 8:16] ** 2),
        argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(gp[0]), np.asarray(go[0][:, 8:16]),
                               rtol=1e-4, atol=1e-5)
    for got, want in zip(gp[1:], go[1:]):
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=1e-4, atol=1e-5)


def test_bf16_finite_and_close(rng):
    q, k, v = qkv(rng)
    out = flash_attention(q.astype(jnp.bfloat16), k.astype(jnp.bfloat16),
                          v.astype(jnp.bfloat16))
    assert out.dtype == jnp.bfloat16
    assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(attention_oracle(q, k, v)),
                               rtol=5e-2, atol=5e-2)


def test_rejects_bad_shapes(rng):
    q, k, v = qkv(rng)
    with pytest.raises(ValueError, match="expected"):
        flash_attention(q[:, :, :1], k, v)  # mismatched heads


def test_as_long_context_plan(rng):
    """flash_attention slots into LongContextTransformer.attention_fn and
    reproduces the oracle plan's outputs on one parameter tree."""
    from ntxent_tpu.models import LongContextTransformer

    def build(fn):
        return LongContextTransformer(
            vocab_size=32, hidden_dim=16, depth=1, num_heads=2,
            mlp_dim=32, max_len=24, dtype=jnp.float32, attention_fn=fn)

    tokens = jax.random.randint(rng, (2, 24), 0, 32)
    params = build(attention_oracle).init(jax.random.PRNGKey(0), tokens)
    want = build(attention_oracle).apply(params, tokens)
    got = build(functools.partial(flash_attention, block_q=8)).apply(
        params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
