"""Autotuner: candidate filtering, cache, CPU fallback."""
import jax
import jax.numpy as jnp

from ntxent_tpu.ops.autotune import _candidates, autotune_blocks, clear_cache, _CACHE
from ntxent_tpu.ops.blocks import choose_blocks


def test_cpu_falls_back_to_heuristic():
    clear_cache()
    got = autotune_blocks(4096, 4096, 128)
    assert got == choose_blocks(4096, 4096, 128)


def test_candidates_respect_vmem_and_shape():
    cands = list(_candidates(512, 512, 128, 4))
    assert cands, "no candidates for a plain shape"
    assert all(br <= 512 and bc <= 512 for br, bc in cands)
    small = list(_candidates(64, 128, 32, 4))
    assert all(br <= 64 and bc <= 128 for br, bc in small)
