"""Autotuner: candidate filtering, cache, CPU fallback, and the live
measured sweep's decision logic (winner selection, disk persistence,
budget truncation) exercised off-chip with stubbed backend + timer —
the timing ACCURACY of the sweep is asserted on real hardware by
tests/test_tpu_only.py::test_autotune_live_sweep_caches_winner."""
import jax
import jax.numpy as jnp
import pytest

from ntxent_tpu.ops import autotune
from ntxent_tpu.ops.autotune import (
    _CACHE,
    _candidates,
    autotune_blocks,
    clear_cache,
)
from ntxent_tpu.ops.blocks import choose_blocks


def test_cpu_falls_back_to_heuristic():
    clear_cache()
    got = autotune_blocks(4096, 4096, 128)
    assert got == choose_blocks(4096, 4096, 128)


def test_candidates_respect_vmem_and_shape():
    cands = list(_candidates(512, 512, 128, 4))
    assert cands, "no candidates for a plain shape"
    assert all(br <= 512 and bc <= 512 for br, bc in cands)
    small = list(_candidates(64, 128, 32, 4))
    assert all(br <= 64 and bc <= 128 for br, bc in small)


@pytest.fixture()
def sweep_env(monkeypatch, tmp_path):
    """Run the measured-sweep code path on CPU: backend probe says 'tpu',
    the chain timer is a deterministic stub, the disk cache is isolated."""
    clear_cache()
    monkeypatch.setenv("NTXENT_TPU_CACHE", str(tmp_path))
    monkeypatch.setattr(autotune.jax, "default_backend", lambda: "tpu")
    yield tmp_path
    clear_cache()


def test_sweep_picks_fastest_candidate_and_persists(sweep_env, monkeypatch):
    calls = []

    def fake_timer(fn, z, length, spans, with_grad, **kw):
        # Identify the candidate from the closure defaults (loss binds
        # _br/_bc as keyword defaults) and hand (256, 128) the best time.
        br, bc = fn.__defaults__
        calls.append((br, bc))
        return (0.5 if (br, bc) == (256, 128) else 1.0 + br / 1e4), 0.0

    monkeypatch.setattr(autotune, "time_fn_chained", fake_timer)
    best = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    assert best == (256, 128)
    assert len(calls) == len(list(_candidates(512, 512, 64, 4)))
    # Full (untruncated) sweep persists per device kind: a fresh process
    # (cleared in-memory cache, dropped disk mirror) must hit the FILE,
    # not re-measure.
    _CACHE.clear()
    monkeypatch.setattr(autotune, "_DISK_CACHE", None)
    calls.clear()
    again = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    assert again == (256, 128)
    assert calls == [], "disk-cached winner was re-measured"


def test_sweep_truncation_stores_progress_and_converges(sweep_env,
                                                        monkeypatch):
    import json

    grid = list(_candidates(512, 512, 64, 4))

    def slow_timer(fn, z, **kw):
        import time as _t
        _t.sleep(0.05)
        br, bc = fn.__defaults__
        return 1.0 + br / 1e4, 0.0

    monkeypatch.setattr(autotune, "time_fn_chained", slow_timer)
    # Budget only allows ~the first candidate: winner is best-of-partial.
    best = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=0.01)
    assert best in grid
    # The truncated sweep stores a PROGRESS RECORD under the |partial
    # twin key — never a servable vote under the sweep key itself (an
    # old reader scanning served entries must only ever see lists).
    disk = json.loads(autotune.cache_path().read_text())
    partial_keys = [k for k in disk if k.endswith("|partial")]
    assert partial_keys and not any(
        isinstance(disk[k], dict) for k in disk if not k.endswith("|partial"))
    rec = disk[partial_keys[0]]
    assert tuple(rec["blocks"]) == best and rec["measured"]
    n_measured = len(rec["measured"])

    # A later call re-measures (the partial is not served) but SKIPS the
    # already-measured candidates — sweeps partition the grid instead of
    # re-walking the same prefix.
    _CACHE.clear()
    timed = []
    monkeypatch.setattr(
        autotune, "time_fn_chained",
        lambda fn, z, **kw: (timed.append(fn.__defaults__) or (9.0, 0.0)))
    full = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    assert timed, "truncated winner was treated as authoritative"
    # The anchor (prior best-so-far) is re-measured FIRST under this
    # process's conditions — its recorded ms is never compared against
    # fresh timings (the v2 cross-condition lesson) — and every other
    # already-measured candidate is skipped.
    assert tuple(timed[0]) == best
    assert len(timed) == len(grid) - n_measured + 1
    assert not any(tuple(t) in {tuple(c) for c in rec["measured"]}
                   for t in timed[1:])
    # Grid exhausted -> the entry finalizes into a served vote and the
    # progress record is dropped; a fresh process hits the file.
    disk = json.loads(autotune.cache_path().read_text())
    assert not any(k.endswith("|partial") for k in disk)
    _CACHE.clear()
    monkeypatch.setattr(autotune, "_DISK_CACHE", None)
    timed.clear()
    again = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    assert again == full and timed == []


def test_sweep_all_candidates_fail_falls_back(sweep_env, monkeypatch):
    def broken_timer(fn, z, **kw):
        raise RuntimeError("compile failed")

    monkeypatch.setattr(autotune, "time_fn_chained", broken_timer)
    best = autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    assert best == choose_blocks(512, 512, 64)


def test_attention_cpu_falls_back_to_heuristic():
    from ntxent_tpu.ops.attention_pallas import _blocks
    from ntxent_tpu.ops.autotune import autotune_attention_blocks

    clear_cache()
    got = autotune_attention_blocks(4096, 4096, 64, jnp.bfloat16)
    assert got == _blocks(4096, 4096, 64, None, None, 2)


def test_attention_candidates_respect_vmem_and_shape():
    from ntxent_tpu.ops.attention_pallas import attention_working_set_bytes
    from ntxent_tpu.ops.autotune import _attention_candidates
    from ntxent_tpu.ops.blocks import VMEM_BUDGET_BYTES

    cands = list(_attention_candidates(4096, 4096, 64, 2))
    assert cands, "no candidates for a plain long-context shape"
    assert all(attention_working_set_bytes(bq, bk, 64, 2)
               <= VMEM_BUDGET_BYTES for bq, bk in cands)
    small = list(_attention_candidates(64, 128, 64, 2))
    assert all(bq <= 64 and bk <= 128 for bq, bk in small)


def test_attention_sweep_picks_fastest_and_persists(sweep_env, monkeypatch):
    from ntxent_tpu.ops.autotune import autotune_attention_blocks

    calls = []

    def fake_timer(fn, q, length, spans, with_grad, **kw):
        bq, bk = fn.__defaults__
        calls.append((bq, bk))
        return (0.25 if (bq, bk) == (128, 256) else 1.0 + bq / 1e4), 0.0

    monkeypatch.setattr(autotune, "time_fn_chained", fake_timer)
    best = autotune_attention_blocks(1024, 1024, 64, jnp.bfloat16,
                                     length=5, spans=1, budget_s=None)
    assert best == (128, 256)
    assert calls, "sweep never measured"
    # Cached on disk under a DIFFERENT key family than the loss tiles:
    # a fresh process must hit the file, not re-measure.
    _CACHE.clear()
    monkeypatch.setattr(autotune, "_DISK_CACHE", None)
    calls.clear()
    again = autotune_attention_blocks(1024, 1024, 64, jnp.bfloat16,
                                      length=5, spans=1, budget_s=None)
    assert again == (128, 256)
    assert calls == []


def test_every_vote_is_span_amortized(sweep_env, monkeypatch):
    """The v3 protocol fix (BASELINE.md "v3 span-amortized votes"): the
    v2 sweep's short-chain votes were relay-dispatch noise at fast
    shapes and demonstrably pinned a bad attention tile (the 4.11 ms
    1024-causal row). Every vote — loss tiles AND attention tiles — must
    pass min_span_ms >= 400 to time_fn_chained so the chain length is
    grown until the measured span dwarfs the ~64 ms dispatch overhead.
    A regression that drops the kwarg silently reverts to v2."""
    from ntxent_tpu.ops.autotune import autotune_attention_blocks

    spans_seen = []

    def fake_timer(fn, z, length, spans, with_grad, **kw):
        spans_seen.append(kw.get("min_span_ms"))
        return 1.0, 0.0

    monkeypatch.setattr(autotune, "time_fn_chained", fake_timer)
    autotune_blocks(512, 512, 64, length=5, spans=1, budget_s=None)
    autotune_attention_blocks(1024, 1024, 64, jnp.bfloat16,
                              length=5, spans=1, budget_s=None)
    assert spans_seen, "no votes were cast"
    assert all(s is not None and s >= 400.0 for s in spans_seen), \
        f"un-amortized (v2-style) votes present: {spans_seen}"
