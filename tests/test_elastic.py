"""Elastic training (ISSUE 6): topology-portable checkpoints and
shrink/grow restarts.

The crash-safe layer proved bit-exact resume onto an IDENTICAL mesh;
these tests pin the elastic upgrade: every save records its logical
placement (PartitionSpec tree + mesh identity), restore re-places
host-gathered values under whatever mesh the new incarnation built
(``reshard="gather_replace"``), pre-elastic checkpoints keep the old
behavior, the spec-resolver vocabulary in parallel/mesh.py behaves, the
``shrink@K``/``grow@K`` chaos actions drive the supervisor's
topology-rebuild restart path, and ``fit(restore_step=)`` resumes from
an explicit historical step. CPU-cheap (tiny pytrees, one tiny model),
NOT slow-marked — tier-1 keeps the elasticity invariants green;
``scripts/elastic_smoke.sh`` drives the same story end-to-end through
the CLI across real subprocess device-count changes.
"""

import functools
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ntxent_tpu.parallel.mesh import (
    create_mesh,
    match_partition_rules,
    mesh_topology,
    resolve_restore_specs,
    tree_partition_specs,
)
from ntxent_tpu.resilience import FaultInjector, FaultPlan, Supervisor
from ntxent_tpu.resilience.faults import TopologyChange
from ntxent_tpu.training.checkpoint import CheckpointManager, _Snapshot

pytestmark = pytest.mark.elastic

needs_mesh = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs an 8-device mesh")


@pytest.fixture
def mesh8():
    return create_mesh(axis_names=("data",))


@pytest.fixture
def mesh4():
    return create_mesh(devices=jax.devices()[:4], axis_names=("data",))


def sharded_tree(mesh):
    return {
        "params": {
            "w": jax.device_put(jnp.arange(32.0).reshape(8, 4),
                                NamedSharding(mesh, P("data"))),
            "b": jax.device_put(jnp.ones((4,)),
                                NamedSharding(mesh, P())),
        },
        "step": jnp.int32(5),
    }


def host_values(tree):
    return jax.tree.map(np.asarray, tree)


# ---------------------------------------------------------------------------
# Spec vocabulary (parallel/mesh.py)
# ---------------------------------------------------------------------------

@needs_mesh
def test_tree_partition_specs_records_layout_and_mesh(mesh8):
    rec = tree_partition_specs(sharded_tree(mesh8))
    assert rec["specs"]["params/w"] == ["data"]
    assert rec["specs"]["params/b"] == []
    assert rec["mesh"]["device_count"] == 8
    assert rec["mesh"]["axis_names"] == ["data"]
    assert rec["mesh"]["shape"] == [8]
    # JSON-able by construction: the checkpoint sidecar is json.dump'd.
    json.dumps(rec)


@needs_mesh
def test_resolve_restore_specs_across_meshes(mesh8, mesh4):
    tree = sharded_tree(mesh8)
    rec = tree_partition_specs(tree)
    specs = resolve_restore_specs(rec, mesh4, host_values(tree))
    assert specs["params"]["w"] == P("data")
    assert specs["params"]["b"] == P()
    assert specs["step"] == P()


@needs_mesh
def test_resolve_restore_specs_falls_back_toward_replication(mesh8):
    """A recorded axis the new mesh lacks, or a dim the new axis size no
    longer divides, resolves to replicated for that dim — never a crash."""
    tree = {"w": jax.device_put(jnp.ones((8, 4)),
                                NamedSharding(mesh8, P("data", None)))}
    rec = tree_partition_specs(tree)
    other_axis = create_mesh(devices=jax.devices()[:4],
                             axis_names=("model",))
    specs = resolve_restore_specs(rec, other_axis, host_values(tree))
    assert specs["w"] == P(None, None)
    mesh3 = create_mesh(devices=jax.devices()[:3], axis_names=("data",))
    specs3 = resolve_restore_specs(rec, mesh3, host_values(tree))
    assert specs3["w"] == P(None, None)  # 8 % 3 != 0


@needs_mesh
def test_match_partition_rules(mesh8):
    tree = {"dense": {"kernel": jnp.ones((8, 4)),
                      "bias": jnp.ones((4,)),
                      "scale": jnp.ones(())},
            "head": {"kernel": jnp.ones((4, 2))}}
    specs = match_partition_rules(
        [("dense/kernel", P("data", None)), (".*", P())], tree)
    assert specs["dense"]["kernel"] == P("data", None)
    assert specs["head"]["kernel"] == P()
    assert specs["dense"]["scale"] == P()  # scalars never partitioned
    with pytest.raises(ValueError, match="no partition rule"):
        match_partition_rules([("dense/kernel", P())], tree)


@needs_mesh
def test_mesh_topology_identity(mesh8, mesh4):
    assert mesh_topology(mesh8) != mesh_topology(mesh4)
    assert mesh_topology(mesh8) == mesh_topology(
        create_mesh(axis_names=("data",)))


# ---------------------------------------------------------------------------
# Topology-portable checkpoints (training/checkpoint.py)
# ---------------------------------------------------------------------------

@needs_mesh
def test_topology_sidecar_round_trip(tmp_path, mesh8):
    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, tree, force=True)
    sidecar = json.load(open(tmp_path / "ckpt" / "5" / "topology.json"))
    assert sidecar == tree_partition_specs(
        jax.tree.map(lambda x: x, tree))
    # The sidecar rides the CRC manifest like every other payload file.
    manifest = json.load(open(tmp_path / "ckpt" / "manifests.json"))
    assert "topology.json" in manifest["5"]["files"]


@needs_mesh
def test_restore_onto_smaller_mesh_resharding(tmp_path, mesh8, mesh4):
    """A checkpoint taken on 8 devices restores onto 4: identical
    (host-gathered) values, placed under the NEW mesh's NamedSharding,
    with the reshard counter moving."""
    from ntxent_tpu.obs.registry import default_registry

    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, tree, force=True)

    template = jax.tree.map(jnp.zeros_like, host_values(tree))
    template = {
        "params": {
            "w": jax.device_put(template["params"]["w"],
                                NamedSharding(mesh4, P("data"))),
            "b": jax.device_put(template["params"]["b"],
                                NamedSharding(mesh4, P())),
        },
        "step": template["step"],
    }
    before = default_registry().counter(
        "checkpoint_reshard_total", "").value
    out = CheckpointManager(tmp_path / "ckpt").restore(template)
    after = default_registry().counter("checkpoint_reshard_total", "").value
    assert after == before + 1
    assert out["params"]["w"].sharding.mesh.size == 4
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.arange(32.0).reshape(8, 4))
    np.testing.assert_array_equal(np.asarray(out["params"]["b"]),
                                  np.ones((4,)))


@needs_mesh
def test_restore_uncommitted_template_uses_recorded_specs(tmp_path, mesh8,
                                                          mesh4):
    """With an uncommitted template and an explicit ``mesh=``, the
    RECORDED logical specs decide placement on the new mesh — the
    match_partition_rules/shard-fn restore path."""
    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, tree, force=True)
    template = host_values(tree)
    out = CheckpointManager(tmp_path / "ckpt").restore(template, mesh=mesh4)
    w = out["params"]["w"]
    assert isinstance(w.sharding, NamedSharding)
    assert w.sharding.mesh.size == 4
    assert w.sharding.spec == P("data")
    np.testing.assert_array_equal(np.asarray(w),
                                  np.arange(32.0).reshape(8, 4))


@needs_mesh
def test_pre_elastic_checkpoint_restores_with_warning(tmp_path, mesh8,
                                                      caplog):
    """A checkpoint with NO topology sidecar (pre-elastic save) still
    restores onto a matching mesh with the old template-placement
    behavior — a warning, never a crash."""
    from flax import serialization as flax_ser

    tree = sharded_tree(mesh8)
    snap = _Snapshot(
        jax.tree.map(np.array, flax_ser.to_state_dict(tree)), None)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, snap, force=True)
    assert not (tmp_path / "ckpt" / "5" / "topology.json").exists()

    template = jax.tree.map(jnp.zeros_like, sharded_tree(mesh8))
    with caplog.at_level("WARNING"):
        out = CheckpointManager(tmp_path / "ckpt").restore(template)
    assert any("pre-elastic" in rec.message for rec in caplog.records)
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.arange(32.0).reshape(8, 4))
    # Template placement preserved exactly (no behavior change).
    assert out["params"]["w"].sharding == template["params"]["w"].sharding


@needs_mesh
def test_uncommitted_template_same_host_is_not_a_reshard(tmp_path, mesh8):
    """An uncommitted template (no NamedSharding leaves — the eval/serve
    restore shape) on an UNCHANGED host must not be stamped as a
    re-shard: ambient shape is unknowable there, and device count alone
    says nothing moved."""
    from ntxent_tpu.obs.registry import default_registry

    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, tree, force=True)
    before = default_registry().counter(
        "checkpoint_reshard_total", "").value
    out = CheckpointManager(tmp_path / "ckpt").restore(host_values(tree))
    assert default_registry().counter(
        "checkpoint_reshard_total", "").value == before
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.arange(32.0).reshape(8, 4))


def test_fit_restore_step_without_dir_fails_loudly():
    from ntxent_tpu.training.trainer import fit

    state, step, data = _tiny_fit_setup()
    with pytest.raises(ValueError, match="restore_step"):
        fit(state, data, step, num_steps=4, checkpoint_dir=None,
            restore_step=2)


@needs_mesh
def test_matching_topology_restore_is_not_a_reshard(tmp_path, mesh8):
    from ntxent_tpu.obs.registry import default_registry

    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(5, tree, force=True)
    before = default_registry().counter(
        "checkpoint_reshard_total", "").value
    out = CheckpointManager(tmp_path / "ckpt").restore(
        jax.tree.map(jnp.zeros_like, tree))
    assert default_registry().counter(
        "checkpoint_reshard_total", "").value == before
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]),
                                  np.arange(32.0).reshape(8, 4))


# ---------------------------------------------------------------------------
# shrink@K / grow@K chaos actions + supervisor topology restarts
# ---------------------------------------------------------------------------

def test_fault_plan_parses_shrink_grow():
    plan = FaultPlan.parse("shrink@5,grow@9,nan@3")
    assert plan.shrink_batches == (5,)
    assert plan.grow_batches == (9,)
    assert not plan.empty()
    with pytest.raises(ValueError, match="shrink"):
        FaultPlan.parse("shrink@zero")


def test_injector_raises_topology_change():
    injector = FaultInjector(FaultPlan.parse("shrink@2,grow@4"))
    batches = iter(injector.wrap_iterator(iter(range(10))))
    assert next(batches) == 0
    with pytest.raises(TopologyChange) as e:
        next(batches)
    assert e.value.action == "shrink" and e.value.batch == 2
    assert next(batches) == 2
    with pytest.raises(TopologyChange) as e:
        next(batches)
    assert e.value.action == "grow"
    assert injector.fired == ["shrink@2", "grow@4"]


def test_supervisor_topology_hook_rebuilds_between_attempts():
    """A TopologyChange attempt triggers the hook BEFORE the next
    attempt, the record carries the action, and the run completes on the
    rebuilt world."""
    calls = []
    world = {"devices": 8}

    class S:
        step = 10

    def run_attempt(attempt, stop_fn, watchdog):
        if attempt == 0:
            assert world["devices"] == 8
            raise TopologyChange("shrink", 5)
        if attempt == 1:
            assert world["devices"] == 4  # hook ran first
            raise TopologyChange("grow", 9)
        assert world["devices"] == 8
        return S(), [{"step": 10}]

    def hook(action):
        calls.append(action)
        world["devices"] = 4 if action == "shrink" else 8

    sup = Supervisor(run_attempt, num_steps=10, max_restarts=3,
                     topology_hook=hook, sleep=lambda _s: None)
    result = sup.run()
    assert result.completed
    assert calls == ["shrink", "grow"]
    assert [r.topology for r in result.records] == ["shrink", "grow", None]


def test_supervisor_topology_without_hook_restarts_unchanged():
    attempts = []

    class S:
        step = 10

    def run_attempt(attempt, stop_fn, watchdog):
        attempts.append(attempt)
        if attempt == 0:
            raise TopologyChange("shrink", 3)
        return S(), []

    sup = Supervisor(run_attempt, num_steps=10, max_restarts=1,
                     sleep=lambda _s: None)
    result = sup.run()
    assert result.completed and attempts == [0, 1]
    assert result.records[0].topology == "shrink"


# ---------------------------------------------------------------------------
# fit(restore_step=): explicit historical resume
# ---------------------------------------------------------------------------

def _tiny_fit_setup():
    from ntxent_tpu.models import ResNet, SimCLRModel
    from ntxent_tpu.training import TrainerConfig, create_train_state
    from ntxent_tpu.training.trainer import make_train_step

    model = SimCLRModel(
        encoder=functools.partial(ResNet, stage_sizes=(1,),
                                  small_images=True, dtype=jnp.float32),
        proj_hidden_dim=16, proj_dim=8)
    cfg = TrainerConfig(batch_size=4, total_steps=8, warmup_steps=1)
    state = create_train_state(model, jax.random.PRNGKey(0),
                               (1, 8, 8, 3), cfg)
    step = make_train_step(temperature=0.1)

    def data_iter():
        k = jax.random.PRNGKey(1)
        i = 0
        while True:
            i += 1
            ka, kb = jax.random.split(jax.random.fold_in(k, i))
            yield (jax.random.uniform(ka, (4, 8, 8, 3)),
                   jax.random.uniform(kb, (4, 8, 8, 3)))

    return state, step, data_iter()


def test_fit_restore_step_resumes_historical(tmp_path):
    from ntxent_tpu.training.checkpoint import CheckpointManager
    from ntxent_tpu.training.trainer import fit

    state, step, data = _tiny_fit_setup()
    state, _ = fit(state, data, step, num_steps=6,
                   checkpoint_dir=str(tmp_path / "ckpt"),
                   checkpoint_every=2, log_every=10,
                   checkpoint_keep_last=None)
    assert int(state.step) == 6

    # Resume from step 2, NOT the newest (6): fit must restore exactly
    # the named step, DELETE the abandoned future (rewind is git-reset —
    # stale steps 4/6 would otherwise swallow the replay's saves and win
    # any crash-mid-replay newest-valid race), and train forward.
    state2, step2, data2 = _tiny_fit_setup()
    state2, history = fit(state2, data2, step2, num_steps=4,
                          checkpoint_dir=str(tmp_path / "ckpt"),
                          checkpoint_every=2, log_every=10,
                          checkpoint_keep_last=None, restore_step=2)
    assert int(state2.step) == 4
    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=None)
    # 1 = the first run's save-immediately step, 2 = the restore point;
    # 6 was rewound away and the REPLAYED 4 was actually persisted.
    assert mgr.all_steps() == [1, 2, 4]
    # The persisted step 4 is the REPLAY's, not the old lineage's: its
    # bytes restore to the replayed state.
    restored = mgr.restore(jax.tree.map(jnp.zeros_like, state2), step=4)
    for a, b in zip(jax.tree.leaves(restored.params),
                    jax.tree.leaves(state2.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@needs_mesh
def test_truncate_after_clears_both_replicas(tmp_path, mesh8):
    """Rewind must clear the MIRROR's future too: a stale future step
    surviving in either replica would win the newest-valid race after a
    crash mid-replay (latest_valid_step consults both)."""
    tree = sharded_tree(mesh8)
    mgr = CheckpointManager(tmp_path / "ckpt", max_to_keep=None,
                            mirror_dir=tmp_path / "mirror")
    for s in (2, 4, 6):
        assert mgr.save(s, tree, force=True)
    deleted = mgr.truncate_after(2)
    assert deleted == [4, 6]
    assert mgr.all_steps() == [2]
    assert mgr.latest_valid_step() == 2  # the mirror can't resurrect 4/6
    mirror = CheckpointManager(tmp_path / "mirror", max_to_keep=None)
    assert mirror.all_steps() == [2]


def test_fit_restore_step_missing_raises(tmp_path):
    from ntxent_tpu.training.trainer import fit

    state, step, data = _tiny_fit_setup()
    (tmp_path / "ckpt").mkdir()
    with pytest.raises(FileNotFoundError):
        fit(state, data, step, num_steps=4,
            checkpoint_dir=str(tmp_path / "ckpt"),
            checkpoint_every=2, restore_step=3)


def test_parse_schedule_single_and_multiprocess_entries():
    from ntxent_tpu.resilience.crashsim import parse_schedule

    assert parse_schedule("8,4,8") == [(8, 1), (4, 1), (8, 1)]
    assert parse_schedule("8, 4x2 ,8") == [(8, 1), (4, 2), (8, 1)]
    with pytest.raises(ValueError, match="DEVICESxPROCESSES"):
        parse_schedule("8,four")
    with pytest.raises(ValueError, match="multiple of processes"):
        parse_schedule("8x3")
    with pytest.raises(ValueError, match="multiple of processes"):
        parse_schedule("0x1")
    with pytest.raises(ValueError, match="empty"):
        parse_schedule(" , ")
