// Native unit tests for the C++ NT-Xent core (no GPU, no GTest dependency).
//
// Covers what the reference's GTest suite attempted
// (/root/reference/tests/test_forward.cpp, test_backward.cpp) — smoke
// positivity/finiteness, batch-size sweep, gradient norm bounds — PLUS the
// checks it lacked entirely (SURVEY.md §4): a closed-form value check and a
// finite-difference gradient check. Unlike the reference's suite, which
// hard-required a physical CUDA device (test_forward.cpp:8-11) and could not
// compile (D5), this runs anywhere.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <random>
#include <vector>

extern "C" {
int ntxent_forward_cpu(const float* z, int64_t two_n, int64_t dim,
                       float temperature, float* loss_out, float* lse_out);
int ntxent_backward_cpu(const float* z, const float* lse, int64_t two_n,
                        int64_t dim, float temperature, float grad_output,
                        float* grad_out);
}

namespace {

int failures = 0;

#define CHECK(cond, msg)                                        \
  do {                                                          \
    if (!(cond)) {                                              \
      std::printf("FAIL %s:%d: %s\n", __FILE__, __LINE__, msg); \
      ++failures;                                               \
    }                                                           \
  } while (0)

std::vector<float> random_embeddings(int64_t rows, int64_t dim,
                                     uint32_t seed) {
  std::mt19937 gen(seed);
  std::normal_distribution<float> dist(0.0f, 1.0f);
  std::vector<float> z(rows * dim);
  for (auto& v : z) v = dist(gen);
  for (int64_t i = 0; i < rows; ++i) {
    float norm = 0.0f;
    for (int64_t k = 0; k < dim; ++k) norm += z[i * dim + k] * z[i * dim + k];
    norm = std::sqrt(std::max(norm, 1e-12f));
    for (int64_t k = 0; k < dim; ++k) z[i * dim + k] /= norm;
  }
  return z;
}

float forward(const std::vector<float>& z, int64_t two_n, int64_t dim,
              float t) {
  float loss = -1.0f;
  int rc = ntxent_forward_cpu(z.data(), two_n, dim, t, &loss, nullptr);
  CHECK(rc == 0, "forward rc");
  return loss;
}

void test_basic_forward() {
  // Smoke parity with BasicForward (test_forward.cpp:19-27): loss > 0, finite.
  auto z = random_embeddings(64, 128, 1);
  float loss = forward(z, 64, 128, 0.07f);
  CHECK(loss > 0.0f, "loss positive");
  CHECK(std::isfinite(loss), "loss finite");
}

void test_batch_sizes() {
  // Mirror of DifferentBatchSizes (test_forward.cpp:40-52).
  for (int64_t b : {16, 32, 64, 128}) {
    auto z = random_embeddings(b, 128, 2);
    float loss = forward(z, b, 128, 0.07f);
    CHECK(std::isfinite(loss) && loss > 0.0f, "batch sweep finite/positive");
  }
}

void test_closed_form_two_pairs() {
  // 2N=4 hand-checkable case: orthonormal pairs. For unit rows with
  // z0.z2 = 1 (identical), z0.z1 = z0.z3 = 0:
  // row0: masked lse over {s01=0, s02=1/T, s03=0}; pos(0)=2 -> s=1/T.
  const float t = 0.5f;
  std::vector<float> z = {
      1, 0,  // z0
      0, 1,  // z1
      1, 0,  // z2 = z0 (its positive)
      0, 1,  // z3 = z1
  };
  float loss = forward(z, 4, 2, t);
  const float inv_t = 1.0f / t;
  // each row: lse = log(exp(inv_t) + 2*exp(0)), pos sim = inv_t
  const float expected = std::log(std::exp(inv_t) + 2.0f) - inv_t;
  CHECK(std::fabs(loss - expected) < 1e-5f, "closed-form value");
}

void test_invalid_arguments() {
  float loss;
  auto z = random_embeddings(8, 4, 3);
  CHECK(ntxent_forward_cpu(nullptr, 8, 4, 0.07f, &loss, nullptr) != 0,
        "null z rejected");
  CHECK(ntxent_forward_cpu(z.data(), 7, 4, 0.07f, &loss, nullptr) != 0,
        "odd rows rejected");
  CHECK(ntxent_forward_cpu(z.data(), 8, 4, -1.0f, &loss, nullptr) != 0,
        "bad temperature rejected");
}

void test_backward_finite_and_norm() {
  // Mirror of BasicBackward + GradientNorm (test_backward.cpp:19-49):
  // finite grads, 0 < ||g|| < 100 at 2N=64, D=128.
  auto z = random_embeddings(64, 128, 4);
  std::vector<float> grad(64 * 128);
  int rc = ntxent_backward_cpu(z.data(), nullptr, 64, 128, 0.07f, 1.0f,
                               grad.data());
  CHECK(rc == 0, "backward rc");
  double norm = 0.0;
  bool finite = true;
  for (float g : grad) {
    finite &= std::isfinite(g);
    norm += static_cast<double>(g) * g;
  }
  norm = std::sqrt(norm);
  CHECK(finite, "grads finite");
  CHECK(norm > 0.0 && norm < 100.0, "grad norm in (0, 100)");
}

void test_backward_finite_difference() {
  // The gradcheck the reference never had (SURVEY.md §2.3-D8).
  const int64_t two_n = 8, dim = 6;
  const float t = 0.2f;
  auto z = random_embeddings(two_n, dim, 5);
  std::vector<float> grad(two_n * dim);
  CHECK(ntxent_backward_cpu(z.data(), nullptr, two_n, dim, t, 1.0f,
                            grad.data()) == 0,
        "backward rc");
  const float eps = 1e-3f;
  const int64_t probes[][2] = {{0, 0}, {3, 2}, {7, 5}};
  for (auto& p : probes) {
    auto zp = z, zm = z;
    zp[p[0] * dim + p[1]] += eps;
    zm[p[0] * dim + p[1]] -= eps;
    float fd = (forward(zp, two_n, dim, t) - forward(zm, two_n, dim, t)) /
               (2 * eps);
    float an = grad[p[0] * dim + p[1]];
    CHECK(std::fabs(fd - an) < 5e-3f * std::max(1.0f, std::fabs(fd)),
          "finite-difference gradient match");
  }
}

void test_grad_output_scaling() {
  // grad_output is honored (the reference ignored it, D8).
  auto z = random_embeddings(16, 8, 6);
  std::vector<float> g1(16 * 8), g3(16 * 8);
  ntxent_backward_cpu(z.data(), nullptr, 16, 8, 0.07f, 1.0f, g1.data());
  ntxent_backward_cpu(z.data(), nullptr, 16, 8, 0.07f, 3.0f, g3.data());
  for (size_t i = 0; i < g1.size(); ++i) {
    CHECK(std::fabs(g3[i] - 3.0f * g1[i]) < 1e-4f, "grad_output scaling");
  }
}

}  // namespace

int main() {
  test_basic_forward();
  test_batch_sizes();
  test_closed_form_two_pairs();
  test_invalid_arguments();
  test_backward_finite_and_norm();
  test_backward_finite_difference();
  test_grad_output_scaling();
  if (failures == 0) {
    std::printf("native tests: ALL PASS\n");
    return 0;
  }
  std::printf("native tests: %d FAILURES\n", failures);
  return 1;
}
