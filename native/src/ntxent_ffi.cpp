// XLA FFI custom-call handlers for the native NT-Xent core.
//
// This is the framework's native XLA entry point (SURVEY.md §7.1): where the
// reference exposed its CUDA host ops to Python through pybind11
// (/root/reference/src/binding_new.cpp:4-21), this library exposes the C++
// core (ntxent_cpu.cpp) to the XLA *runtime itself* as typed FFI custom
// calls. The ops are registered from Python via jax.ffi.register_ffi_target
// (ntxent_tpu/ffi.py) and invoked with jax.ffi.ffi_call — so the native code
// participates in jit programs (fusion boundaries, buffer donation, async
// dispatch) instead of living behind a host-side binding the compiler cannot
// see. Handlers run on the CPU platform; the TPU hot path remains the Pallas
// kernel (ops/ntxent_pallas.py), and tests assert the two agree.

#include <cstdint>

#include "xla/ffi/api/ffi.h"

namespace ffi = xla::ffi;

extern "C" {
int ntxent_forward_cpu(const float* z, int64_t two_n, int64_t dim,
                       float temperature, float* loss_out, float* lse_out);
int ntxent_backward_cpu(const float* z, const float* lse, int64_t two_n,
                        int64_t dim, float temperature, float grad_output,
                        float* grad_out);
}

namespace ntxent_tpu {

// forward(z: f32[2N, D]; temperature) -> (loss: f32[], lse: f32[2N])
// Returns the mean canonical NT-Xent loss plus the O(N) logsumexp residual
// (the residual contract the reference intended but never honored, D9).
static ffi::Error ForwardImpl(ffi::BufferR2<ffi::F32> z, float temperature,
                              ffi::ResultBufferR0<ffi::F32> loss,
                              ffi::ResultBufferR1<ffi::F32> lse) {
  auto dims = z.dimensions();  // rank 2 guaranteed by the BufferR2 binding
  const int64_t two_n = dims[0];
  const int64_t dim = dims[1];
  if (lse->dimensions()[0] != two_n) {
    return ffi::Error::InvalidArgument("lse result must have 2N rows");
  }
  int rc = ntxent_forward_cpu(z.typed_data(), two_n, dim, temperature,
                              loss->typed_data(), lse->typed_data());
  if (rc != 0) {
    return ffi::Error::InvalidArgument(
        "ntxent_forward_cpu rejected its arguments (need even 2N > 0, "
        "D > 0, temperature > 0)");
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(NtxentForwardFfi, ForwardImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR2<ffi::F32>>()
                                  .Attr<float>("temperature")
                                  .Ret<ffi::BufferR0<ffi::F32>>()
                                  .Ret<ffi::BufferR1<ffi::F32>>());

// backward(z: f32[2N, D], lse: f32[2N], g: f32[]; temperature)
//   -> grad_z: f32[2N, D]
// Exact dense cotangent of the mean loss scaled by the upstream scalar g —
// the contract the reference's backward violated (SURVEY.md §2.3-D8).
static ffi::Error BackwardImpl(ffi::BufferR2<ffi::F32> z,
                               ffi::BufferR1<ffi::F32> lse,
                               ffi::BufferR0<ffi::F32> g, float temperature,
                               ffi::ResultBufferR2<ffi::F32> grad) {
  auto dims = z.dimensions();  // rank 2 guaranteed by the BufferR2 binding
  const int64_t two_n = dims[0];
  const int64_t dim = dims[1];
  if (lse.dimensions()[0] != two_n) {
    return ffi::Error::InvalidArgument("lse must have 2N rows");
  }
  int rc = ntxent_backward_cpu(z.typed_data(), lse.typed_data(), two_n, dim,
                               temperature, *g.typed_data(),
                               grad->typed_data());
  if (rc != 0) {
    return ffi::Error::InvalidArgument(
        "ntxent_backward_cpu rejected its arguments");
  }
  return ffi::Error::Success();
}

XLA_FFI_DEFINE_HANDLER_SYMBOL(NtxentBackwardFfi, BackwardImpl,
                              ffi::Ffi::Bind()
                                  .Arg<ffi::BufferR2<ffi::F32>>()
                                  .Arg<ffi::BufferR1<ffi::F32>>()
                                  .Arg<ffi::BufferR0<ffi::F32>>()
                                  .Attr<float>("temperature")
                                  .Ret<ffi::BufferR2<ffi::F32>>());

}  // namespace ntxent_tpu
