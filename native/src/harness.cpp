// Native benchmark harness over the canonical NT-Xent C++ core.
//
// Re-hosts the reference's C++ benchmark protocol
// (/root/reference/src/benchmark.cpp: warmup + 100 timed runs with a full
// sync per iteration, grid B in {32..1024} x D in {64,128,256}, T=0.07,
// mean/std/min/max reporting) against this framework's native host
// implementation — the native-surface counterpart of benchmarks/
// run_benchmarks.py, so the C++ layer has the same measurable contract the
// reference's native layer had. CPU sync is implicit (synchronous calls).

#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <random>
#include <vector>

extern "C" {
int ntxent_forward_cpu(const float* z, int64_t two_n, int64_t dim,
                       float temperature, float* loss_out, float* lse_out);
int ntxent_backward_cpu(const float* z, const float* lse, int64_t two_n,
                        int64_t dim, float temperature, float grad_output,
                        float* grad_out);
int ntxent_native_threads(void);
}

namespace {

struct Stats {
  double mean_ms, std_ms, min_ms, max_ms;
};

std::vector<float> make_embeddings(int64_t rows, int64_t dim, uint32_t seed) {
  std::mt19937 gen(seed);
  std::normal_distribution<float> dist(0.0f, 1.0f);
  std::vector<float> z(rows * dim);
  for (auto& v : z) v = dist(gen);
  for (int64_t i = 0; i < rows; ++i) {
    float norm = 0.0f;
    for (int64_t k = 0; k < dim; ++k) norm += z[i * dim + k] * z[i * dim + k];
    norm = std::sqrt(std::max(norm, 1e-12f));
    for (int64_t k = 0; k < dim; ++k) z[i * dim + k] /= norm;
  }
  return z;
}

template <typename F>
Stats time_runs(F&& fn, int warmup, int runs) {
  for (int i = 0; i < warmup; ++i) fn();
  std::vector<double> ms;
  ms.reserve(runs);
  for (int i = 0; i < runs; ++i) {
    auto t0 = std::chrono::steady_clock::now();
    fn();
    auto t1 = std::chrono::steady_clock::now();
    ms.push_back(std::chrono::duration<double, std::milli>(t1 - t0).count());
  }
  double sum = 0.0, mn = ms[0], mx = ms[0];
  for (double v : ms) {
    sum += v;
    mn = std::min(mn, v);
    mx = std::max(mx, v);
  }
  double mean = sum / ms.size();
  double var = 0.0;
  for (double v : ms) var += (v - mean) * (v - mean);
  return {mean, std::sqrt(var / ms.size()), mn, mx};
}

}  // namespace

int main(int argc, char** argv) {
  const int runs = std::max(1, argc > 1 ? std::atoi(argv[1]) : 100);
  const float t = 0.07f;
  std::printf("ntxent_tpu native harness: %d threads, %d runs/config\n",
              ntxent_native_threads(), runs);
  std::printf("%6s %5s | %10s %8s %8s %8s | %10s\n", "2N", "D", "fwd mean",
              "std", "min", "max", "bwd mean");

  const int64_t grid_b[] = {32, 64, 128, 256, 512, 1024};
  const int64_t grid_d[] = {64, 128, 256};
  for (int64_t b : grid_b) {
    for (int64_t d : grid_d) {
      auto z = make_embeddings(b, d, 42);
      std::vector<float> lse(b), grad(b * d);
      float loss = 0.0f;
      auto fwd = time_runs(
          [&] { ntxent_forward_cpu(z.data(), b, d, t, &loss, lse.data()); },
          1, runs);
      auto bwd = time_runs(
          [&] {
            ntxent_backward_cpu(z.data(), lse.data(), b, d, t, 1.0f,
                                grad.data());
          },
          1, runs);
      std::printf("%6lld %5lld | %10.4f %8.4f %8.4f %8.4f | %10.4f\n",
                  static_cast<long long>(b), static_cast<long long>(d),
                  fwd.mean_ms, fwd.std_ms, fwd.min_ms, fwd.max_ms,
                  bwd.mean_ms);
    }
  }
  return 0;
}
