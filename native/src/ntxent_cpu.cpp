// Native host implementation of canonical NT-Xent (C ABI shared library).
//
// Role in the framework (SURVEY.md §7.1): the reference's native surface is a
// CUDA/C++ host op (+ cuBLAS) behind pybind11 (/root/reference/src/*.cu,
// binding*.cpp). The TPU build's hot path is the Pallas kernel; this file is
// the native-host counterpart: a portable, threaded, blockwise C++
// implementation with the SAME canonical semantics (positives at (i+N) mod
// 2N, diagonal masked) used as (a) a cross-language golden reference the
// Python/Pallas stack is tested against, (b) the compute core of the native
// benchmark harness, and (c) a CPU fallback callable from any host runtime
// via ctypes/dlopen — no Python required.
//
// Design notes (deliberately NOT the reference's): no 2N x 2N matrix is
// materialized (the reference allocated logits + softmax of that size,
// ntxent_kernel.cu:154-158); each row block streams over column blocks with
// an online-softmax fold (running max / running sum), exactly like the
// Pallas kernel's VMEM tiling. Backward recomputes tiles flash-style and
// produces the exact dense gradient (the reference's backward was wrong and
// ignored grad_output; SURVEY.md §2.3-D8).

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

constexpr float kNegInf = -1e30f;

inline int num_threads() {
  unsigned hw = std::thread::hardware_concurrency();
  return hw == 0 ? 1 : static_cast<int>(hw);
}

// Parallel-for over row blocks.
template <typename F>
void parallel_rows(int rows, F&& fn) {
  int nt = std::min(num_threads(), rows);
  if (nt <= 1) {
    fn(0, rows);
    return;
  }
  std::vector<std::thread> pool;
  pool.reserve(nt);
  int chunk = (rows + nt - 1) / nt;
  for (int t = 0; t < nt; ++t) {
    int lo = t * chunk;
    int hi = std::min(rows, lo + chunk);
    if (lo >= hi) break;
    pool.emplace_back([lo, hi, &fn] { fn(lo, hi); });
  }
  for (auto& th : pool) th.join();
}

inline float dot(const float* a, const float* b, int dim) {
  float acc = 0.0f;
  for (int k = 0; k < dim; ++k) acc += a[k] * b[k];
  return acc;
}

}  // namespace

extern "C" {

// Canonical NT-Xent forward.
//   z:    (two_n, dim) row-major embeddings (caller normalizes if desired)
//   loss_out: scalar mean loss
//   lse_out:  optional (two_n) per-row logsumexp residuals (may be null)
// Returns 0 on success, nonzero on invalid arguments.
int ntxent_forward_cpu(const float* z, int64_t two_n, int64_t dim,
                       float temperature, float* loss_out, float* lse_out) {
  if (z == nullptr || loss_out == nullptr || two_n <= 0 || dim <= 0 ||
      (two_n % 2) != 0 || temperature <= 0.0f) {
    return 1;
  }
  const int64_t n = two_n / 2;
  const float inv_t = 1.0f / temperature;

  std::vector<double> partial(two_n, 0.0);
  parallel_rows(static_cast<int>(two_n), [&](int lo, int hi) {
    for (int i = lo; i < hi; ++i) {
      const float* zi = z + static_cast<int64_t>(i) * dim;
      float m = kNegInf;
      float l = 0.0f;
      for (int64_t j = 0; j < two_n; ++j) {
        if (j == i) continue;  // masked diagonal
        float s = dot(zi, z + j * dim, static_cast<int>(dim)) * inv_t;
        if (s > m) {
          l = l * std::exp(m - s) + 1.0f;
          m = s;
        } else {
          l += std::exp(s - m);
        }
      }
      const int64_t pos = (i + n) % two_n;
      const float s_pos =
          dot(zi, z + pos * dim, static_cast<int>(dim)) * inv_t;
      const float lse = m + std::log(l);
      if (lse_out != nullptr) lse_out[i] = lse;
      partial[i] = static_cast<double>(lse) - static_cast<double>(s_pos);
    }
  });

  double total = 0.0;
  for (double p : partial) total += p;
  *loss_out = static_cast<float>(total / static_cast<double>(two_n));
  return 0;
}

// Exact dense gradient of the mean loss w.r.t. z, scaled by grad_output.
//   lse: per-row logsumexp from forward (pass null to recompute internally).
//   grad_out: (two_n, dim), overwritten.
int ntxent_backward_cpu(const float* z, const float* lse, int64_t two_n,
                        int64_t dim, float temperature, float grad_output,
                        float* grad_out) {
  if (z == nullptr || grad_out == nullptr || two_n <= 0 || dim <= 0 ||
      (two_n % 2) != 0 || temperature <= 0.0f) {
    return 1;
  }
  const int64_t n = two_n / 2;
  const float inv_t = 1.0f / temperature;

  std::vector<float> lse_local;
  if (lse == nullptr) {
    lse_local.resize(two_n);
    float loss;
    int rc = ntxent_forward_cpu(z, two_n, dim, temperature, &loss,
                                lse_local.data());
    if (rc != 0) return rc;
    lse = lse_local.data();
  }

  const float scale = grad_output * inv_t / static_cast<float>(two_n);
  // grad_z[a] = scale * sum_b (p[a,b] + p[b,a] - 2*1{b=pos(a)}) z[b]
  // with p[a,b] = exp(s_ab - lse[a]) (s symmetric, diagonal masked).
  parallel_rows(static_cast<int>(two_n), [&](int lo, int hi) {
    for (int a = lo; a < hi; ++a) {
      const float* za = z + static_cast<int64_t>(a) * dim;
      float* ga = grad_out + static_cast<int64_t>(a) * dim;
      std::memset(ga, 0, sizeof(float) * dim);
      const int64_t pos_a = (a + n) % two_n;
      for (int64_t b = 0; b < two_n; ++b) {
        if (b == a) continue;
        const float* zb = z + b * dim;
        const float s = dot(za, zb, static_cast<int>(dim)) * inv_t;
        float w = std::exp(s - lse[a]) + std::exp(s - lse[b]);
        if (b == pos_a) w -= 2.0f;
        w *= scale;
        for (int64_t k = 0; k < dim; ++k) ga[k] += w * zb[k];
      }
    }
  });
  return 0;
}

// Capability probe (native analog of check_tensor_core_support,
// binding_new.cpp:19-20): reports host SIMD/thread facts.
int ntxent_native_threads(void) { return num_threads(); }

const char* ntxent_native_version(void) { return "ntxent_tpu-native-0.1.0"; }

}  // extern "C"
