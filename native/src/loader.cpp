// Threaded batch-gather engine behind the native data loader
// (ntxent_tpu/training/native_loader.py).
//
// Division of labour: Python keeps ALL loading policy — the seeded epoch
// permutation, shard slicing, and exact-resume arithmetic live in ONE
// place (_ShardedShuffle, training/datasets.py) regardless of engine — and
// this engine does the part Python threads do poorly: gathering thousands
// of scattered rows from a memory-mapped store into dense batch buffers on
// a worker pool, keeping `queue_depth` batches ready ahead of the
// consumer. This is the native-DataLoader role the reference delegated to
// torch (its C++ DataLoader workers); here it is a first-class component
// of the framework's own native layer (SURVEY.md §5: aux subsystems).
//
// C ABI (consumed via ctypes, same pattern as ntxent_cpu.cpp):
//   ntx_loader_open(path, offset, n_rows, row_bytes, batch_rows,
//                   num_threads, queue_depth) -> handle | NULL
//   ntx_loader_submit(handle, indices, count, out) -> 0 | -1  (blocking)
//   ntx_loader_next(handle)                   -> rows | -1    (blocking)
//   ntx_loader_outstanding(handle)            -> #batches in flight
//   ntx_loader_close(handle)
//
// submit() enqueues one batch's row indices (count <= batch_rows; a short
// final batch is fine) together with the DESTINATION buffer the caller
// wants the batch gathered into, and blocks while `queue_depth` batches
// are already in flight. Workers gather straight into that buffer — zero
// staging copies; the caller must keep `out` alive and untouched until
// the matching next() returns. next() blocks until the OLDEST submitted
// batch is complete and returns its row count — completion order is
// submission order, whatever order workers finish in. Rows are validated
// against [0, n_rows) at submit time.

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Slot {
  std::vector<int64_t> idx;
  uint8_t* dst = nullptr;  // caller-owned destination (alive until next())
  int remaining = 0;       // gather chunks still outstanding (under mu)
  bool ready = false;
};

// One unit of worker work: rows [lo, hi) of slot `sid`. Batches are split
// into ~num_threads chunks at submit time so a single large batch uses
// the whole pool (intra-batch parallelism), not just one worker — without
// it, effective parallelism would be min(num_threads, queue_depth).
struct Chunk {
  int sid;
  int64_t lo, hi;
};

struct Loader {
  int fd = -1;
  const uint8_t* map = nullptr;
  size_t map_len = 0;
  int64_t offset = 0;
  int64_t n_rows = 0;
  int64_t row_bytes = 0;
  int64_t batch_rows = 0;

  int num_threads = 1;
  std::vector<Slot> slots;
  std::deque<int> free_ids;    // slots available to submit into
  std::deque<Chunk> work;      // gather chunks awaiting a worker
  std::deque<int> order;       // submission order, consumed by next()
  std::mutex mu;
  std::condition_variable cv_work, cv_ready, cv_space, cv_drain;
  std::vector<std::thread> workers;
  int active_calls = 0;  // blocked/running submit()/next() calls
  bool stop = false;
};

// Counts a caller inside submit()/next() so close() can wait for them to
// drain before freeing the Loader — without this, a consumer thread
// blocked in a wait() would wake up inside freed memory.
struct CallGuard {
  Loader* ld;
  explicit CallGuard(Loader* l) : ld(l) {
    std::lock_guard<std::mutex> lk(ld->mu);
    ++ld->active_calls;
  }
  ~CallGuard() {
    {
      std::lock_guard<std::mutex> lk(ld->mu);
      --ld->active_calls;
    }
    ld->cv_drain.notify_all();
  }
};

void worker_main(Loader* ld) {
  for (;;) {
    Chunk c;
    {
      std::unique_lock<std::mutex> lk(ld->mu);
      ld->cv_work.wait(lk, [&] { return ld->stop || !ld->work.empty(); });
      if (ld->stop) return;
      c = ld->work.front();
      ld->work.pop_front();
    }
    Slot& s = ld->slots[c.sid];
    const uint8_t* base = ld->map + ld->offset;
    for (int64_t r = c.lo; r < c.hi; ++r)
      std::memcpy(s.dst + r * ld->row_bytes,
                  base + s.idx[static_cast<size_t>(r)] * ld->row_bytes,
                  static_cast<size_t>(ld->row_bytes));
    bool done;
    {
      std::lock_guard<std::mutex> lk(ld->mu);
      done = (--s.remaining == 0);
      if (done) s.ready = true;
    }
    if (done) ld->cv_ready.notify_all();
  }
}

}  // namespace

extern "C" {

void* ntx_loader_open(const char* path, int64_t offset, int64_t n_rows,
                      int64_t row_bytes, int64_t batch_rows,
                      int32_t num_threads, int32_t queue_depth) {
  if (!path || offset < 0 || n_rows <= 0 || row_bytes <= 0 ||
      batch_rows <= 0 || num_threads <= 0 || queue_depth <= 0)
    return nullptr;
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (::fstat(fd, &st) != 0 ||
      st.st_size < offset + n_rows * row_bytes) {
    ::close(fd);
    return nullptr;
  }
  void* map = ::mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                     MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* ld = new Loader();
  ld->num_threads = num_threads;
  ld->fd = fd;
  ld->map = static_cast<const uint8_t*>(map);
  ld->map_len = static_cast<size_t>(st.st_size);
  ld->offset = offset;
  ld->n_rows = n_rows;
  ld->row_bytes = row_bytes;
  ld->batch_rows = batch_rows;
  ld->slots.resize(static_cast<size_t>(queue_depth));
  for (int i = 0; i < queue_depth; ++i) ld->free_ids.push_back(i);
  ld->workers.reserve(static_cast<size_t>(num_threads));
  for (int i = 0; i < num_threads; ++i)
    ld->workers.emplace_back(worker_main, ld);
  return ld;
}

int ntx_loader_submit(void* h, const int64_t* indices, int64_t count,
                      uint8_t* out) {
  auto* ld = static_cast<Loader*>(h);
  if (!ld || !indices || !out || count <= 0 || count > ld->batch_rows)
    return -1;
  for (int64_t i = 0; i < count; ++i)
    if (indices[i] < 0 || indices[i] >= ld->n_rows) return -1;
  CallGuard guard(ld);
  int sid;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->cv_space.wait(lk, [&] { return ld->stop || !ld->free_ids.empty(); });
    if (ld->stop) return -1;
    sid = ld->free_ids.front();
    ld->free_ids.pop_front();
    Slot& s = ld->slots[sid];
    s.idx.assign(indices, indices + count);
    s.dst = out;
    s.ready = false;
    int64_t chunks = ld->num_threads < count ? ld->num_threads : count;
    int64_t per = (count + chunks - 1) / chunks;
    s.remaining = 0;
    for (int64_t lo = 0; lo < count; lo += per) {
      ld->work.push_back({sid, lo, lo + per < count ? lo + per : count});
      ++s.remaining;
    }
    ld->order.push_back(sid);
  }
  ld->cv_work.notify_all();
  return 0;
}

int64_t ntx_loader_next(void* h) {
  auto* ld = static_cast<Loader*>(h);
  if (!ld) return -1;
  CallGuard guard(ld);
  int64_t rows;
  {
    std::unique_lock<std::mutex> lk(ld->mu);
    if (ld->order.empty()) return -1;  // nothing submitted: caller bug
    int sid = ld->order.front();
    ld->cv_ready.wait(lk, [&] { return ld->stop || ld->slots[sid].ready; });
    if (ld->stop) return -1;
    rows = static_cast<int64_t>(ld->slots[sid].idx.size());
    ld->order.pop_front();
    ld->free_ids.push_back(sid);
  }
  ld->cv_space.notify_one();
  return rows;
}

int64_t ntx_loader_outstanding(void* h) {
  auto* ld = static_cast<Loader*>(h);
  if (!ld) return -1;
  std::lock_guard<std::mutex> lk(ld->mu);
  return static_cast<int64_t>(ld->order.size());
}

void ntx_loader_close(void* h) {
  auto* ld = static_cast<Loader*>(h);
  if (!ld) return;
  {
    std::lock_guard<std::mutex> lk(ld->mu);
    ld->stop = true;
  }
  ld->cv_work.notify_all();
  ld->cv_ready.notify_all();
  ld->cv_space.notify_all();
  {
    // Wait for any caller still blocked in submit()/next() to observe
    // `stop` and leave before the Loader is freed under it.
    std::unique_lock<std::mutex> lk(ld->mu);
    ld->cv_drain.wait(lk, [&] { return ld->active_calls == 0; });
  }
  for (auto& t : ld->workers) t.join();
  ::munmap(const_cast<uint8_t*>(ld->map), ld->map_len);
  ::close(ld->fd);
  delete ld;
}

}  // extern "C"
