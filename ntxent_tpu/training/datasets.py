"""Disk-backed streaming input pipelines (ImageNet layout, CIFAR-10, mmap).

The reference has no data code (SURVEY.md §0.2), but its declared training
configs (BASELINE.json configs[2-4]: ImageNet ResNet-50 / ViT / CLIP) are
unreachable without a loader that streams from disk faster than the device
steps — SURVEY §7.4 ranks input-boundness the #1 MFU risk. Design:

* **Random-access sources** (`ImageFolderSource`, `Cifar10Source`, plain
  arrays / np.memmap): ``len()`` + ``[idx] -> uint8 HWC image``. Decode
  (PIL) and resize happen per index, so any worker pool can drive them.
* **StreamingLoader**: seeded per-epoch shuffle + a bounded thread pool
  decoding ahead of the consumer. Yields contiguous uint8 (B, H, W, C)
  batches. Threads, not processes: decode is PIL/numpy C code that releases
  the GIL, and the arrays go straight to ``jax.device_put`` with no pickling.
* **Checkpointable**: ``state()``/``restore()`` capture (epoch, offset,
  seed) so training resumes mid-epoch without replaying host data
  (trainer.fit wires this up — the fix for round 1's O(steps) fast-forward).
  ``restore()`` also works on an already-iterated pipeline (the generator
  is rebuilt at the restored position) — the in-process-restart path
  ``resilience.Supervisor`` takes after a rollback.
* **Transient-fault tolerant**: pass ``retry_policy``
  (``resilience.RetryPolicy``) and per-item source fetches retry with
  exponential backoff instead of killing the epoch on one flaky
  NFS/network read (chaos coverage: ``resilience.faults`` ``fetch@n``).
* **Device overlap**: `device_prefetch` moves batches onto the device (or a
  sharded mesh layout) ahead of consumption; JAX async dispatch overlaps the
  copy with the running step.
* Optional **grain** backing (`grain_loader`): the same sources are valid
  `grain` random-access data sources, for users who want its worker-process
  machinery; the native path above has no extra dependency.

On-device augmentation stays in training/augment.py — the host only moves
uint8 bytes (4x smaller than f32 over PCIe/DCN).
"""

from __future__ import annotations

import os
import pickle
import threading
from collections.abc import Iterator, Sequence
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import jax
import numpy as np

__all__ = [
    "ImageFolderSource",
    "Cifar10Source",
    "ArraySource",
    "StreamingLoader",
    "TwoViewPipeline",
    "device_prefetch",
    "grain_loader",
    "streaming_two_view_iterator",
]

_IMAGE_EXTS = {".jpeg", ".jpg", ".png", ".bmp", ".ppm", ".webp"}


class ImageFolderSource:
    """ImageNet-layout directory: ``root/<class_name>/<image>``.

    Decodes with PIL at access time: resize shorter side to ``image_size``
    then center-crop (the standard eval geometry; SimCLR's random crop runs
    later, on device). Returns uint8 (H, W, 3).
    """

    def __init__(self, root: str | os.PathLike, image_size: int = 224,
                 class_names: Sequence[str] | None = None):
        self.root = Path(root)
        self.image_size = image_size
        if class_names is None:
            class_names = sorted(
                p.name for p in self.root.iterdir() if p.is_dir())
        if not class_names:
            raise ValueError(f"no class directories under {self.root}")
        self.class_names = list(class_names)
        self.paths: list[Path] = []
        self.labels_list: list[int] = []
        for li, cname in enumerate(self.class_names):
            cdir = self.root / cname
            for p in sorted(cdir.iterdir()):
                if p.suffix.lower() in _IMAGE_EXTS:
                    self.paths.append(p)
                    self.labels_list.append(li)
        if not self.paths:
            raise ValueError(f"no images found under {self.root}")
        self.labels = np.asarray(self.labels_list, np.int32)

    def __len__(self) -> int:
        return len(self.paths)

    def __getitem__(self, idx: int) -> np.ndarray:
        from PIL import Image

        s = self.image_size
        with Image.open(self.paths[idx]) as im:
            im = im.convert("RGB")
            w, h = im.size
            scale = s / min(w, h)
            im = im.resize((max(s, round(w * scale)),
                            max(s, round(h * scale))), Image.BILINEAR)
            w, h = im.size
            left, top = (w - s) // 2, (h - s) // 2
            im = im.crop((left, top, left + s, top + s))
            return np.asarray(im, np.uint8)


class Cifar10Source:
    """CIFAR-10 python-pickle batches (the canonical on-disk layout:
    ``data_batch_1..5`` / ``test_batch`` under ``cifar-10-batches-py``)."""

    def __init__(self, root: str | os.PathLike, train: bool = True):
        root = Path(root)
        if (root / "cifar-10-batches-py").is_dir():
            root = root / "cifar-10-batches-py"
        names = [f"data_batch_{i}" for i in range(1, 6)] if train \
            else ["test_batch"]
        datas, labels = [], []
        for name in names:
            with open(root / name, "rb") as f:
                d = pickle.load(f, encoding="bytes")
            datas.append(d[b"data"])
            labels.extend(d[b"labels"])
        # (N, 3072) row-major CHW -> (N, 32, 32, 3) HWC
        self.images = np.concatenate(datas).reshape(-1, 3, 32, 32) \
            .transpose(0, 2, 3, 1).copy()
        self.labels = np.asarray(labels, np.int32)

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> np.ndarray:
        return self.images[idx]


class ArraySource:
    """Random-access view over an in-memory array or ``np.load(...,
    mmap_mode='r')`` memmap — the zero-decode streaming path: only the pages
    of the rows actually sampled are read from disk."""

    def __init__(self, images, labels=None):
        self.images = images
        self.labels = labels

    def __len__(self) -> int:
        return len(self.images)

    def __getitem__(self, idx: int) -> np.ndarray:
        return np.asarray(self.images[idx])


class _ShardedShuffle:
    """Shared seeded-permutation + shard-slice arithmetic for the
    checkpointable loaders — ONE source of truth for the resume and
    multi-process-sharding math (StreamingLoader, PairedArrayLoader).

    Every process computes the SAME seeded global order (a pure function
    of seed and epoch) and yields only its ``batch_size``-row slice of
    each global batch of ``batch_size * shard_count`` rows: disjoint by
    construction, no coordination needed (the per-rank DataLoader role of
    the reference's implied MPI launch, SURVEY.md §2.2).
    """

    def _init_shuffle(self, n_rows: int, batch_size: int, seed: int,
                      shard_index: int, shard_count: int,
                      drop_remainder: bool = True) -> None:
        if not 0 <= shard_index < shard_count:
            raise ValueError(f"shard {shard_index} not in [0, {shard_count})")
        if shard_count > 1 and not drop_remainder:
            raise ValueError("sharded loading requires drop_remainder=True "
                             "(a ragged tail batch would leave shards with "
                             "unequal row counts)")
        if n_rows < batch_size * shard_count:
            raise ValueError(
                f"source of {n_rows} < global batch "
                f"{batch_size * shard_count}")
        self._n_rows = n_rows
        self.batch_size = batch_size
        self.seed = seed
        self.shard_index = shard_index
        self.shard_count = shard_count
        self.drop_remainder = drop_remainder
        self._epoch = 0
        self._offset = 0  # batches already yielded within the epoch
        self._lock = threading.Lock()

    # -- checkpointable-iterator protocol (trainer.fit looks for these);
    # ONE implementation for every engine (Python, native, paired) so the
    # exact-resume contract cannot drift between them --
    def state(self) -> dict:
        with self._lock:
            return {"epoch": self._epoch, "offset": self._offset,
                    "seed": self.seed}

    def restore(self, state: dict) -> None:
        with self._lock:
            self.seed = int(state["seed"])
            self._epoch = int(state["epoch"])
            self._offset = int(state["offset"])

    def batches_per_epoch(self) -> int:
        rows = self.batch_size * self.shard_count
        n = self._n_rows // rows
        if not self.drop_remainder and self._n_rows % rows:
            n += 1
        return n

    def _epoch_order(self, epoch: int) -> np.ndarray:
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch]))
        return rng.permutation(self._n_rows)

    def _batch_indices(self, order: np.ndarray, bi: int) -> np.ndarray:
        rows = self.batch_size * self.shard_count
        lo = bi * rows + self.shard_index * self.batch_size
        return order[lo:lo + self.batch_size]


class StreamingLoader(_ShardedShuffle):
    """Seeded shuffling batch loader with threaded read-ahead.

    Iterating yields uint8/float (B, H, W, C) numpy batches forever (epoch
    loop). ``state()`` / ``restore()`` give exact mid-epoch resumability:
    the permutation is a pure function of (seed, epoch), so (epoch, offset)
    pins the next batch precisely. ``shard_index``/``shard_count``:
    coordination-free multi-process sharding (see ``_ShardedShuffle``).
    """

    def __init__(self, source, batch_size: int, seed: int = 0,
                 num_threads: int = 8, read_ahead: int = 4,
                 drop_remainder: bool = True,
                 shard_index: int = 0, shard_count: int = 1,
                 retry_policy=None):
        self._init_shuffle(len(source), batch_size, seed, shard_index,
                           shard_count, drop_remainder)
        self.source = source
        self.num_threads = num_threads
        self.read_ahead = max(1, read_ahead)
        self.retry_policy = retry_policy

    def _fetch(self, idx: int) -> np.ndarray:
        """One source read, retried per ``retry_policy`` (runs on the
        pool's worker threads; RetryPolicy.call is thread-safe)."""
        if self.retry_policy is None:
            return self.source[idx]
        return self.retry_policy.call(self.source.__getitem__, idx)

    def __iter__(self) -> Iterator[np.ndarray]:
        # Not a `with` block: a generator abandoned mid-epoch is finalized
        # via GeneratorExit (possibly at interpreter shutdown, where a
        # blocking executor join raises) — shut down without waiting.
        pool = ThreadPoolExecutor(max_workers=self.num_threads)
        try:
            while True:
                with self._lock:
                    epoch, start = self._epoch, self._offset
                order = self._epoch_order(epoch)
                nb = self.batches_per_epoch()
                # Keep `read_ahead` whole batches of per-image decode tasks
                # in flight ahead of the consumer. Tasks are item-level only
                # — a batch-level task that fanned out on the same pool
                # would deadlock once workers < in-flight batches.
                pending: list[list] = []
                bi = start
                while bi < nb or pending:
                    while bi < nb and len(pending) < self.read_ahead:
                        idxs = self._batch_indices(order, bi)
                        pending.append([
                            pool.submit(self._fetch, int(i))
                            for i in idxs])
                        bi += 1
                    batch = np.stack([f.result() for f in pending.pop(0)])
                    with self._lock:
                        self._offset += 1
                    yield batch
                with self._lock:
                    self._epoch += 1
                    self._offset = 0
        finally:
            try:
                pool.shutdown(wait=False, cancel_futures=True)
            except Exception:
                pass


def streaming_two_view_iterator(loader, key: jax.Array, blur: bool = True,
                                sharding=None):
    """(view1, view2) device batches from any batch iterator: uint8 batch ->
    device (optionally sharded) -> on-device two-view SimCLR augmentation.

    The augmentation key is derived from (seed-key, epoch, offset) when the
    loader is checkpointable, so a resumed run reproduces the exact
    augmentation stream of an uninterrupted one.
    """
    import jax.numpy as jnp

    from .augment import augment_batch_pair

    stateful = hasattr(loader, "state")
    it = iter(loader)
    counter = 0
    while True:
        if stateful:
            st = loader.state()
            sub = jax.random.fold_in(
                jax.random.fold_in(key, st["epoch"]), st["offset"])
        else:
            sub = jax.random.fold_in(key, counter)
            counter += 1
        batch = next(it)
        x = jnp.asarray(batch) if sharding is None \
            else jax.device_put(batch, sharding)
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32) / 255.0
        yield augment_batch_pair(sub, x, blur=blur)


class TwoViewPipeline:
    """Checkpointable end-to-end SSL input pipeline: StreamingLoader ->
    device -> two-view augmentation, exposing ``state()``/``restore()`` in
    CONSUMER terms.

    ``state()`` reflects batches the consumer actually pulled, so a resumed
    pipeline replays nothing and skips nothing. The loader's own threaded
    read-ahead provides host overlap; do NOT wrap this in another host-
    thread prefetcher (it would decouple loader position from consumer
    position). ``data.DevicePrefetcher`` IS safe to wrap around it — its
    ``state()`` tags each buffered batch with the consumer position, so
    the exact-resume contract survives device-side read-ahead.
    trainer.fit detects these two methods and checkpoints the state next to
    the model (the fix for round 1's O(steps) fast-forward resume).
    """

    def __init__(self, loader: StreamingLoader, key: jax.Array,
                 blur: bool = True, sharding=None):
        self.loader = loader
        self.key = key
        self.blur = blur
        self.sharding = sharding
        self._gen = None

    def state(self) -> dict:
        return self.loader.state()

    def restore(self, state: dict) -> None:
        # Also valid mid-iteration (the supervisor's in-process restart):
        # the running generator would not see a mid-epoch reposition (the
        # loader re-reads its offset only at epoch boundaries), so drop it
        # and rebuild at the restored position on the next __next__. The
        # abandoned generator's read-ahead pool shuts down on finalize.
        self.loader.restore(state)
        self._gen = None

    def __iter__(self):
        return self

    def __next__(self):
        if self._gen is None:
            self._gen = streaming_two_view_iterator(
                self.loader, self.key, blur=self.blur,
                sharding=self.sharding)
        return next(self._gen)


class PairedArrayLoader(_ShardedShuffle):
    """(images, tokens) paired-batch loader for CLIP-style training, with
    the same checkpointable-iterator protocol as ``StreamingLoader``
    (seeded per-epoch shuffle, ``state()``/``restore()`` exact resume,
    coordination-free multi-process sharding — all via ``_ShardedShuffle``).

    In-memory arrays only: the contrastive text-image workload
    (BASELINE.json configs[4]) feeds from pre-tokenized pairs; for
    disk-resident images compose ``ImageFolderSource`` + your tokenizer
    into arrays first (or use grain).
    """

    def __init__(self, images, tokens, batch_size: int, seed: int = 0,
                 shard_index: int = 0, shard_count: int = 1):
        images = np.asarray(images)
        tokens = np.asarray(tokens)
        if len(images) != len(tokens):
            raise ValueError(f"{len(images)} images vs {len(tokens)} tokens")
        self._init_shuffle(len(images), batch_size, seed, shard_index,
                           shard_count)
        self.images, self.tokens = images, tokens
        self._gen = None

    def restore(self, state: dict) -> None:
        # Valid mid-iteration too (see TwoViewPipeline.restore): the
        # generator reads (epoch, offset) per epoch, so rebuild it at the
        # restored position.
        super().restore(state)
        self._gen = None

    def __next__(self):
        if self._gen is None:
            self._gen = self._generate()
        return next(self._gen)

    def __iter__(self):
        return self

    def _generate(self):
        while True:
            order = self._epoch_order(self._epoch)
            nb = self.batches_per_epoch()
            for bi in range(self._offset, nb):
                idx = self._batch_indices(order, bi)
                self._offset += 1
                yield self.images[idx], self.tokens[idx]
            self._epoch += 1
            self._offset = 0


class GlobalTwoViewPipeline:
    """Multi-process SSL input pipeline: per-process loader shard -> global
    uint8 batch assembly -> two-view augmentation as ONE sharded program.

    Only the raw (usually uint8) bytes cross the host boundary — the
    augmented float32 views are born sharded on device and never come back
    (cf. the module-header bandwidth note). The augmentation key derives
    from (key, epoch, offset) only: a replicated global program requires
    the SAME key on every process, and per-row randomness comes from each
    row's position in the GLOBAL batch, so shards stay decorrelated.
    Exposes the same checkpointable ``state()``/``restore()`` contract as
    ``TwoViewPipeline`` (trainer.fit saves and restores it).

    Works single-process too (where assembly reduces to a device_put), but
    ``TwoViewPipeline`` with a ``sharding`` is the simpler spelling there.
    """

    def __init__(self, loader: StreamingLoader, key: jax.Array, mesh,
                 axis: str = "data", blur: bool = True):
        from jax.sharding import NamedSharding, PartitionSpec

        self.loader = loader
        self.key = key
        self.blur = blur
        self._sharding = NamedSharding(mesh, PartitionSpec(axis))
        self._it = None

    def state(self) -> dict:
        return self.loader.state()

    def restore(self, state: dict) -> None:
        # Valid mid-iteration too (see TwoViewPipeline.restore).
        self.loader.restore(state)
        self._it = None

    def __iter__(self):
        return self

    def __next__(self):
        import jax.numpy as jnp

        from .augment import augment_batch_pair

        if self._it is None:
            self._it = iter(self.loader)
        st = self.loader.state()
        sub = jax.random.fold_in(
            jax.random.fold_in(self.key, st["epoch"]), st["offset"])
        batch = next(self._it)  # this process's rows, host memory
        x = jax.make_array_from_process_local_data(self._sharding, batch)

        def views(k, xx):
            if xx.dtype == jnp.uint8:
                xx = xx.astype(jnp.float32) / 255.0
            return augment_batch_pair(k, xx, blur=self.blur)

        return jax.jit(views)(sub, x)


def device_prefetch(iterator, depth: int = 2, sharding=None):
    """Move batches to device ahead of consumption.

    ``jax.device_put`` is asynchronous: issuing the transfer for batch k+1
    while the step for batch k runs overlaps host->device copy with compute.
    Thin constructor over ``training.data.DevicePrefetcher`` (the full
    pipeline stage: committed-sharding placement, checkpointable-iterator
    passthrough, per-batch fetch/transfer timing for the step timeline).
    """
    from .data import DevicePrefetcher

    return DevicePrefetcher(iterator, depth=depth, sharding=sharding)


def grain_loader(source, batch_size: int, seed: int = 0,
                 worker_count: int = 0, drop_remainder: bool = True):
    """Optional grain-backed equivalent of StreamingLoader.

    Any of the sources above is a valid grain random-access data source
    (``__len__`` + ``__getitem__``). Returns an iterator of (B, H, W, C)
    batches using grain's sampler/worker machinery; import is deferred so
    grain stays an optional dependency.
    """
    import grain.python as grain

    sampler = grain.IndexSampler(
        num_records=len(source),
        shard_options=grain.NoSharding(),
        shuffle=True,
        seed=seed,
    )
    loader = grain.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[grain.Batch(batch_size=batch_size,
                                drop_remainder=drop_remainder)],
        worker_count=worker_count,
    )
    return iter(loader)
