"""SSL evaluation protocol: frozen-feature linear probe and kNN accuracy.

The standard SimCLR measurement loop (Chen et al. 2020 §B.6): freeze the
pretrained encoder, extract features, train a linear classifier (or run a
kNN vote) and report top-1. The reference had no evaluation of any kind
(SURVEY.md §0.2 — no model, no trainer); this completes the training story
its name promised. Everything jits: the probe is one `lax.scan` of adam
steps over replicated feature batches — no host loop per epoch.
"""

from __future__ import annotations

from collections.abc import Callable

import jax
import jax.numpy as jnp
import optax

__all__ = ["extract_features", "linear_probe", "knn_accuracy", "finetune"]


def extract_features(
    apply_features: Callable,
    images: jax.Array,
    batch_size: int = 256,
) -> jax.Array:
    """Frozen-encoder features in jitted batches.

    ``apply_features(x) -> (B, F)`` is the encoder forward (e.g.
    ``lambda x: model.apply(variables, x, train=False, method="features")``).
    The tail partial batch is padded to keep one compiled shape and sliced
    off afterwards.
    """
    n = images.shape[0]
    fn = jax.jit(apply_features)
    outs = []
    for start in range(0, n, batch_size):
        batch = images[start:start + batch_size]
        pad = batch_size - batch.shape[0]
        if pad:
            batch = jnp.pad(batch, ((0, pad),) + ((0, 0),) * (batch.ndim - 1))
        out = fn(batch)
        outs.append(out[:batch_size - pad] if pad else out)
    return jnp.concatenate(outs, axis=0)


def linear_probe(
    train_feats: jax.Array,
    train_labels: jax.Array,
    test_feats: jax.Array,
    test_labels: jax.Array,
    num_classes: int,
    steps: int = 500,
    learning_rate: float = 1e-2,
    weight_decay: float = 1e-4,
    key: jax.Array | None = None,
) -> dict:
    """Train a linear classifier on frozen features; return accuracies.

    Full-batch adam inside one ``lax.scan`` — compiled once, no host loop.
    Features are standardized (train statistics) for conditioning.
    """
    mu = train_feats.mean(axis=0, keepdims=True)
    sd = train_feats.std(axis=0, keepdims=True) + 1e-6
    xtr = (train_feats - mu) / sd
    xte = (test_feats - mu) / sd

    f = xtr.shape[-1]
    if key is None:
        key = jax.random.PRNGKey(0)
    w0 = jax.random.normal(key, (f, num_classes)) * 0.01
    b0 = jnp.zeros((num_classes,))
    tx = optax.adamw(learning_rate, weight_decay=weight_decay)

    # Features/labels enter as jit ARGUMENTS: closure constants would bake
    # the train matrix into the executable and defeat the jit cache.
    @jax.jit
    def run(params, x, y):
        opt_state = tx.init(params)

        def loss_fn(params):
            logits = x @ params[0] + params[1]
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()

        def step(carry, _):
            params, opt_state = carry
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return (optax.apply_updates(params, updates), opt_state), loss

        (params, _), losses = jax.lax.scan(step, (params, opt_state), None,
                                           length=steps)
        return params, losses

    params, losses = run((w0, b0), xtr, train_labels)

    def acc(x, y):
        return float(jnp.mean(jnp.argmax(x @ params[0] + params[1], -1) == y))

    return {
        "train_accuracy": acc(xtr, train_labels),
        "test_accuracy": acc(xte, test_labels),
        "final_loss": float(losses[-1]),
    }


def finetune(
    model,
    variables: dict,
    train_images: jax.Array,
    train_labels: jax.Array,
    test_images: jax.Array,
    test_labels: jax.Array,
    num_classes: int,
    steps: int = 200,
    batch_size: int = 64,
    learning_rate: float = 1e-3,
    key: jax.Array | None = None,
) -> dict:
    """End-to-end fine-tuning evaluation (the SimCLR paper's third
    protocol alongside the linear probe and kNN): attach a fresh linear
    head to the PRETRAINED encoder and train every weight on the labeled
    set, then report top-1.

    The whole run is one jitted ``lax.scan`` of adamw minibatch steps
    (indices pre-sampled host-side and passed as the scan xs); BatchNorm
    statistics update through the scan carry and are used frozen at eval.
    """
    if key is None:
        key = jax.random.PRNGKey(0)
    k_head, k_idx = jax.random.split(key)

    def feats(params, batch_stats, x, train):
        variables_ = {"params": params, "batch_stats": batch_stats}
        if train:
            f, updates = model.apply(variables_, x, train=True,
                                     method="features",
                                     mutable=["batch_stats"])
            return f, updates["batch_stats"]
        return model.apply(variables_, x, train=False,
                           method="features"), batch_stats

    feat_dim = feats(variables["params"], variables["batch_stats"],
                     train_images[:1], False)[0].shape[-1]
    head = (jax.random.normal(k_head, (feat_dim, num_classes)) * 0.01,
            jnp.zeros((num_classes,)))
    params0 = {"encoder": variables["params"], "head": head}

    def _decay_mask(params):
        # Standard SimCLR fine-tune protocol: weight decay applies to the
        # matmul kernels only — BatchNorm/LayerNorm scale+bias and every
        # bias vector are exempt (they are named 'scale'/'bias' in flax;
        # the fresh head is a (W, b) tuple whose index 0 is the matrix).
        def keep(path, _leaf):
            last = path[-1]
            if isinstance(last, jax.tree_util.SequenceKey):
                return last.idx == 0
            return getattr(last, "key", "") == "kernel"

        return jax.tree_util.tree_map_with_path(keep, params)

    tx = optax.adamw(learning_rate, weight_decay=1e-4, mask=_decay_mask)

    n = train_images.shape[0]
    idx = jax.random.randint(k_idx, (steps, min(batch_size, n)), 0, n)

    @jax.jit
    def run(params, batch_stats, xtr, ytr, idx):
        opt_state = tx.init(params)

        def loss_fn(params, batch_stats, x, y):
            f, new_stats = feats(params["encoder"], batch_stats, x, True)
            logits = f @ params["head"][0] + params["head"][1]
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, y).mean()
            return loss, new_stats

        def step(carry, batch_idx):
            params, batch_stats, opt_state = carry
            x, y = xtr[batch_idx], ytr[batch_idx]
            (loss, batch_stats), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, batch_stats, x, y)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return (params, batch_stats, opt_state), loss

        (params, batch_stats, _), losses = jax.lax.scan(
            step, (params, batch_stats, opt_state), idx)
        return params, batch_stats, losses

    params, batch_stats, losses = run(
        params0, variables["batch_stats"], train_images, train_labels, idx)

    @jax.jit
    def predict(x):
        f, _ = feats(params["encoder"], batch_stats, x, False)
        return jnp.argmax(f @ params["head"][0] + params["head"][1], -1)

    def acc(x, y):
        # Batched like extract_features: one full-split forward would put
        # the entire image set (and its activations) on device at once.
        hits = total = 0
        for start in range(0, x.shape[0], batch_size):
            xb, yb = x[start:start + batch_size], y[start:start + batch_size]
            pad = batch_size - xb.shape[0]
            if pad:  # keep one compiled shape for the tail
                xb = jnp.pad(xb, ((0, pad),) + ((0, 0),) * (xb.ndim - 1))
            hits += int(jnp.sum(predict(xb)[:yb.shape[0]] == yb))
            total += yb.shape[0]
        return hits / max(total, 1)

    return {
        "train_accuracy": acc(train_images, train_labels),
        "test_accuracy": acc(test_images, test_labels),
        "final_loss": float(losses[-1]),
    }


def knn_accuracy(
    train_feats: jax.Array,
    train_labels: jax.Array,
    test_feats: jax.Array,
    test_labels: jax.Array,
    k: int = 20,
    temperature: float = 0.07,
) -> float:
    """Weighted-kNN top-1 (the standard SSL monitor; cosine similarity,
    exp(s/T)-weighted votes over the k nearest train features)."""
    num_classes = int(train_labels.max()) + 1  # static for the jit below
    # top_k over (Nte, Ntr) requires k <= Ntr; clamp rather than surface
    # lax.top_k's opaque shape error when the train split is tiny.
    k = min(k, int(train_feats.shape[0]))

    def norm(x):
        return x / jnp.maximum(
            jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)

    @jax.jit
    def run(xtr, ytr, xte, yte):  # arrays as args: cacheable, not constants
        sims = norm(xte) @ norm(xtr).T                     # (Nte, Ntr)
        top_s, top_i = jax.lax.top_k(sims, k)
        votes = jax.nn.one_hot(ytr[top_i], num_classes)
        w = jnp.exp(top_s / temperature)[..., None]
        scores = jnp.sum(votes * w, axis=1)
        return jnp.mean(jnp.argmax(scores, -1) == yte)

    return float(run(train_feats, train_labels, test_feats, test_labels))
