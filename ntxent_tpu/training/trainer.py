"""SimCLR trainer: train state, fused-loss train step, sharded train step.

The training loop the reference promised by name but never contained
(SURVEY.md §0.2). Single-chip path jits model fwd + fused Pallas NT-Xent +
LARS update; the distributed path wraps the same step in ``shard_map`` over
the mesh's data axis: batch sharded, params replicated, embeddings
all-gathered into the fused partial loss (parallel/dist_loss.py), gradients
``psum``-reduced — the all-reduce role the reference assigned to NCCL.

Metrics include steps/sec and MFU accounting (BASELINE.json north star:
>=50% MFU on ResNet-50 at global batch 4096)."""

from __future__ import annotations

import dataclasses
import functools
import logging
import time
from collections.abc import Callable
from typing import Any

import flax
import jax
import jax.numpy as jnp
import optax
from flax.training import train_state
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.ntxent_pallas import ntxent_loss_fused
from ..parallel.dist_loss import (
    local_ntxent_allgather,
    resolve_local_infonce,
    resolve_local_ntxent,
)
from ..parallel.moe import moe_aux_from
from .lars import cosine_warmup_schedule, create_lars, simclr_learning_rate
from ..parallel.mesh import collective_precision, comms_accounting
from ..parallel.mesh import pmean as _pmean_acct
from ..parallel.mesh import quantized_grad_reduce
from ..parallel.mesh import shard_map as _shard_map_compat

logger = logging.getLogger(__name__)

__all__ = ["TrainState", "create_train_state", "make_train_step",
           "make_clip_train_step", "make_sharded_train_step",
           "make_sharded_clip_train_step", "init_error_feedback",
           "measure_comms_overlap", "train_loop", "fit",
           "TrainerConfig", "StepOutcome"]


@dataclasses.dataclass(frozen=True)
class StepOutcome:
    """Host-side record of one completed train step, handed to the
    ``step_guard`` hook of ``train_loop`` (resilience.DivergenceGuard is
    the canonical consumer; any callable taking a StepOutcome works).

    ``ok=False`` means the jitted guard (``make_train_step(guard=True)``)
    found a non-finite loss or grad norm and SKIPPED the update: params /
    optimizer state / BN stats kept their pre-step values while
    ``state.step`` still advanced. ``grad_norm`` is None for steps built
    without the guard (they report no norm).

    ``lag`` is how many steps behind dispatch this outcome was read
    (``train_loop(metrics_lag=1)``: the host drains step N-1's metrics
    while step N runs, so the guard learns about a divergence exactly one
    step late — never missing it, because the jit-side guard already kept
    the bad update out of the params).
    """

    step: int
    loss: float
    grad_norm: float | None
    ok: bool
    lag: int = 0


def _guarded_update(state: TrainState, grads, loss, new_stats=None):
    """Jit-side divergence guard shared by the guarded step factories.

    One cheap reduction (global grad norm) + two isfinite checks decide
    ``ok``; on a bad step every leaf of params/opt_state (and BN stats)
    is selected from the PRE-step state, so a NaN batch can neither move
    the weights nor poison optimizer moments. ``state.step`` always
    increments — skip-batch semantics keep the counter monotone for
    checkpoint cadence and the supervisor (resilience/supervisor.py).
    Returns ``(new_state, metrics)``.
    """
    gnorm = optax.global_norm(grads)
    ok = jnp.isfinite(loss) & jnp.isfinite(gnorm)
    # Zero the grads on a bad step BEFORE the optimizer update: optax
    # transforms (moments, trust ratios) must never see a NaN even though
    # their outputs are discarded below — NaN*0 is NaN, where() is not.
    safe_grads = jax.tree.map(
        lambda g: jnp.where(ok, g, jnp.zeros_like(g)), grads)
    updated = state.apply_gradients(grads=safe_grads)
    keep = functools.partial(jax.tree.map,
                             lambda new, old: jnp.where(ok, new, old))
    updated = updated.replace(
        params=keep(updated.params, state.params),
        opt_state=keep(updated.opt_state, state.opt_state))
    if new_stats is not None:
        updated = updated.replace(
            batch_stats=keep(new_stats, state.batch_stats))
    metrics = {"grad_norm": gnorm, "step_ok": ok}
    return updated, metrics


class TrainState(train_state.TrainState):
    batch_stats: Any = None
    # Error-feedback residual for quantized gradient collectives
    # (ISSUE 12): a pytree shaped like ``params`` with one extra leading
    # axis of size P (the mesh's data-axis group), each device's slice
    # holding ITS local compression error — so the state stays
    # replicated (out_spec P()) while the residual stays per-device
    # (spec P(axis) on the stacked dim). None (the default) on
    # full-precision runs: no structural change anywhere.
    # ``init_error_feedback`` builds it; the sharded step threads it
    # through shard_map as its own operand (like the guard's grad-scale).
    # Checkpoints DROP it by default (slim saves, ISSUE 13 — restore
    # falls back to zero residual via checkpoint._from_bytes_tolerant);
    # CheckpointManager(save_ef_residual=True) opts back in.
    ef_residual: Any = None


@flax.struct.dataclass
class TrainerConfig:
    batch_size: int = 256
    temperature: float = 0.1
    base_lr: float = 0.3
    weight_decay: float = 1e-6
    warmup_steps: int = 100
    total_steps: int = 1000
    # Gradient accumulation: optimizer updates apply every `accum_steps`
    # micro-batches (optax.MultiSteps). NOTE the contrastive semantics:
    # negatives stay within each micro-batch — accumulation scales the
    # optimizer's effective batch, not the loss's negative pool (use the
    # distributed all-gather/ring losses to scale the pool itself).
    accum_steps: int = 1
    # NOTE on rematerialization: remat is a property of the STEP, not the
    # config — pass remat=True to make_train_step/make_sharded_train_step
    # (the CLI's --remat does exactly that). Trades ~1 extra forward of
    # FLOPs for not keeping encoder activations live across the loss.

    @property
    def learning_rate(self) -> float:
        return simclr_learning_rate(self.batch_size, self.base_lr)


def create_train_state(
    model,
    rng: jax.Array,
    input_shape: tuple[int, ...],
    config: TrainerConfig,
    tx: optax.GradientTransformation | None = None,
) -> TrainState:
    variables = model.init(rng, jnp.zeros(input_shape, jnp.float32),
                           train=False)
    params = variables["params"]
    if tx is None:
        schedule = cosine_warmup_schedule(
            config.learning_rate, config.warmup_steps, config.total_steps)
        tx = create_lars(schedule, config.weight_decay, params=params)
        if config.accum_steps > 1:
            tx = optax.MultiSteps(tx, every_k_schedule=config.accum_steps)
    return TrainState.create(
        apply_fn=model.apply, params=params, tx=tx,
        batch_stats=variables.get("batch_stats", flax.core.freeze({})),
    )


def init_error_feedback(state: TrainState, mesh: Mesh,
                        axis: str = "data") -> TrainState:
    """Attach a zero error-feedback residual for quantized gradient
    collectives (``make_sharded_train_step(collective_dtype="int8")``).

    Builds one float32 zeros leaf of shape ``(P,) + param.shape`` per
    parameter (P = the mesh's ``axis`` group size), committed to the
    mesh sharded over the leading axis — the global array is the stack
    of every device's residual, each device holding only its own slice.
    Call after ``replicate_state`` (placement order does not matter,
    but the residual must exist before the first int8 step; a step
    without it falls back to quantization WITHOUT error feedback).

    PERSISTENCE (ISSUE 13): checkpoints DROP the residual by default —
    it is P x the f32 param payload of carry-over compression noise
    that restore resets to zeros on any topology change anyway; the
    tolerant restore path fills the missing field with this function's
    zeros. Runs that want exact same-topology residual resume opt in
    with ``CheckpointManager(save_ef_residual=True)`` /
    ``--ckpt-save-ef``."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    p = 1
    for a in axes:
        p *= mesh.shape[a]
    zeros = jax.tree.map(
        lambda g: jnp.zeros((p,) + jnp.shape(g), jnp.float32),
        state.params)
    placed = jax.device_put(zeros, NamedSharding(mesh, P(axes)))
    return state.replace(ef_residual=placed)


def _apply_two_views(state: TrainState, params, v1, v2, train: bool = True,
                     remat: bool = False, collect_moe_aux: bool = False):
    """Run both views through the model in ONE batched forward (2B on the
    batch axis keeps the MXU fed and BN statistics shared across views).

    ``remat=True`` wraps the forward in ``jax.checkpoint``: encoder
    activations are recomputed during the backward pass instead of held in
    HBM across the loss (TrainerConfig.remat).

    ``collect_moe_aux=True`` also collects the ``intermediates`` sown by
    MoE towers (parallel/moe.py) and returns the summed load-balance aux
    loss as a fourth element (0.0 otherwise).
    """
    both = jnp.concatenate([v1, v2], axis=0)
    variables = {"params": params, "batch_stats": state.batch_stats}
    mutable = ["batch_stats", "intermediates"] if collect_moe_aux \
        else ["batch_stats"]

    def fwd(variables, x):
        return state.apply_fn(variables, x, train=train, mutable=mutable)

    if remat:
        fwd = jax.checkpoint(fwd)
    z, updates = fwd(variables, both)
    n = v1.shape[0]
    aux = moe_aux_from(updates) if collect_moe_aux else 0.0
    return z[:n], z[n:], updates["batch_stats"], aux


def make_train_step(temperature: float = 0.1,
                    use_fused: bool | None = None,
                    remat: bool = False,
                    moe_aux_weight: float = 0.0,
                    guard: bool = False) -> Callable:
    """Single-device train step: fused Pallas loss, donated state.

    ``use_fused=None`` auto-selects: the Pallas kernel where it compiles
    natively (TPU), the jnp oracle elsewhere (identical loss — the tests
    prove it — but interpret-mode Pallas on CPU is ~100x slower and
    measures nothing; same policy as api._loss_fn).
    ``remat`` rematerializes the encoder forward in the backward pass
    (TrainerConfig.remat).
    ``moe_aux_weight > 0`` adds that multiple of the MoE towers'
    load-balance aux loss (Switch uses 1e-2) to the objective and reports
    it under ``metrics["moe_aux"]``.
    ``guard=True`` adds the in-step divergence guard (``_guarded_update``):
    the step takes a trailing ``scale`` operand (gradient multiplier; a
    traced scalar, so the host can back it off without a recompile),
    skips non-finite updates, and reports ``grad_norm``/``step_ok`` —
    pair with ``train_loop(step_guard=resilience.DivergenceGuard(...))``.
    """
    if use_fused is None:
        from ..utils.capability import is_tpu_backend

        use_fused = is_tpu_backend()
    if use_fused:
        loss_impl = ntxent_loss_fused
    else:
        from ..ops.oracle import ntxent_loss as loss_impl
    collect = moe_aux_weight > 0.0

    def _loss_and_grads(state, v1, v2):
        def loss_fn(params):
            z1, z2, new_stats, aux = _apply_two_views(
                state, params, v1, v2, remat=remat, collect_moe_aux=collect)
            z = jnp.concatenate([z1, z2], axis=0)
            loss = loss_impl(z, temperature) + moe_aux_weight * aux
            return loss, (new_stats, aux)

        return jax.value_and_grad(loss_fn, has_aux=True)(state.params)

    if guard:
        # NO donation on the guarded path (unlike the plain step): every
        # output leaf here is a where-select between the updated and the
        # PRE-step value, and XLA:CPU's donation aliasing has been observed
        # to miscompile that pattern — the int32 ``step`` output comes
        # back holding the bit pattern of an ~1.0 float (reproduced
        # deterministically under the full test suite; never without
        # donation). Guarded runs trade one state copy for correctness.
        @jax.jit
        def guarded_step(state: TrainState, v1, v2, scale=1.0):
            (loss, (new_stats, aux)), grads = _loss_and_grads(state, v1, v2)
            grads = jax.tree.map(lambda g: g * scale, grads)
            state, gmetrics = _guarded_update(state, grads, loss, new_stats)
            metrics = {"loss": loss, **gmetrics}
            if collect:
                metrics["moe_aux"] = aux
            return state, metrics

        return guarded_step

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, v1, v2):
        (loss, (new_stats, aux)), grads = _loss_and_grads(state, v1, v2)
        state = state.apply_gradients(grads=grads)
        state = state.replace(batch_stats=new_stats)
        metrics = {"loss": loss}
        if collect:
            metrics["moe_aux"] = aux
        return state, metrics

    return train_step


def _clip_towers(state, remat: bool, collect_moe_aux: bool = False):
    """Dual-tower forward closure shared by both CLIP steps (the analog of
    ``_apply_two_views`` for the SimCLR pair): params ->
    (zi, zt, scale, moe_aux), optionally rematerialized in the backward
    pass (``moe_aux`` is 0.0 unless ``collect_moe_aux``)."""

    def fwd(params, images, tokens):
        if not collect_moe_aux:
            zi, zt, scale = state.apply_fn(
                {"params": params}, images, tokens, train=True)
            return zi, zt, scale, 0.0
        (zi, zt, scale), updates = state.apply_fn(
            {"params": params}, images, tokens, train=True,
            mutable=["intermediates"])
        return zi, zt, scale, moe_aux_from(updates)

    return jax.checkpoint(fwd) if remat else fwd


def make_clip_train_step(use_fused: bool | None = None,
                         remat: bool = False,
                         moe_aux_weight: float = 0.0) -> Callable:
    """Single-device CLIP train step: dual towers, learnable logit scale.

    ``state.apply_fn(variables, images, tokens)`` must return
    ``(image_embeds, text_embeds, scale)`` (models/clip.py). Symmetric
    InfoNCE runs at temperature ``1/scale`` so the scale's gradient flows.
    ``remat`` rematerializes the tower forwards in the backward pass.
    ``moe_aux_weight > 0`` adds the MoE towers' load-balance aux loss
    (reported under ``metrics["moe_aux"]``).
    The multi-chip equivalents are ``parallel.tp.make_tp_clip_train_step``
    (GSPMD) and the ring/all-gather InfoNCE losses (parallel/).
    """
    if use_fused is None:
        from ..utils.capability import is_tpu_backend

        use_fused = is_tpu_backend()
    if use_fused:
        from ..ops.infonce_pallas import info_nce_fused as _nce

        def loss_of(zi, zt, scale):
            return _nce(zi, zt, scale=scale)
    else:
        from ..ops.oracle import info_nce_loss as _nce

        def loss_of(zi, zt, scale):
            return _nce(zi, zt, temperature=1.0 / scale)
    collect = moe_aux_weight > 0.0

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state, images, tokens):
        towers = _clip_towers(state, remat, collect_moe_aux=collect)

        def loss_fn(params):
            zi, zt, scale, aux = towers(params, images, tokens)
            return loss_of(zi, zt, scale) + moe_aux_weight * aux, aux

        (loss, aux), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        metrics = {"loss": loss}
        if collect:
            metrics["moe_aux"] = aux
        return state.apply_gradients(grads=grads), metrics

    return train_step


# -- shared error-feedback plumbing (ISSUE 12/15) ---------------------------
# ONE implementation of the residual split/reduce/stack rules for every
# sharded step factory (SimCLR and CLIP): this is the subtlest wiring in
# the trainer — a per-factory copy would silently drift.

def _ef_reduce_rule(qdt: str, axis):
    """``(reduced grads, new residual-or-None)`` under the wire policy:
    int8 with a residual rides error feedback, any other non-f32 dtype
    quantizes the pmean without feedback."""
    use_ef = qdt == "int8"

    def reduce_grads(grads, ef):
        if use_ef and ef is not None:
            return quantized_grad_reduce(grads, ef, axis)
        if qdt != "float32":
            with collective_precision(qdt):
                return _pmean_acct(grads, axis), None
        return _pmean_acct(grads, axis), None

    return reduce_grads


def _ef_split_rule(qdt: str):
    """``(state without residual, residual-or-None, has_ef)`` — the
    residual crosses shard_map as its own P(axis)-sharded operand; the
    rest of the state stays replicated (P())."""
    use_ef = qdt == "int8"

    def split_ef(state):
        ef = state.ef_residual
        has_ef = use_ef and ef is not None \
            and bool(jax.tree_util.tree_leaves(ef))
        return state.replace(ef_residual=None), \
            (ef if has_ef else None), has_ef

    return split_ef


def _ef_unstack(stacked):
    """The per-device slice of the P(axis)-stacked residual operand."""
    return jax.tree.map(lambda t: t[0], stacked)


def _ef_stack(local):
    """Re-stack a per-device residual for the P(axis) out_spec."""
    return jax.tree.map(lambda t: t[None], local)


def make_sharded_train_step(
    mesh: Mesh,
    temperature: float = 0.1,
    axis: str = "data",
    interpret: bool | None = None,
    remat: bool = False,
    loss_impl: str = "strip",
    moe_aux_weight: float = 0.0,
    guard: bool = False,
    collective_dtype: str = "float32",
    ring_chunks: int | None = None,
) -> Callable:
    """Distributed train step over the mesh's data axis.

    Batch sharded along ``axis``; params/opt-state replicated. Inside the
    per-device body: forward on the local shard (BN stats psum'd via the
    model's ``axis_name``), ``lax.all_gather`` of embeddings into the fused
    partial loss, ``psum`` of gradients — i.e. the complete NCCL-SimCLR
    collective pattern compiled onto ICI by XLA.

    ``loss_impl="pair"`` swaps the loss for the balanced shard-pair
    schedule (parallel/pair.py: each global similarity tile walked once
    across the mesh — ~2.2x fewer loss matmuls at P=8).

    ``loss_impl="chunked"`` (ISSUE 19) replaces the embedding all-gather
    with the chunked ring-overlap schedule (dist_loss.
    local_ntxent_chunked): per ring hop, each chunk's onward ppermute is
    issued before its similarity fold, so chunk k+1's transfer overlaps
    chunk k's compute at identical total wire bytes. ``ring_chunks``
    pins the per-hop chunk count; ``None`` defers to
    ``ops.autotune.resolve_ring_chunks`` (cached table, CPU-safe
    heuristic default — never a per-step measurement). Other impls
    reject a ``ring_chunks`` setting loudly.

    ``moe_aux_weight > 0`` adds the MoE load-balance aux loss, pmean'd
    over the mesh (each device routes its own batch shard, so the mean of
    per-shard aux losses is the dp=ep estimator of balance).

    ``guard=True``: in-step divergence guard, as in ``make_train_step``
    (trailing replicated ``scale`` operand, skip-on-non-finite,
    ``grad_norm``/``step_ok`` metrics). The finite check runs AFTER the
    gradient pmean, so a NaN on any one shard skips the update uniformly
    on every device — the replicated state stays bitwise identical.

    ``collective_dtype`` (ISSUE 12): wire precision for the step's
    hand-written collectives. ``"bf16"`` casts payloads to bfloat16
    around the wire (2x fewer bytes); ``"int8"`` quantizes eligible
    payloads with in-graph per-chunk symmetric scales (~4x fewer bytes
    — embedding gathers ride a straight-through-estimator custom_vjp,
    and gradient reductions use ERROR FEEDBACK when the state carries a
    residual (``init_error_feedback``): each device's compression error
    carries into its next step's payload, so quantization noise is
    absorbed instead of biasing SGD. On a guarded step, a skipped
    (non-finite) step keeps the pre-step residual too). BatchNorm
    statistics always reduce in full precision (running stats, a
    negligible byte share). The comms accounting records the quantized
    WIRE bytes, so ``collective_bytes_total`` / the per-step
    ``train_step_comms_bytes`` series show the drop directly.
    """
    num_devices = mesh.shape[axis]
    loss_body = resolve_local_ntxent(loss_impl)
    if ring_chunks is not None and loss_impl != "chunked":
        raise ValueError(
            f"ring_chunks tunes the chunked ring-overlap schedule; "
            f"loss_impl={loss_impl!r} has no ring chunks — it would be "
            f"silently ignored")
    _loss_extra = {"chunks": ring_chunks} if loss_impl == "chunked" else {}
    collect = moe_aux_weight > 0.0
    # Validates the name (and normalizes the bfloat16 alias) eagerly —
    # a typo'd dtype must fail at build, not first trace.
    qdt = collective_precision(collective_dtype).dtype
    _reduce_grads = _ef_reduce_rule(qdt, axis)
    _split_ef = _ef_split_rule(qdt)
    _ef_in, _ef_out = _ef_unstack, _ef_stack

    def local_loss(z1, z2):
        return loss_body(z1, z2, temperature, axis, num_devices, interpret,
                         **_loss_extra)

    def _loss_and_grads(state, v1, v2):
        def loss_fn(params):
            z1, z2, new_stats, aux = _apply_two_views(
                state, params, v1, v2, remat=remat, collect_moe_aux=collect)
            loss = local_loss(z1, z2) + moe_aux_weight * aux
            return loss, (new_stats, aux)

        # The precision context is trace-time thread-local state: enter
        # it around the grad TRACE so both the forward's embedding
        # gathers and their AD duals build under the policy.
        with collective_precision(qdt):
            return jax.value_and_grad(loss_fn, has_aux=True)(state.params)

    def _metrics(loss, aux):
        # The aux term varies per shard (each device routes its own
        # batch); pmean the REPORTED loss so it equals the optimized
        # objective (whose gradient is the pmean'd grads) on every device
        # — the P() out_spec would otherwise publish one arbitrary
        # shard's.
        metrics = {"loss": _pmean_acct(loss, axis) if collect else loss}
        if collect:
            metrics["moe_aux"] = _pmean_acct(aux, axis)
        return metrics

    if guard:
        def per_device_guarded(state: TrainState, v1, v2, scale, ef=None):
            (loss, (new_stats, aux)), grads = _loss_and_grads(state, v1, v2)
            grads, new_ef = _reduce_grads(grads, ef)
            new_stats = _pmean_acct(new_stats, axis)
            grads = jax.tree.map(lambda g: g * scale, grads)
            # A non-finite local loss whose NaN died in a masked reduction
            # could leave grads finite; fold the pmean'd loss into the
            # check so every shard agrees on it either way.
            loss_all = _pmean_acct(loss, axis)
            state, gmetrics = _guarded_update(state, grads, loss_all,
                                              new_stats)
            metrics = {**_metrics(loss, aux), **gmetrics}
            if new_ef is None:
                return state, metrics
            # A skipped step applied no update, so its compression error
            # must not carry either — keep the pre-step residual.
            ok = gmetrics["step_ok"]
            new_ef = jax.tree.map(lambda n, o: jnp.where(ok, n, o),
                                  new_ef, ef)
            return state, metrics, new_ef

        sharded_guarded = _shard_map_compat(
            per_device_guarded,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P()),
            out_specs=(P(), P()),
            check_vma=False,
        )

        def _guarded_ef_body(state, v1, v2, scale, ef_stacked):
            state, metrics, new_ef = per_device_guarded(
                state, v1, v2, scale, _ef_in(ef_stacked))
            return state, metrics, _ef_out(new_ef)

        sharded_guarded_ef = _shard_map_compat(
            _guarded_ef_body,
            mesh=mesh,
            in_specs=(P(), P(axis), P(axis), P(), P(axis)),
            out_specs=(P(), P(), P(axis)),
            check_vma=False,
        )

        # Undonated for the same XLA aliasing reason as the single-device
        # guarded step (see make_train_step).
        @jax.jit
        def guarded_step(state: TrainState, v1, v2, scale=1.0):
            scale = jnp.asarray(scale, jnp.float32)
            bare, ef, has_ef = _split_ef(state)
            if not has_ef:
                out, metrics = sharded_guarded(bare, v1, v2, scale)
                return out.replace(ef_residual=state.ef_residual), metrics
            out, metrics, new_ef = sharded_guarded_ef(bare, v1, v2,
                                                      scale, ef)
            return out.replace(ef_residual=new_ef), metrics

        return guarded_step

    def per_device_step(state: TrainState, v1, v2, ef=None):
        (loss, (new_stats, aux)), grads = _loss_and_grads(state, v1, v2)
        grads, new_ef = _reduce_grads(grads, ef)
        new_stats = _pmean_acct(new_stats, axis)
        state = state.apply_gradients(grads=grads)
        state = state.replace(batch_stats=new_stats)
        if new_ef is None:
            return state, _metrics(loss, aux)
        return state, _metrics(loss, aux), new_ef

    sharded = _shard_map_compat(
        per_device_step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def _plain_ef_body(state, v1, v2, ef_stacked):
        state, metrics, new_ef = per_device_step(state, v1, v2,
                                                 _ef_in(ef_stacked))
        return state, metrics, _ef_out(new_ef)

    sharded_ef = _shard_map_compat(
        _plain_ef_body,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P(axis)),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, v1, v2):
        bare, ef, has_ef = _split_ef(state)
        if not has_ef:
            out, metrics = sharded(bare, v1, v2)
            return out.replace(ef_residual=state.ef_residual), metrics
        out, metrics, new_ef = sharded_ef(bare, v1, v2, ef)
        return out.replace(ef_residual=new_ef), metrics

    return train_step


def make_sharded_clip_train_step(
    mesh: Mesh,
    axis: str = "data",
    interpret: bool | None = None,
    remat: bool = False,
    loss_impl: str = "dual",
    moe_aux_weight: float = 0.0,
    collective_dtype: str = "float32",
) -> Callable:
    """Distributed CLIP train step over the mesh's data axis (shard_map).

    The dual-tower analog of ``make_sharded_train_step``: per-device tower
    forwards on the local (images, tokens) shard, then the fused partial
    InfoNCE over the global batch (per-device local-rows x global-cols
    blocks, O(N) residuals), gradients pmean'd. ``loss_impl="dual"``
    (default) gathers one modality and walks the similarity block once
    for both softmax directions (dist_loss.local_infonce_dual — half the
    loss communication and matmuls); ``"twopass"`` keeps the
    gather-both/walk-twice form. This is the production TPU path for
    data-parallel CLIP; use ``parallel.tp.make_tp_clip_train_step`` when
    the towers themselves need sharding (GSPMD tensor parallelism).
    ``moe_aux_weight``: as in ``make_sharded_train_step`` (aux pmean'd —
    the dp=ep estimator over per-shard routing).

    ``collective_dtype``: wire precision for the modality gathers and
    the gradient pmean, as in ``make_sharded_train_step`` — int8
    gradient reductions carry ERROR FEEDBACK exactly like the SimCLR
    step (ISSUE 15 satellite, closing the ROADMAP item 1 follow-up):
    the residual rides ``TrainState.ef_residual`` as its own
    P(axis)-sharded shard_map operand (``init_error_feedback`` builds
    it; a state without one falls back to plain int8 quantization),
    checkpoints drop it by default and restore tolerantly to zeros.
    """
    local_loss = resolve_local_infonce(loss_impl)
    collect = moe_aux_weight > 0.0
    qdt = collective_precision(collective_dtype).dtype
    # The SimCLR step's EF rules, shared (one implementation — see the
    # module-level helpers).
    _reduce_grads = _ef_reduce_rule(qdt, axis)
    _split_ef = _ef_split_rule(qdt)

    def per_device_step(state, images, tokens, ef=None):
        towers = _clip_towers(state, remat, collect_moe_aux=collect)

        def loss_fn(params):
            zi, zt, scale, aux = towers(params, images, tokens)
            return local_loss(zi, zt, scale, axis, interpret) \
                + moe_aux_weight * aux, aux

        # The precision context wraps the grad TRACE so the modality
        # gathers and their AD duals build under the policy; the
        # gradient reduction applies it (or the EF schedule) itself.
        with collective_precision(qdt):
            (loss, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
        grads, new_ef = _reduce_grads(grads, ef)
        # Same rationale as make_sharded_train_step: the per-shard aux
        # makes loss shard-varying; report the pmean (== the objective).
        metrics = {"loss": _pmean_acct(loss, axis) if collect else loss}
        if collect:
            metrics["moe_aux"] = _pmean_acct(aux, axis)
        state = state.apply_gradients(grads=grads)
        if new_ef is None:
            return state, metrics
        return state, metrics, new_ef

    sharded = _shard_map_compat(
        per_device_step,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis)),
        out_specs=(P(), P()),
        check_vma=False,
    )

    def _ef_body(state, images, tokens, ef_stacked):
        state, metrics, new_ef = per_device_step(
            state, images, tokens, _ef_unstack(ef_stacked))
        return state, metrics, _ef_stack(new_ef)

    sharded_ef = _shard_map_compat(
        _ef_body,
        mesh=mesh,
        in_specs=(P(), P(axis), P(axis), P(axis)),
        out_specs=(P(), P(), P(axis)),
        check_vma=False,
    )

    @functools.partial(jax.jit, donate_argnums=(0,))
    def train_step(state: TrainState, images, tokens):
        bare, ef, has_ef = _split_ef(state)
        if not has_ef:
            out, metrics = sharded(bare, images, tokens)
            return out.replace(ef_residual=state.ef_residual), metrics
        out, metrics, new_ef = sharded_ef(bare, images, tokens, ef)
        return out.replace(ef_residual=new_ef), metrics

    return train_step


def shard_batch(batch, mesh: Mesh, axis: str = "data"):
    """Place a host batch with its leading dim sharded over the mesh.

    This is the BLOCKING per-step spelling (fine for tests and one-off
    placement); on the training hot path wrap the batch iterator in
    ``parallel.mesh.sharded_prefetch`` instead, which keeps committed
    global arrays transferring under the running step.
    """
    sharding = NamedSharding(mesh, P(axis))
    return jax.tree.map(lambda x: jax.device_put(x, sharding), batch)


def aot_compile_with_flops(train_step, *args):
    """(flops-or-None, compiled-or-None): AOT-compile one train step and
    read XLA's cost analysis off the executable.

    For an SPMD (shard_map/pjit) step the compiled module is the per-device
    program, so the FLOP count is per-chip — exactly what per-chip MFU
    accounting wants. Callers should EXECUTE the returned compiled object
    (it is a plain callable with the jit donation semantics baked in) —
    lower().compile() does not populate the jit dispatch cache, so calling
    the original wrapper afterwards would compile a second time.
    """
    from ..utils.profiling import flops_from_compiled

    try:
        compiled = train_step.lower(*args).compile()
    except (AttributeError, TypeError, ValueError, NotImplementedError,
            RuntimeError) as e:
        # AttributeError/TypeError: not a jit wrapper (no .lower, or a
        # signature we can't bind); the rest: the backend refused AOT.
        # Degrading to per-call dispatch without FLOP/MFU accounting is
        # legitimate, but it must be OBSERVABLE, not silent.
        logger.warning(
            "AOT step compile unavailable on backend %r (%s: %s) — "
            "falling back to per-call jit dispatch; MFU accounting "
            "disabled for this run", jax.default_backend(),
            type(e).__name__, e)
        return None, None
    return flops_from_compiled(compiled), compiled


def compiled_step_flops(train_step, *args) -> float | None:
    """FLOPs of one compiled train step (cost-analysis only; prefer
    aot_compile_with_flops when you will also run the step)."""
    return aot_compile_with_flops(train_step, *args)[0]


def _graph_census(step_fn, args, declared, compiled):
    """Graph-census summary of one traced train step (ISSUE 14): the
    jaxpr census re-traced with accounting SUPPRESSED (the first trace
    already counted — counters must not double-bump), diffed against
    the step's declared comms delta; the AD-dual remainder (and, for
    pure-GSPMD steps whose jaxpr holds no collective eqns at all, the
    compiled module's HLO census) is what
    ``timeline.set_comms_per_step(graph=...)`` publishes as
    ``collective_graph_bytes_total{source=ad|gspmd}``. Never raises —
    telemetry must not break training."""
    try:
        from ..analysis.graph.census import (
            census_bytes,
            census_of_callable,
            graph_remainder,
            hlo_census,
        )

        entries, _ = census_of_callable(step_fn, *args,
                                        suppress_accounting=True)
        summary = graph_remainder(entries, declared)
        if not entries and compiled is not None:
            # No collectives in the jaxpr: everything the compiled
            # module moves was GSPMD-inserted (the TP/FSDP class).
            try:
                summary["gspmd_bytes"] = round(
                    census_bytes(hlo_census(
                        compiled.as_text(),
                        default_group_size=jax.device_count())), 3)
            except Exception:  # noqa: BLE001 — an executable without
                pass           # readable HLO text just skips the half
        return summary
    except Exception:  # noqa: BLE001 — strictly best-effort telemetry
        logger.debug("graph census skipped", exc_info=True)
        return None


def measure_comms_overlap(
    mesh: Mesh,
    n_local: int,
    dim: int,
    *,
    axis: str = "data",
    temperature: float = 0.1,
    ring_chunks: int | None = None,
    include_backward: bool = True,
    repeats: int = 5,
    warmup: int = 2,
    timeline=None,
    seed: int = 0,
) -> dict:
    """On-chip A/B of the chunked ring schedule's overlap window
    (ISSUE 19): time the monolithic all-gather loss against the chunked
    ring-overlap loss on the CURRENT backend, both jitted and
    ``block_until_ready`` bracketed, and report the wall clock the
    chunked schedule hides. The CPU comms record pins BYTES (census
    byte parity is machine-checked); this helper prices the TIME — an
    accelerator effect, meaningful on ICI, near-zero (possibly
    negative, clamped by the timeline series) on host backends.

    Returns ``{"monolithic_ms", "chunked_ms", "overlap_ms",
    "overlap_frac", "chunks", "backend"}`` (medians over ``repeats``)
    and, when ``timeline`` is given, publishes through
    ``StepTimeline.set_comms_overlap`` (gauges + one ``comms_overlap``
    event). ``ring_chunks=None`` uses the autotune-resolved count —
    the same resolution the chunked step itself performs.
    """
    import numpy as np

    from ..ops.autotune import resolve_ring_chunks
    from ..parallel.dist_loss import make_sharded_ntxent

    num_devices = mesh.shape[axis]
    n_global = num_devices * int(n_local)
    rng = np.random.default_rng(seed)

    def unit(shape):
        z = rng.standard_normal(shape).astype(np.float32)
        return jnp.asarray(z / np.linalg.norm(z, axis=-1, keepdims=True))

    z1, z2 = unit((n_global, dim)), unit((n_global, dim))
    chunks = resolve_ring_chunks(2 * int(n_local), int(dim), num_devices,
                                 jnp.float32, chunks=ring_chunks)

    def timed(loss):
        fn = (jax.grad(lambda a, b: loss(a, b), argnums=(0, 1))
              if include_backward else loss)
        fn = jax.jit(fn)
        for _ in range(max(int(warmup), 1)):
            jax.block_until_ready(fn(z1, z2))
        samples = []
        for _ in range(max(int(repeats), 1)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn(z1, z2))
            samples.append((time.perf_counter() - t0) * 1e3)
        return float(np.median(samples))

    mono_ms = timed(make_sharded_ntxent(mesh, temperature, axis=axis,
                                        impl="strip"))
    chunk_ms = timed(make_sharded_ntxent(mesh, temperature, axis=axis,
                                         impl="chunked",
                                         ring_chunks=chunks))
    overlap_ms = max(mono_ms - chunk_ms, 0.0)
    out = {
        "monolithic_ms": round(mono_ms, 3),
        "chunked_ms": round(chunk_ms, 3),
        "overlap_ms": round(overlap_ms, 3),
        "overlap_frac": round(overlap_ms / mono_ms, 4) if mono_ms else 0.0,
        "chunks": int(chunks),
        "backend": jax.default_backend(),
    }
    if timeline is not None:
        timeline.set_comms_overlap(overlap_ms, monolithic_ms=mono_ms,
                                   chunked_ms=chunk_ms, chunks=chunks)
    return out


def train_loop(
    state: TrainState,
    data_iter,
    train_step: Callable,
    num_steps: int,
    log_every: int = 50,
    flops_per_step: float | str | None = "auto",
    hook: Callable | None = None,
    step_hook: Callable | None = None,
    stop_fn: Callable[[], bool] | None = None,
    watchdog=None,
    step_guard: Callable | None = None,
    timeline=None,
    metrics_lag: int = 0,
    graph_census: bool | None = None,
):
    """Simple host loop: step, log loss / steps-per-sec / MFU.

    MFU is automatic: with ``flops_per_step="auto"`` (default) the loop asks
    XLA's compiled cost analysis for the step's per-chip FLOPs on the first
    batch (BASELINE.json north star: >=50% MFU needs a measurement pathway,
    not a hand-passed constant). Pass an explicit float to override, or None
    to disable MFU accounting.

    ``hook(state, entry)`` fires at log points; ``step_hook(state)`` fires
    after EVERY step (for periodic side effects keyed on the global
    ``state.step``, e.g. interval-filtered checkpoint saves).

    ``stop_fn()`` is polled after every step; returning True ends the loop
    early at a step boundary (the preemption pathway —
    training/preemption.PreemptionGuard turns SIGTERM into exactly this).

    ``watchdog`` (a started ``utils.watchdog.StallWatchdog``) is beaten
    once per step, so a hung collective/transfer past its timeout produces
    thread-stack dumps and fires its ``on_stall`` policy (§5.3 failure
    detection — a stalled run should diagnose itself, not go silent).

    ``step_guard`` (e.g. ``resilience.DivergenceGuard``) is called after
    EVERY step with a ``StepOutcome``; it may raise (DivergenceError) to
    abort the attempt for the supervisor's rollback tier. When the guard
    exposes ``scale_value()`` the loop passes its gradient scale as the
    step's trailing operand — the step must then be built with
    ``guard=True``. NOTE the cost: building the outcome reads the loss
    every step, which synchronizes host and device per step (acceptable
    for guarded runs; leave step_guard None on the raw-throughput path).

    ``timeline`` (``obs.StepTimeline``) records the per-step breakdown —
    data-fetch wait (split into host-fetch vs device-transfer when the
    iterator is a ``data.DevicePrefetcher``), ``block_until_ready``-
    bracketed device time, step-hook (checkpoint) time, steps/sec, MFU —
    into the metrics registry and event log. Same per-step host-sync cost
    caveat as ``step_guard``; leave None on the raw-throughput path — or
    pair either with ``metrics_lag=1`` to take the sync off the critical
    path.

    ``metrics_lag=1`` (lag-1 metrics drain): the host reads step N-1's
    ``loss``/``grad_norm``/``step_ok`` AFTER dispatching step N, so the
    guard's/timeline's device-to-host reads overlap step N's compute
    instead of serializing the loop. Semantics under lag, all documented
    one-step-late, never-missed:

    * ``step_guard`` sees each ``StepOutcome`` (tagged ``lag=1``) exactly
      one step after it was dispatched; a ``DivergenceError`` therefore
      aborts with one extra step dispatched — harmless, because the
      jit-side guard already kept the non-finite update out of the
      params, and the final pending outcome is always drained (a NaN on
      the very last step still raises).
    * A guard-driven gradient ``scale`` change reaches the step stream
      up to two steps after the diverged step (the next step is already
      dispatched when the outcome is read).
    * ``step_hook`` (checkpoint cadence) for step N runs after step
      N-1's outcome validated, so a diverged attempt never force-saves
      past its last validated step — same invariant as the sync path,
      shifted one step.
    * ``timeline`` records device time as dispatch-to-ready latency
      (the sync bracket would reintroduce the stall being removed) and
      ``hook(state, entry)`` observes the newest dispatched state.

    ``graph_census`` (ISSUE 14; default ``None`` = on whenever
    ``timeline`` is set): after the step-1 comms bracket, re-trace the
    step (accounting suppressed) and publish the graph-level traffic
    the shims cannot declare — AD duals and, for pure-GSPMD steps,
    compiler-inserted collectives — as
    ``collective_graph_bytes_total{source=ad|gspmd}`` plus
    ``graph_bytes``/``ad_bytes`` fields on the ``comms_profile``
    event. Costs one extra abstract trace on step 1 (no compile);
    pass ``False`` to skip it. An explicit ``True`` without a
    ``timeline`` raises — the census publishes through the timeline's
    comms bracket, so there would be nowhere to put the result.
    """
    if metrics_lag not in (0, 1):
        raise ValueError(f"metrics_lag must be 0 or 1, got {metrics_lag}")
    if graph_census and timeline is None:
        # The census publishes THROUGH the timeline's comms bracket; an
        # explicit True with nowhere to publish would be a silent no-op.
        raise ValueError("graph_census=True requires timeline= (the "
                         "census publishes through its comms bracket)")
    history = []
    use_scale = step_guard is not None and hasattr(step_guard,
                                                   "scale_value")

    def run_step(ts, s, a, b):
        if use_scale:
            return ts(s, a, b, step_guard.scale_value())
        return ts(s, a, b)

    t0 = time.perf_counter()
    last_t, last_step = t0, 0
    # Timeline records carry GLOBAL step numbers (state.step is the
    # resume point): a run restored at step 200 must not emit step
    # events restarting at 1 that cannot be correlated with its own
    # checkpoint/restart events. The one int() sync is paid only on
    # telemetry-enabled runs.
    step_base = 0
    comms_mark = None
    # The census must trace the JIT WRAPPER (the auto-AOT path swaps
    # train_step for the bare executable, which cannot be re-traced).
    census_step = train_step
    compiled_obj = None
    do_census = graph_census if graph_census is not None \
        else timeline is not None
    if timeline is not None:
        step_base = int(state.step)
        timeline.new_attempt()  # restart gaps are not step time
        # Bracket the step's trace (AOT lowering below, or the first
        # call's jit trace) so the comms-accounting delta is exactly one
        # compiled step's static collective profile (obs/timeline.py).
        comms_mark = comms_accounting().totals()
    if stop_fn is not None and stop_fn():
        # Signal landed before the loop (e.g. during checkpoint restore):
        # don't pull a batch or pay the step-1 AOT compile on the way out.
        logger.warning("stop requested before training started")
        return state, history

    def outcome_of(step, metrics):
        return StepOutcome(
            step=step, loss=float(metrics["loss"]),
            grad_norm=(float(metrics["grad_norm"])
                       if "grad_norm" in metrics else None),
            ok=bool(metrics.get("step_ok", True)), lag=metrics_lag)

    def record_and_log(step, metrics, device_s, waits, hook_s,
                       force_log=False):
        """Timeline record + log-boundary reads for one COMPLETED step
        (metrics already host-readable). Shared by the sync path and the
        lag-1 drain."""
        nonlocal last_t, last_step
        data_wait_s, host_fetch_s, transfer_s = waits
        if timeline is not None:
            timeline.record_step(
                step=step_base + step, loss=float(metrics["loss"]),
                data_wait_s=data_wait_s, device_s=device_s,
                hook_s=hook_s,
                host_fetch_s=host_fetch_s, transfer_s=transfer_s,
                ok=(bool(metrics["step_ok"]) if "step_ok" in metrics
                    else None),
                grad_norm=(float(metrics["grad_norm"])
                           if "grad_norm" in metrics else None))
        if step % log_every == 0 or step == num_steps or force_log:
            loss = float(metrics["loss"])
            now = time.perf_counter()
            sps = (step - last_step) / max(now - last_t, 1e-9)
            last_t, last_step = now, step
            entry = {"step": step, "loss": loss, "steps_per_sec": sps}
            if flops_per_step:
                entry["mfu"] = estimate_mfu(flops_per_step, sps)
            history.append(entry)
            logger.info("step %d: loss=%.4f, %.2f steps/s", step, loss, sps)
            if hook is not None:
                hook(state, entry)

    def drain(rec, force_log=False):
        """Lag-1 path: consume a previously dispatched step's metrics.
        The block here overlaps the step dispatched after it — by drain
        time the metrics are usually already resident."""
        step, metrics, t_dispatch, waits, hook_s = rec
        metrics = jax.block_until_ready(metrics)
        # Dispatch-to-ready latency, not a bracketed sync (see docstring).
        device_s = time.perf_counter() - t_dispatch
        if watchdog is not None:
            watchdog.beat()
        if step_guard is not None:
            step_guard(outcome_of(step, metrics))
        record_and_log(step, metrics, device_s, waits, hook_s, force_log)

    pending = None  # lag-1: (step, metrics, t_dispatch, waits, hook_s)
    stopped = False
    for step in range(1, num_steps + 1):
        t_fetch = time.perf_counter()
        v1, v2 = next(data_iter)
        data_wait_s = time.perf_counter() - t_fetch
        # DevicePrefetcher exposes the (host-fetch, transfer) split of the
        # batch it just yielded; a plain iterator's wait is all host fetch.
        split = data_iter.last_timing() \
            if hasattr(data_iter, "last_timing") else None
        waits = (data_wait_s, split[0] if split else data_wait_s,
                 split[1] if split else None)
        if step == 1 and flops_per_step == "auto":
            aot_args = (state, v1, v2) + (
                (step_guard.scale_value(),) if use_scale else ())
            t_compile = time.perf_counter()
            flops_per_step, compiled = aot_compile_with_flops(
                train_step, *aot_args)
            if compiled is not None:
                train_step = compiled  # reuse the executable we just built
                compiled_obj = compiled
            if flops_per_step is not None:
                logger.info("compiled step cost: %.3e FLOPs/chip",
                            flops_per_step)
            if timeline is not None:
                timeline.set_flops_per_step(
                    flops_per_step if isinstance(flops_per_step, float)
                    else None)
                timeline.record_compile(
                    (time.perf_counter() - t_compile) * 1e3,
                    flops_per_step if isinstance(flops_per_step, float)
                    else None)
        t_step = time.perf_counter()
        state, metrics = run_step(train_step, state, v1, v2)
        if step == 1 and comms_mark is not None:
            # Dispatch returned, so the step is traced: the delta is its
            # per-compiled-step comms profile (empty on single-device).
            delta = comms_accounting().delta(comms_mark)
            graph = None
            if do_census:
                census_args = (state, v1, v2) + (
                    (step_guard.scale_value(),) if use_scale else ())
                graph = _graph_census(census_step, census_args, delta,
                                      compiled_obj)
            timeline.set_comms_per_step(delta, graph=graph)
            comms_mark = None
        if metrics_lag:
            # Step N is in flight; NOW read step N-1 (overlapped drain).
            if pending is not None:
                drain(pending)
                pending = None
            t_hook = time.perf_counter()
            if step_hook is not None:
                step_hook(state)
            pending = (step, metrics, t_step, waits,
                       time.perf_counter() - t_hook)
            stopped = stop_fn is not None and stop_fn()
            if stopped:
                drain(pending, force_log=True)
                pending = None
                logger.warning("stop requested: leaving train loop at "
                               "step %d of %d", step, num_steps)
                break
            continue
        if timeline is not None:
            # Bracket the device time: without the sync, the dispatch
            # returns immediately and per-step timing measures nothing
            # (the timeline's documented host-sync cost, as step_guard).
            metrics = jax.block_until_ready(metrics)
        device_s = time.perf_counter() - t_step
        if watchdog is not None:
            watchdog.beat()
        if step_guard is not None:
            step_guard(outcome_of(step, metrics))
        t_hook = time.perf_counter()
        if step_hook is not None:
            step_hook(state)
        hook_s = time.perf_counter() - t_hook
        stopped = stop_fn is not None and stop_fn()
        record_and_log(step, metrics, device_s, waits, hook_s,
                       force_log=stopped)
        if stopped:
            logger.warning("stop requested: leaving train loop at step %d "
                           "of %d", step, num_steps)
            break
    if pending is not None:
        # Lag-1 epilogue: the final step's outcome is ALWAYS drained —
        # a divergence on the last step raises here, before fit's
        # force-save can persist past it.
        drain(pending)
    return state, history


def fit(
    state: TrainState,
    data_iter,
    train_step: Callable,
    num_steps: int,
    checkpoint_dir: str | None = None,
    checkpoint_every: int = 500,
    log_every: int = 50,
    flops_per_step: float | str | None = "auto",
    fast_forward_data: bool = False,
    stop_fn: Callable[[], bool] | None = None,
    watchdog=None,
    step_guard: Callable | None = None,
    timeline=None,
    metrics_lag: int = 0,
    checkpoint_retry_policy=None,
    checkpoint_verify_writes: bool = True,
    async_checkpointing: bool = False,
    checkpoint_keep_last: int | None = 3,
    checkpoint_keep_every: int | None = None,
    checkpoint_mirror: str | None = None,
    checkpoint_fault_hook: Callable | None = None,
    restore_step: int | None = None,
    checkpoint_save_ef: bool = False,
):
    """Checkpoint-aware training: restore the latest checkpoint if one
    exists, train to ``num_steps`` total, save every ``checkpoint_every``
    steps (on the GLOBAL ``state.step``) and at the end.

    ``restore_step`` pins the resume point to an explicit historical step
    instead of the newest valid one (CLI ``--restore-step``): the named
    step is restored with the same mirror-fallback semantics restore
    always has, and a step that exists in NO replica raises — silently
    training from scratch when the caller named a specific step would
    discard exactly the history they asked for. Rewinding is git-reset,
    not a detached checkout: steps NEWER than the restore point are
    deleted from both replicas (loudly), so the replay's own saves land
    and a crash mid-replay resumes the REPLAYED lineage, never the
    abandoned one.

    ``async_checkpointing=True`` wraps the manager in an
    ``AsyncCheckpointer``: cadence saves snapshot to host and serialize
    on a bounded background writer (the loop blocks only when a save is
    already in flight) — and when the run is stopped by ``stop_fn``
    (SIGTERM / preemption / supervisor stall escalation), the final save
    goes through ``emergency_save``: pending writes drain and the
    stopped step is written synchronously before ``fit`` returns, so the
    grace window cannot expire with the last step still queued.
    ``checkpoint_keep_last`` / ``checkpoint_keep_every`` set the
    retention policy (keep-last-k + keep-every-n; the newest VALID step
    is never collected); ``checkpoint_mirror`` replicates every save to
    a second directory that restore falls back to when the primary is
    corrupt or missing; ``checkpoint_fault_hook`` is the chaos hook run
    at the start of every physical write (``diskfull@N``).

    ``step_guard`` / ``watchdog`` / ``timeline`` / ``metrics_lag``:
    forwarded to ``train_loop`` (divergence policy, stall detection,
    per-step telemetry, lag-1 metrics drain). A guard-raised
    DivergenceError propagates WITHOUT the final force-save — the diverged state must not become the
    newest checkpoint; resilience.Supervisor catches it and restarts from
    the last valid one (restore falls back past corrupt saves via
    CheckpointManager.latest_valid_step).

    ``checkpoint_retry_policy`` / ``checkpoint_verify_writes``: forwarded
    to CheckpointManager. verify_writes=True (default) records per-save
    CRC manifests; writes are atomic either way (tmp-dir + fsync +
    rename), so the manifest guards post-write corruption, not torn
    saves.

    ``stop_fn`` (see ``train_loop``) makes the run preemptible: when it
    trips, the loop exits at the next step boundary and the final
    force-save below persists exactly that step (model + data-iterator
    state), so the next incarnation of the job resumes where the signal
    landed. Pair with ``preemption.PreemptionGuard`` for SIGTERM handling.

    The resume point is ``state.step`` (incremented by apply_gradients), so
    a re-run after preemption continues where the last saved state stopped —
    the capability the reference's multi-day target configs require
    (SURVEY.md §5.4; the reference itself persisted nothing).

    Counting caveats:

    * All step counts here are TRAIN-STEP counts. With
      ``TrainerConfig.accum_steps > 1`` each train step is one micro-batch
      (flax increments ``state.step`` even when MultiSteps skips the
      update), so optimizer updates number ``num_steps / accum_steps``.
    * Data-iterator state: when ``data_iter`` exposes ``state()`` /
      ``restore()`` (e.g. datasets.TwoViewPipeline), its state is saved
      inside each checkpoint and restored on resume — exact mid-epoch
      repositioning with zero host replay. Otherwise ``data_iter`` restarts
      wherever the caller's iterator starts; set ``fast_forward_data=True``
      to consume ``state.step`` batches first (exact for seeded pipelines;
      costs host+augment time proportional to the skipped steps).
    """
    manager = None
    stateful_data = hasattr(data_iter, "state") \
        and hasattr(data_iter, "restore")
    if restore_step is not None and checkpoint_dir is None:
        # The feature's contract is fail-loud: silently training from
        # step 0 when the caller named a specific resume step would
        # discard exactly the history they asked for.
        raise ValueError(
            f"restore_step={restore_step} requires checkpoint_dir "
            "(there is no store to restore the named step from)")
    try:
        if checkpoint_dir is not None:
            from .checkpoint import AsyncCheckpointer, CheckpointManager

            manager = CheckpointManager(
                checkpoint_dir, save_interval_steps=checkpoint_every,
                retry_policy=checkpoint_retry_policy,
                verify_writes=checkpoint_verify_writes,
                max_to_keep=checkpoint_keep_last,
                keep_every=checkpoint_keep_every,
                mirror_dir=checkpoint_mirror,
                fault_hook=checkpoint_fault_hook,
                save_ef_residual=checkpoint_save_ef)
            if async_checkpointing:
                manager = AsyncCheckpointer(manager)
            if restore_step is not None or manager.latest_step() is not None:
                state, data_state = manager.restore_with_data_state(
                    state, restore_step)
                logger.info("resumed from checkpoint at step %d%s",
                            int(state.step),
                            " (explicit --restore-step)"
                            if restore_step is not None else "")
                if restore_step is not None:
                    # The replay OWNS the timeline from here: stale
                    # future steps would silently swallow every cadence
                    # save (existing dir beats a non-forced write) and
                    # would win the newest-valid race on any crash-mid-
                    # replay restart — resuming the lineage the caller
                    # explicitly rewound away from.
                    stale = manager.truncate_after(int(state.step))
                    if stale:
                        logger.warning(
                            "explicit restore_step=%d: deleted %d "
                            "newer checkpoint step(s) %s — the replay "
                            "owns the timeline from here",
                            restore_step, len(stale), stale)
                if stateful_data and data_state is not None:
                    data_iter.restore(data_state)
                    logger.info("data iterator repositioned: %s", data_state)
                    fast_forward_data = False  # already exact, skip replay

        done = int(state.step)
        remaining = num_steps - done
        if remaining <= 0:
            logger.info("nothing to do: checkpoint already at step %d", done)
            return state, []
        if fast_forward_data:
            for _ in range(done):
                if stop_fn is not None and stop_fn():
                    # Preempted during the replay: nothing new to save —
                    # the checkpoint we restored is still the truth.
                    logger.warning("stop requested during data fast-forward")
                    return state, []
                next(data_iter)

        # The hook tracks the global step on the HOST (state.step advances
        # exactly once per train_step call, even under MultiSteps or the
        # guard's skip): reading int(s.step) here would sync host and
        # device EVERY step, putting the device round-trip this PR's
        # async writer exists to hide right back on the hot path.
        hook_step = done

        def step_hook(s):
            # Every step; the manager's interval filter keeps global steps
            # divisible by checkpoint_every (a resumed run keeps the cadence).
            nonlocal hook_step
            hook_step += 1
            if manager is not None and manager.should_save(hook_step):
                manager.save(hook_step, s,
                             data_state=data_iter.state()
                             if stateful_data else None)

        state, history = train_loop(
            state, data_iter, train_step, remaining,
            log_every=log_every,
            flops_per_step=flops_per_step, step_hook=step_hook,
            stop_fn=stop_fn, watchdog=watchdog, step_guard=step_guard,
            timeline=timeline, metrics_lag=metrics_lag)
        if manager is not None:
            # Drain pending async saves BEFORE deciding on the final
            # force-save: a cadence save of this very step may still be
            # in the writer queue.
            manager.wait_until_finished()
            if manager.latest_step() != int(state.step):
                final_data_state = data_iter.state() \
                    if stateful_data else None
                if async_checkpointing \
                        and stop_fn is not None and stop_fn():
                    # Preemption/stall stop: the process may be inside a
                    # SIGTERM grace window — write synchronously NOW
                    # (PreemptionGuard -> stop_fn -> here is the wiring).
                    manager.emergency_save(int(state.step), state,
                                           data_state=final_data_state)
                else:
                    manager.save(int(state.step), state, force=True,
                                 data_state=final_data_state)
        return state, history
    finally:
        # Always drain + close the manager (its async save machinery holds
        # background threads), including on the nothing-to-do early return.
        if manager is not None:
            manager.wait_until_finished()
            manager.close()


def peak_flops_per_chip() -> float:
    """Peak bf16 FLOP/s of the local accelerator (for MFU accounting)."""
    kind = jax.local_devices()[0].device_kind.lower()
    # Public peak numbers: v4 275T, v5e 197T, v5p 459T, v6e 918T bf16.
    table = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
             "v5p": 459e12, "v6e": 918e12, "v6 lite": 918e12}
    for key, val in table.items():
        if key in kind:
            return val
    return 100e12  # unknown accelerator: conservative placeholder


def estimate_mfu(flops_per_step: float, steps_per_sec: float) -> float:
    return flops_per_step * steps_per_sec / peak_flops_per_chip()


def peak_hbm_bytes_per_chip() -> float:
    """Peak HBM bandwidth (bytes/s) of the local accelerator.

    Pairs with peak_flops_per_chip for roofline accounting: a step whose
    arithmetic intensity (FLOPs / bytes accessed) sits below
    peak_flops / peak_bw cannot reach full MFU no matter how well its
    matmuls tile onto the MXU — its MFU ceiling is
    intensity / (peak_flops / peak_bw).
    """
    kind = jax.local_devices()[0].device_kind.lower()
    # Public peak numbers: v4 1228, v5e 819, v5p 2765, v6e 1638 GB/s.
    table = {"v4": 1228e9, "v5 lite": 819e9, "v5e": 819e9,
             "v5p": 2765e9, "v6e": 1638e9, "v6 lite": 1638e9}
    for key, val in table.items():
        if key in kind:
            return val
    return 819e9  # unknown accelerator: v5e-class placeholder
