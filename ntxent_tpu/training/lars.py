"""LARS optimizer + SimCLR learning-rate schedule.

SimCLR's large-batch recipe: LARS with weight decay and trust-ratio scaling,
excluding batch-norm parameters and biases from both, under a linear-warmup
cosine-decay schedule scaled by batch size. Built on optax (the reference
has no optimizer code — SURVEY.md §0.2)."""

from __future__ import annotations

import re

import flax
import jax.numpy as jnp
import optax

__all__ = ["create_lars", "cosine_warmup_schedule", "simclr_learning_rate"]


def _is_excluded(path: tuple[str, ...]) -> bool:
    """BN params and biases are excluded from weight decay and trust ratio.

    Matched on whole path segments (a module named "subnet" must not trip a
    substring "bn" test): any segment that is/starts/ends with a batch-norm
    marker, or a leaf named bias / BN's scale companions.
    """
    names = [str(p).lower() for p in path]

    def is_bn_segment(s: str) -> bool:
        # bn, bn1, bn_2, batchnorm_0, batch_norm, stem_bn, proj_bn ...
        return bool(re.fullmatch(r"(bn|batch_?norm)[_\d]*", s)) \
            or s.endswith("_bn") or "batchnorm" in s

    return any(is_bn_segment(s) for s in names) or names[-1] == "bias"


def exclusion_mask(params):
    """True where weight decay / trust ratio APPLY (i.e. not excluded)."""
    flat = flax.traverse_util.flatten_dict(params)
    mask = {k: not _is_excluded(k) for k in flat}
    return flax.traverse_util.unflatten_dict(mask)


def cosine_warmup_schedule(
    base_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=base_lr,
        warmup_steps=max(warmup_steps, 1),
        decay_steps=max(total_steps, warmup_steps + 1),
    )


def simclr_learning_rate(batch_size: int, base: float = 0.3) -> float:
    """SimCLR linear scaling: lr = base * batch/256 (sqrt scaling for LARS
    uses base=0.075 * sqrt(batch); linear is the paper's LARS default)."""
    return base * batch_size / 256.0


def create_lars(
    learning_rate: float | optax.Schedule,
    weight_decay: float = 1e-6,
    momentum: float = 0.9,
    trust_coefficient: float = 0.001,
    params=None,
) -> optax.GradientTransformation:
    """LARS with SimCLR's exclusion rules.

    If ``params`` is given, a mask excluding BN/bias leaves is computed from
    it; otherwise a callable mask derives it per-update (optax accepts both).
    """
    mask = exclusion_mask(params) if params is not None else exclusion_mask
    return optax.lars(
        learning_rate=learning_rate,
        weight_decay=weight_decay,
        weight_decay_mask=mask,
        trust_coefficient=trust_coefficient,
        trust_ratio_mask=mask,
        momentum=momentum,
    )
