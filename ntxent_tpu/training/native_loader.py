"""Native-engine streaming loader: C++ worker pool, Python policy.

The reference's input pipeline leaned on torch's DataLoader, whose real
work happens in its native (C++) workers. This module is that component
for this framework: ``native/src/loader.cpp`` gathers scattered rows from
a memory-mapped store into dense batch buffers on a thread pool, keeping
``read_ahead`` batches ready ahead of the consumer — released from the
GIL entirely, unlike ``StreamingLoader``'s Python thread pool.

Policy stays in Python on purpose: ``NativeStreamingLoader`` derives from
the same ``_ShardedShuffle`` as ``StreamingLoader``, so the seeded epoch
permutation, coordination-free shard slicing, and exact mid-epoch resume
arithmetic have ONE source of truth — the engines are interchangeable and
the tests assert batch-for-batch equality between them.

Requires a *memory-mapped row store* (``np.memmap`` / ``np.load(...,
mmap_mode='r')`` / a raw file) — the zero-decode path ``ArraySource``
serves. Sources that decode per item (ImageFolderSource) keep using
``StreamingLoader``; decoding belongs where the decoder lives.
"""

from __future__ import annotations

import ctypes
from collections import deque
from typing import Iterator

import numpy as np

from .datasets import ArraySource, _ShardedShuffle

__all__ = ["NativeStreamingLoader", "native_loader_available"]


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    lib.ntx_loader_open.restype = ctypes.c_void_p
    lib.ntx_loader_open.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_int64, ctypes.c_int32, ctypes.c_int32]
    lib.ntx_loader_submit.restype = ctypes.c_int
    lib.ntx_loader_submit.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_uint8)]
    lib.ntx_loader_next.restype = ctypes.c_int64
    lib.ntx_loader_next.argtypes = [ctypes.c_void_p]
    lib.ntx_loader_outstanding.restype = ctypes.c_int64
    lib.ntx_loader_outstanding.argtypes = [ctypes.c_void_p]
    lib.ntx_loader_close.restype = None
    lib.ntx_loader_close.argtypes = [ctypes.c_void_p]
    return lib


def _library() -> ctypes.CDLL:
    from ntxent_tpu.native import load_library

    return _bind(load_library())


def native_loader_available() -> bool:
    """True when the native library is (or can be) built on this host."""
    from ntxent_tpu.native import native_available

    return native_available()


def _as_memmap(source) -> tuple[np.memmap, int]:
    """Validate the source and return (memmap, file offset of row 0).

    The engine addresses rows as ``file_offset + i * row_bytes``, so the
    offset is derived from the view's actual data pointer relative to the
    root mmap — a contiguous slice (``mm[5000:]``) gathers the RIGHT rows
    rather than silently reading from the file start; strided or
    otherwise non-contiguous views are rejected (their rows are not
    ``row_bytes`` apart in the file).
    """
    import mmap as mmaplib

    if isinstance(source, ArraySource):
        source = source.images
    if not isinstance(source, np.memmap):
        raise TypeError(
            "NativeStreamingLoader needs a np.memmap-backed source "
            f"(np.load(..., mmap_mode='r')), got {type(source).__name__}; "
            "use StreamingLoader for in-memory or per-item-decode sources")
    if source.filename is None:  # pragma: no cover - anonymous maps only
        raise TypeError("memmap has no backing file")
    if not source.flags["C_CONTIGUOUS"]:
        raise TypeError("NativeStreamingLoader needs a C-contiguous memmap "
                        "view (strided slices change the on-disk row "
                        "stride); index rows via the loader's shuffle "
                        "instead")
    root = getattr(source, "_mmap", None)
    if root is None:  # pragma: no cover - non-standard memmap subclass
        raise TypeError("memmap view carries no root mmap")
    # numpy maps the file from the page-aligned floor of the header
    # offset; the view's pointer distance from that base is its true
    # position in the file.
    base_addr = np.frombuffer(root, dtype=np.uint8).ctypes.data
    page_base = source.offset - source.offset % mmaplib.ALLOCATIONGRANULARITY
    file_off = page_base + (source.ctypes.data - base_addr)
    if file_off < 0:  # pragma: no cover - defensive
        raise ValueError("memmap data pointer precedes its root mapping")
    return source, int(file_off)


class NativeStreamingLoader(_ShardedShuffle):
    """Drop-in ``StreamingLoader`` over the native batch-gather engine.

    Same constructor surface, same checkpointable-iterator protocol
    (``state()``/``restore()``), same seeded order — only the gather
    engine differs: row copies run on C++ threads against the mmap'd
    file, with ``read_ahead`` whole batches in flight.
    """

    def __init__(self, source, batch_size: int, seed: int = 0,
                 num_threads: int = 8, read_ahead: int = 4,
                 drop_remainder: bool = True,
                 shard_index: int = 0, shard_count: int = 1,
                 retry_policy=None):
        mm, file_off = _as_memmap(source)
        self._init_shuffle(len(mm), batch_size, seed, shard_index,
                           shard_count, drop_remainder)
        self._mm = mm
        self._file_offset = file_off
        self._row_shape = mm.shape[1:]
        self._dtype = mm.dtype
        self._row_bytes = int(mm.dtype.itemsize * np.prod(mm.shape[1:],
                                                          dtype=np.int64))
        self.num_threads = num_threads
        self.read_ahead = max(1, read_ahead)
        self.retry_policy = retry_policy
        self._lib = _library()  # build (or load) eagerly: fail at init

    def _submit_once(self, handle, order: np.ndarray, bi: int) -> np.ndarray:
        idxs = np.ascontiguousarray(self._batch_indices(order, bi),
                                    dtype=np.int64)
        out = np.empty((len(idxs), *self._row_shape), self._dtype)
        rc = self._lib.ntx_loader_submit(
            handle, idxs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(idxs), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
        if rc != 0:
            # Surface as OSError: the engine's submit fails on queue/mmap
            # pressure, the transient class retry_policy defaults cover.
            raise OSError("native loader rejected batch submission")
        return out

    def _submit(self, handle, order: np.ndarray, bi: int) -> np.ndarray:
        """Queue batch ``bi``; workers gather straight into the returned
        buffer (zero staging copies) — it must stay referenced and
        untouched until the matching next() drains it. Submission is
        retried per ``retry_policy`` (resilience.RetryPolicy)."""
        if self.retry_policy is None:
            return self._submit_once(handle, order, bi)
        return self.retry_policy.call(self._submit_once, handle, order, bi)

    def __iter__(self) -> Iterator[np.ndarray]:
        handle = self._lib.ntx_loader_open(
            str(self._mm.filename).encode(), self._file_offset,
            int(self._n_rows), self._row_bytes, self.batch_size,
            int(self.num_threads), int(self.read_ahead))
        if not handle:
            raise RuntimeError(
                f"native loader failed to open {self._mm.filename}")
        try:
            while True:
                with self._lock:
                    epoch, start = self._epoch, self._offset
                order = self._epoch_order(epoch)
                nb = self.batches_per_epoch()
                bi = start
                inflight: deque[np.ndarray] = deque()
                while bi < nb and len(inflight) < self.read_ahead:
                    inflight.append(self._submit(handle, order, bi))
                    bi += 1
                while inflight:
                    rows = self._lib.ntx_loader_next(handle)
                    if rows < 0:
                        raise RuntimeError("native loader next() failed")
                    out = inflight.popleft()
                    if bi < nb:
                        inflight.append(self._submit(handle, order, bi))
                        bi += 1
                    with self._lock:
                        self._offset += 1
                    yield out[:rows]
                with self._lock:
                    self._epoch += 1
                    self._offset = 0
        finally:
            self._lib.ntx_loader_close(handle)
