from ntxent_tpu.training.augment import augment_batch_pair, augment_pair
from ntxent_tpu.training.evaluation import (
    extract_features,
    finetune,
    knn_accuracy,
    linear_probe,
)
from ntxent_tpu.training.data import (
    ArrayDataset,
    DevicePrefetcher,
    PrefetchIterator,
    synthetic_images,
    two_view_iterator,
)
from ntxent_tpu.training.datasets import (
    ArraySource,
    Cifar10Source,
    GlobalTwoViewPipeline,
    ImageFolderSource,
    PairedArrayLoader,
    StreamingLoader,
    TwoViewPipeline,
    device_prefetch,
    grain_loader,
    streaming_two_view_iterator,
)
from ntxent_tpu.training.lars import (
    cosine_warmup_schedule,
    create_lars,
    simclr_learning_rate,
)
from ntxent_tpu.training.preemption import PreemptionGuard
from ntxent_tpu.training.trainer import (
    StepOutcome,
    TrainerConfig,
    TrainState,
    create_train_state,
    estimate_mfu,
    fit,
    init_error_feedback,
    make_clip_train_step,
    make_sharded_clip_train_step,
    make_sharded_train_step,
    make_train_step,
    shard_batch,
    train_loop,
)

__all__ = [
    "augment_batch_pair",
    "augment_pair",
    "AsyncCheckpointer",
    "CheckpointManager",
    "RetentionPolicy",
    "extract_features",
    "finetune",
    "knn_accuracy",
    "linear_probe",
    "ArrayDataset",
    "DevicePrefetcher",
    "PrefetchIterator",
    "synthetic_images",
    "two_view_iterator",
    "ArraySource",
    "Cifar10Source",
    "GlobalTwoViewPipeline",
    "ImageFolderSource",
    "PairedArrayLoader",
    "StreamingLoader",
    "TwoViewPipeline",
    "device_prefetch",
    "grain_loader",
    "streaming_two_view_iterator",
    "PreemptionGuard",
    "StepOutcome",
    "cosine_warmup_schedule",
    "create_lars",
    "simclr_learning_rate",
    "TrainerConfig",
    "TrainState",
    "create_train_state",
    "init_error_feedback",
    "estimate_mfu",
    "make_clip_train_step",
    "make_sharded_clip_train_step",
    "make_sharded_train_step",
    "make_train_step",
    "shard_batch",
    "train_loop",
    "fit",
]


def __getattr__(name):
    # Checkpoint classes lazily: the module imports jax at top level,
    # which initializes the backends as a side effect — that (a) pins the
    # platform before callers can choose one and (b) blocks on
    # accelerator discovery; neither is acceptable for
    # `import ntxent_tpu.training` itself.
    if name in ("CheckpointManager", "AsyncCheckpointer",
                "RetentionPolicy"):
        from ntxent_tpu.training import checkpoint

        return getattr(checkpoint, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
