"""Orbax checkpoint/resume for multi-day pretraining runs.

The reference has no persistence beyond benchmark JSON (SURVEY.md §5.4);
the BASELINE.json configs[2-4] runs (ImageNet/v5e-32 and up) require real
checkpoint/resume. Orbax handles multi-host coordination and atomic writes.

Resilience layer (resilience/ package, SURVEY.md §5.3): every save records
a content manifest (per-file size + CRC32) in a sidecar
``manifests.json``; ``verify()`` re-checksums a step, ``restore`` falls
back past corrupt steps to the newest VALID one (deleting the corrupt
ones so the step sequence can be re-saved), and ``latest_valid_step()``
feeds the supervisor's rollback tier (resilience/supervisor.py). A
``RetryPolicy`` (resilience/retry.py) can wrap the orbax save/restore
calls for transient-filesystem tolerance, and ``save`` reports transient
directory failures by returning False instead of killing the run —
skipping one checkpoint is recoverable; dying mid-run is what this layer
exists to prevent. Fault injection for the corrupt-checkpoint path:
``resilience.faults.truncate_checkpoint_file``.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import time
import zlib
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

from ..obs import events as obs_events
from ..obs.registry import default_registry
from ..resilience.retry import RetryBudgetExceeded

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager"]

# Registry series (ISSUE 3): save/restore/CRC-fallback used to be
# logger-only, so a run quietly skipping every save (full disk, bad
# mount) was indistinguishable from a healthy one on any scrape.
_SAVES = default_registry().counter(
    "checkpoint_saves_total", "successful checkpoint saves")
_SAVE_FAILURES = default_registry().counter(
    "checkpoint_save_failures_total",
    "checkpoint saves skipped on filesystem errors")
_RESTORES = default_registry().counter(
    "checkpoint_restores_total", "checkpoint restores")
_FALLBACKS = default_registry().counter(
    "checkpoint_corrupt_fallbacks_total",
    "corrupt checkpoints skipped by the restore CRC fallback")
_SAVE_MS = default_registry().histogram(
    "checkpoint_save_ms", "wall time of one checkpoint save")

_MANIFEST_NAME = "manifests.json"


def _crc32_file(path: Path, chunk: int = 1 << 20) -> int:
    value = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return value
            value = zlib.crc32(block, value)


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees.

    ``retry_policy`` (resilience.RetryPolicy) retries the underlying orbax
    save/restore on transient errors. ``verify_writes=True`` (default)
    records a per-save content manifest used by ``verify`` /
    ``latest_valid_step`` / the restore fallback; it waits for the async
    save machinery per checksummed save, so a throughput-critical caller
    that trusts its filesystem can turn it off.
    """

    def __init__(self, directory: str | Path, max_to_keep: int = 3,
                 save_interval_steps: int = 1, retry_policy=None,
                 verify_writes: bool = True):
        self.directory = Path(directory).absolute()
        self.retry_policy = retry_policy
        self.verify_writes = verify_writes
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def _call(self, fn, *args, **kwargs):
        if self.retry_policy is not None:
            return self.retry_policy.call(fn, *args, **kwargs)
        return fn(*args, **kwargs)

    # -- content manifests -------------------------------------------------
    def _manifest_path(self) -> Path:
        return self.directory / _MANIFEST_NAME

    def _load_manifests(self) -> dict:
        try:
            with open(self._manifest_path()) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}

    def _store_manifests(self, manifests: dict) -> None:
        tmp = self._manifest_path().with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump(manifests, f)
        os.replace(tmp, self._manifest_path())

    def _step_dir(self, step: int) -> Path | None:
        p = self.directory / str(step)
        if p.is_dir():
            return p
        for q in self.directory.iterdir():  # prefixed/padded layouts
            if q.is_dir():
                digits = "".join(ch for ch in q.name if ch.isdigit())
                if digits and int(digits) == step:
                    return q
        return None

    def _compute_manifest(self, step: int) -> dict | None:
        step_dir = self._step_dir(step)
        if step_dir is None:
            return None
        files = {}
        for p in sorted(step_dir.rglob("*")):
            if p.is_file():
                rel = str(p.relative_to(step_dir))
                files[rel] = [p.stat().st_size, _crc32_file(p)]
        return {"files": files}

    def _record_manifest(self, step: int) -> None:
        # The manifest must describe FINAL bytes: drain the async save
        # machinery first (the documented cost of verify_writes).
        self.manager.wait_until_finished()
        manifest = self._compute_manifest(step)
        if manifest is None:
            logger.warning("no step dir found for step %d; skipping "
                           "checksum manifest", step)
            return
        manifests = self._load_manifests()
        manifests[str(step)] = manifest
        # Drop entries for steps orbax garbage-collected (max_to_keep).
        live = {str(s) for s in (self.manager.all_steps() or [])}
        manifests = {k: v for k, v in manifests.items() if k in live}
        self._store_manifests(manifests)

    def verify(self, step: int) -> bool:
        """Re-checksum a saved step against its manifest.

        True for steps with no recorded manifest (pre-resilience saves are
        unverifiable, not invalid). False on any missing file, size drift,
        or CRC mismatch — e.g. a truncated/partially-written file.
        """
        recorded = self._load_manifests().get(str(step))
        if recorded is None:
            logger.debug("step %d has no checksum manifest; treating as "
                         "valid", step)
            return True
        actual = self._compute_manifest(step)
        if actual is None:
            return False
        want, got = recorded["files"], actual["files"]
        for rel, meta in want.items():
            if rel not in got or got[rel] != meta:
                logger.error(
                    "checkpoint step %d failed verification at %s "
                    "(want size/crc %s, got %s)", step, rel, meta,
                    got.get(rel))
                return False
        return True

    def latest_valid_step(self) -> int | None:
        """Newest step that passes ``verify`` (the supervisor's rollback
        target); None when no step verifies."""
        for step in sorted(self.manager.all_steps() or [], reverse=True):
            if self.verify(step):
                return int(step)
        return None

    def delete_step(self, step: int) -> None:
        """Remove a (corrupt) step and its manifest entry.

        The manifest entry is dropped only once the files are actually
        gone: a failed deletion must keep failing ``verify`` (a
        manifest-less step counts as valid, so popping the entry while
        the truncated files survive would launder corruption into the
        restore fallback's 'newest valid' answer).
        """
        try:
            self.manager.delete(step)
        except Exception:
            step_dir = self._step_dir(step)
            if step_dir is not None:
                shutil.rmtree(step_dir, ignore_errors=True)
        if self._step_dir(step) is not None:
            logger.error("could not delete corrupt checkpoint at step %d; "
                         "keeping its manifest so it stays invalid", step)
            return
        manifests = self._load_manifests()
        if manifests.pop(str(step), None) is not None:
            self._store_manifests(manifests)
        logger.warning("deleted corrupt checkpoint at step %d", step)

    # -- save / restore ----------------------------------------------------
    def save(self, step: int, state: Any, force: bool = False,
             data_state: dict | None = None) -> bool:
        """Save the TrainState, optionally with input-pipeline state.

        ``data_state`` (a small JSON-able dict, e.g. StreamingLoader.state())
        rides along as a composite item so resume can reposition the data
        iterator exactly instead of replaying host batches.

        Returns False — after logging — when the directory hits a
        filesystem error (transient NFS/GCS blips survive a missed
        checkpoint; the next cadence point saves again). Raising here
        would kill a healthy training run over a recoverable IO fault.
        """
        if data_state is not None:
            args: Any = ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                data_state=ocp.args.JsonSave(data_state))
        else:
            args = ocp.args.StandardSave(state)
        t0 = time.perf_counter()
        try:
            saved = self._call(self.manager.save, step, args=args,
                               force=force)
        except (OSError, RetryBudgetExceeded) as e:
            # RetryBudgetExceeded wraps the root OSError once a budgeted
            # retry_policy's wall clock runs out — same recoverable class,
            # and the skip-a-checkpoint contract must not depend on which
            # limit (attempts vs budget) tripped first.
            logger.error("checkpoint save at step %d failed (%s: %s) — "
                         "continuing without it", step,
                         type(e).__name__, e)
            _SAVE_FAILURES.inc()
            obs_events.emit("checkpoint", action="save", step=int(step),
                            ok=False, error=f"{type(e).__name__}: {e}")
            return False
        if saved:
            if self.verify_writes:
                try:
                    self._record_manifest(step)
                except OSError as e:
                    logger.error("checksum manifest for step %d failed "
                                 "(%s); step stays unverifiable", step, e)
            duration_ms = (time.perf_counter() - t0) * 1e3
            _SAVES.inc()
            _SAVE_MS.observe(duration_ms)
            obs_events.emit("checkpoint", action="save", step=int(step),
                            ok=True, forced=bool(force),
                            duration_ms=round(duration_ms, 3),
                            verified=bool(self.verify_writes))
            logger.info("checkpoint saved at step %d -> %s", step,
                        self.directory)
        return saved

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        state, _ = self.restore_with_data_state(state_template, step)
        return state

    def restore_with_data_state(
            self, state_template: Any,
            step: int | None = None) -> tuple[Any, dict | None]:
        """(state, data_state-or-None); handles both checkpoint layouts
        (plain StandardSave and the composite written when data_state was
        provided).

        With ``step=None`` the newest step is verified first; corrupt
        steps are deleted and the search falls back to the newest VALID
        one (the rollback path the supervisor leans on). An explicit
        ``step`` is restored as-is after a verification failure is logged
        — the caller asked for that exact step.
        """
        if step is None:
            step = self.manager.latest_step()
            if step is None:
                raise FileNotFoundError(f"no checkpoint in {self.directory}")
            while not self.verify(step):
                logger.error("checkpoint at step %d is corrupt; falling "
                             "back to the previous one", step)
                _FALLBACKS.inc()
                obs_events.emit("checkpoint", action="fallback",
                                step=int(step), ok=False)
                self.delete_step(step)
                step = self.latest_valid_step()
                if step is None:
                    raise FileNotFoundError(
                        f"no VALID checkpoint left in {self.directory} "
                        "(all candidates failed checksum verification)")
        elif not self.verify(step):
            logger.error("explicitly requested checkpoint step %d fails "
                         "verification; restoring it anyway", step)
        t0 = time.perf_counter()

        def _done(result):
            _RESTORES.inc()
            obs_events.emit(
                "checkpoint", action="restore", step=int(step), ok=True,
                duration_ms=round((time.perf_counter() - t0) * 1e3, 3))
            return result

        try:
            restored = self._call(
                self.manager.restore, step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(state_template),
                    data_state=ocp.args.JsonRestore()))
            return _done((restored["state"],
                          dict(restored["data_state"])))
        except Exception:
            return _done((self._call(
                self.manager.restore, step,
                args=ocp.args.StandardRestore(state_template)), None))

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return sorted(int(s) for s in (self.manager.all_steps() or []))

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()
