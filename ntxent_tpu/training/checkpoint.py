"""Orbax checkpoint/resume for multi-day pretraining runs.

The reference has no persistence beyond benchmark JSON (SURVEY.md §5.4);
the BASELINE.json configs[2-4] runs (ImageNet/v5e-32 and up) require real
checkpoint/resume. Orbax handles multi-host coordination and atomic writes."""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = Path(directory).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False,
             data_state: dict | None = None) -> bool:
        """Save the TrainState, optionally with input-pipeline state.

        ``data_state`` (a small JSON-able dict, e.g. StreamingLoader.state())
        rides along as a composite item so resume can reposition the data
        iterator exactly instead of replaying host batches.
        """
        if data_state is not None:
            args: Any = ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                data_state=ocp.args.JsonSave(data_state))
        else:
            args = ocp.args.StandardSave(state)
        saved = self.manager.save(step, args=args, force=force)
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step,
                        self.directory)
        return saved

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        state, _ = self.restore_with_data_state(state_template, step)
        return state

    def restore_with_data_state(
            self, state_template: Any,
            step: int | None = None) -> tuple[Any, dict | None]:
        """(state, data_state-or-None); handles both checkpoint layouts
        (plain StandardSave and the composite written when data_state was
        provided)."""
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        try:
            restored = self.manager.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(state_template),
                    data_state=ocp.args.JsonRestore()))
            return restored["state"], dict(restored["data_state"])
        except Exception:
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(state_template)), None

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()
