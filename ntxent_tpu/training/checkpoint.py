"""Crash-safe checkpoint/resume: atomic native writes, async saves,
retention, and mirror replication.

The reference has no persistence beyond benchmark JSON (SURVEY.md §5.4);
the BASELINE.json configs[2-4] runs (ImageNet/v5e-32 and up) require real
checkpoint/resume. Earlier rounds wrapped orbax; this round (ISSUE 5)
rebuilds the path natively so every crash-safety property is owned and
auditable here:

* **Atomic steps** — a save writes into a hidden ``.tmp-*`` staging dir,
  fsyncs every file *and* the directory, then ``rename``s it to
  ``<step>/`` and fsyncs the parent. A SIGKILL at any instant leaves
  either the complete old state or a staging dir the next manager init
  purges — a *torn* step dir is impossible, not merely detectable
  (``scripts/crash_audit.sh`` kills a live run mid-save and proves it).
* **Checksum manifests** — every save records per-file size + CRC32 in a
  sidecar ``manifests.json``; ``verify()`` re-checksums a step, restore
  falls back past corrupt steps to the newest VALID one, and
  ``latest_valid_step()`` feeds the supervisor's rollback tier
  (resilience/supervisor.py). Atomicity covers the write; the manifest
  covers everything after it (bit rot, chaos truncation, bad mounts).
* **Async saves** — ``AsyncCheckpointer`` snapshots the state to host
  (one device→host copy) and hands serialization + fsync to a bounded
  background writer: the train loop blocks only when a save is already
  in flight. Queue depth, blocked time, and overlapped write time ride
  the obs registry (``checkpoint_queue_depth`` et al.).
* **Retention** — ``RetentionPolicy`` (keep-last-k + keep-every-n) GCs
  old steps after each save, manifest-aware: the newest VALID step is
  never deleted, even when newer-but-corrupt steps exist.
* **Replication** — ``mirror_dir`` copies every retained step to a
  secondary directory (atomically, same staging discipline); restore
  falls back to the mirror when the primary copy is corrupt or missing.
* **Emergency saves** — ``AsyncCheckpointer.emergency_save`` drains the
  writer and saves synchronously; ``trainer.fit`` uses it on the
  SIGTERM/preemption path (PreemptionGuard → stop_fn → fit's final
  save), so a preempted run's last step is durable before exit even
  when normal saves are async.
* **Topology portability (elastic restore)** — every save records its
  logical placement in a ``topology.json`` sidecar (per-leaf
  PartitionSpec tree over flattened state-dict paths + mesh shape/axis
  names/device count; parallel/mesh.py owns the vocabulary). Restore
  compares it against the ambient mesh: on a mismatch the host-gathered
  values are re-placed under the NEW mesh's NamedShardings
  (``reshard="gather_replace"`` on the restore event,
  ``checkpoint_reshard_total``/``_ms`` in the registry) — a checkpoint
  taken on N devices restores onto M, the restart mode preemptible
  fleets actually exercise. Pre-elastic checkpoints (no sidecar) restore
  exactly as before, with a warning.

A ``RetryPolicy`` (resilience/retry.py) can wrap the physical write, and
``save`` reports filesystem failures by returning False (plus a
``checkpoint`` event with ``ok=false`` and a failure counter) instead of
killing the run — skipping one checkpoint is recoverable; dying mid-run
is what this layer exists to prevent. Fault injection:
``resilience.faults.truncate_checkpoint_file`` (corruption) and
``FaultInjector.on_checkpoint_write`` (``diskfull@N`` → ENOSPC in the
writer); ``NTXENT_CKPT_SLOW_MS`` throttles the physical write so chaos
harnesses can land a kill deterministically mid-save.

Serialization is ``flax.serialization`` msgpack of the host state dict —
deterministic bytes (the crash audit compares final checkpoints of a
killed-and-resumed run against an uninterrupted one CRC-for-CRC).
Restore places every leaf onto the restore template's sharding, so
elastic resume across mesh sizes keeps working. The native backend
requires fully-addressable arrays (single-controller / replicated);
multi-host sharded runs save from process 0 only.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import queue as queue_mod
import shutil
import threading
import time
import uuid
import zlib
from collections.abc import Callable
from pathlib import Path
from typing import Any

import jax
import numpy as np
from flax import serialization as flax_ser

from ..obs import events as obs_events
from ..obs.registry import default_registry
from ..parallel.mesh import (
    mesh_topology,
    place_with_specs,
    resolve_restore_specs,
    tree_partition_specs,
)
from ..resilience.retry import RetryBudgetExceeded

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager", "AsyncCheckpointer", "RetentionPolicy"]

# Registry series (ISSUE 3): save/restore/CRC-fallback used to be
# logger-only, so a run quietly skipping every save (full disk, bad
# mount) was indistinguishable from a healthy one on any scrape.
_SAVES = default_registry().counter(
    "checkpoint_saves_total", "successful checkpoint saves")
_SAVE_FAILURES = default_registry().counter(
    "checkpoint_save_failures_total",
    "checkpoint saves skipped on filesystem errors")
_RESTORES = default_registry().counter(
    "checkpoint_restores_total", "checkpoint restores")
_FALLBACKS = default_registry().counter(
    "checkpoint_corrupt_fallbacks_total",
    "corrupt checkpoints skipped by the restore CRC fallback")
_SAVE_MS = default_registry().histogram(
    "checkpoint_save_ms", "wall time of one checkpoint save")
# ISSUE 5 series: the async writer and its interaction with the train loop.
_QUEUE_DEPTH = default_registry().gauge(
    "checkpoint_queue_depth",
    "async checkpoint saves queued or in flight")
_ASYNC_SAVES = default_registry().counter(
    "checkpoint_async_saves_total",
    "saves handed to the background writer")
_BLOCKED_MS = default_registry().histogram(
    "checkpoint_save_blocked_ms",
    "train-loop time spent waiting for an in-flight async save")
_OVERLAP_MS = default_registry().histogram(
    "checkpoint_save_overlap_ms",
    "background-writer wall time per save (hidden under compute)")
_GC_DELETED = default_registry().counter(
    "checkpoint_gc_deleted_total",
    "checkpoint steps removed by the retention policy")
_MIRROR_COPIES = default_registry().counter(
    "checkpoint_mirror_copies_total",
    "checkpoint steps replicated to the mirror directory")
_MIRROR_FAILURES = default_registry().counter(
    "checkpoint_mirror_failures_total",
    "mirror replications skipped on filesystem errors")
_MIRROR_RESTORES = default_registry().counter(
    "checkpoint_mirror_restores_total",
    "restores served from the mirror after primary corruption/loss")
# ISSUE 6 series: elastic restore across topology changes.
_RESHARDS = default_registry().counter(
    "checkpoint_reshard_total",
    "restores that re-placed state onto a mesh differing from the "
    "recorded save-time topology")
_RESHARD_MS = default_registry().histogram(
    "checkpoint_reshard_ms",
    "wall time of the host-gather -> re-place step on topology-"
    "mismatched restores")

_MANIFEST_NAME = "manifests.json"
_TMP_PREFIX = ".tmp-"
_STATE_FILE = "state.msgpack"
_DATA_STATE_FILE = "data_state.json"
_META_FILE = "meta.json"
_TOPOLOGY_FILE = "topology.json"


def _crc32_file(path: Path, chunk: int = 1 << 20) -> int:
    value = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return value
            value = zlib.crc32(block, value)


def _fsync_path(path: Path) -> None:
    """fsync a file or directory (directory fsync persists the entry).

    ``NTXENT_CKPT_NO_FSYNC=1`` skips the sync — a BENCH-ONLY knob for
    A/B runs on filesystems with jittery fsync latency (the write
    throttle models IO instead). Never set it on a real run: it trades
    power-loss durability for nothing.
    """
    if os.environ.get("NTXENT_CKPT_NO_FSYNC") == "1":
        return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _staging_name(step: int) -> str:
    """``.tmp-<step>-<pid>-<uuid>``: the PID lets ``purge_tmp`` tell a
    killed writer's debris from another live process's in-flight save."""
    return f"{_TMP_PREFIX}{int(step)}-{os.getpid()}-{uuid.uuid4().hex[:8]}"


def _staging_pid(name: str) -> int | None:
    parts = name[len(_TMP_PREFIX):].split("-")
    if len(parts) >= 3 and parts[1].isdigit():
        return int(parts[1])
    return None


def _pid_alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, owned by someone else
    except OSError:
        return False


def _write_delay_s() -> float:
    """Chaos/bench throttle for the physical write (NTXENT_CKPT_SLOW_MS):
    lets crash harnesses land a SIGKILL deterministically mid-save and
    benches model a slow filesystem. 0 (default) = no delay."""
    try:
        return max(0.0, float(os.environ.get("NTXENT_CKPT_SLOW_MS", "0"))
                   ) / 1e3
    except ValueError:
        return 0.0


@dataclasses.dataclass(frozen=True)
class _Snapshot:
    """A host-side copy of a train state (pure numpy state dict), ready
    for background serialization with no device or donation hazards.
    ``topology`` is the save-time logical placement (PartitionSpec tree +
    mesh identity, parallel/mesh.py) that makes the checkpoint portable
    across mesh changes."""

    state_dict: dict
    topology: dict | None = None


def snapshot_state(state: Any, *,
                   keep_ef_residual: bool = False) -> _Snapshot:
    """Copy a (possibly device-resident) state pytree to host numpy.

    Slim by DEFAULT (ISSUE 13 satellite, the ROADMAP item 1 follow-up):
    a populated ``ef_residual`` field is dropped from the snapshot
    BEFORE the device→host copy — the error-feedback residual is a
    P-stacked float32 copy of every parameter — P× the f32 param
    payload per save — holding carry-over compression noise that
    restore resets to zeros on any topology change anyway. Dropping it
    saves both the transfer and the disk; the tolerant restore path
    (``_from_bytes_tolerant``) already fills the missing field with the
    template's zeros. The default lives HERE, not only on the manager,
    so the pre-snapshot donation pattern (``snap = snapshot_state(s)``
    then ``manager.save(step, snap)`` — save's ``_Snapshot``
    early-return never re-applies the manager's flag) gets the same
    slim behavior. ``keep_ef_residual=True`` — what
    ``CheckpointManager(save_ef_residual=True)`` passes — is the opt-in
    for runs that want exact same-topology resume of the residual too.

    This is the only part of an async save that runs on the caller's
    thread: one device→host COPY, after which the training loop may
    donate/overwrite the live buffers freely. The copy must be real:
    on CPU backends ``device_get`` returns zero-copy numpy VIEWS of the
    device buffers, and a donated train step would overwrite them under
    the background writer — serializing a later step's params under this
    step's label (caught by the crash audit's CRC comparison; np.array's
    forced copy is the fix).

    The snapshot also records the state's LOGICAL placement (per-leaf
    PartitionSpecs over flattened state-dict paths, plus the mesh's
    shape/axis names/device count): the host copy is by construction a
    full gather, so placement is the only thing a topology change would
    otherwise lose. Restore compares it against the ambient mesh and
    re-places under the new mesh's NamedShardings when they differ.
    """
    if isinstance(state, _Snapshot):
        return state
    state_dict = flax_ser.to_state_dict(state)
    if not keep_ef_residual and isinstance(state_dict, dict) \
            and state_dict.get("ef_residual") is not None:
        # Pop only a POPULATED residual: a float32-era None field must
        # keep round-tripping exactly as it always has.
        state_dict = dict(state_dict)
        state_dict.pop("ef_residual")
    topology = tree_partition_specs(state_dict)

    def to_host_copy(leaf):
        if isinstance(leaf, jax.Array):
            if not leaf.is_fully_addressable:
                raise ValueError(
                    "native checkpoint backend requires fully-"
                    "addressable arrays (single-controller or "
                    "replicated); shard this save across hosts before "
                    "reaching here")
            return np.array(leaf)  # forced copy, never a view
        if isinstance(leaf, np.ndarray):
            return leaf.copy()
        return leaf

    return _Snapshot(jax.tree.map(to_host_copy, state_dict), topology)


@dataclasses.dataclass(frozen=True)
class RetentionPolicy:
    """keep-last-k + keep-every-n garbage collection for checkpoint dirs.

    ``keep_last`` newest steps always survive; steps divisible by
    ``keep_every`` (when set) survive as long-horizon anchors; and the
    newest VALID step (per checksum manifest) is NEVER collected — when
    the newest saves are corrupt, the only restorable state must outlive
    the policy. ``keep_last=None``/0 disables count-based GC entirely.
    """

    keep_last: int | None = 3
    keep_every: int | None = None

    def keep(self, steps: list[int],
             is_valid: Callable[[int], bool]) -> set[int]:
        """The subset of ``steps`` that must survive GC."""
        steps = sorted(set(int(s) for s in steps))
        if not steps:
            return set()
        if not self.keep_last or len(steps) <= int(self.keep_last):
            # Nothing can be collected: skip the newest-valid CRC scan.
            return set(steps)
        kept = set(steps[-int(self.keep_last):])
        if self.keep_every:
            kept |= {s for s in steps if s % int(self.keep_every) == 0}
        newest_valid = next((s for s in reversed(steps) if is_valid(s)),
                            None)
        if newest_valid is not None:
            kept.add(newest_valid)
        return kept


class _UnreadableStepError(RuntimeError):
    """A step that passes CRC verification but cannot be deserialized
    (foreign format / manifest-less torn bytes). Never auto-deleted."""


class _NativeBackend:
    """The physical checkpoint store: atomic step dirs under ``root``.

    Split from the ``CheckpointManager`` facade so the retry policy and
    the failure-surfacing contract wrap exactly the operations that touch
    the filesystem (tests monkeypatch ``manager.save``/``delete`` here).
    """

    def __init__(self, root: Path, fault_hook: Callable | None = None):
        self.root = root
        self.fault_hook = fault_hook
        self.last_write_manifest: tuple[int, dict] | None = None
        self.root.mkdir(parents=True, exist_ok=True)
        self.purge_tmp()

    # -- enumeration -----------------------------------------------------
    def step_dirs(self) -> dict[int, Path]:
        out = {}
        try:
            entries = list(self.root.iterdir())
        except OSError:
            return out
        for p in entries:
            if p.is_dir() and not p.name.startswith(_TMP_PREFIX):
                digits = "".join(ch for ch in p.name if ch.isdigit())
                if digits:
                    out[int(digits)] = p
        return out

    def all_steps(self) -> list[int]:
        return sorted(self.step_dirs())

    def latest_step(self) -> int | None:
        steps = self.all_steps()
        return steps[-1] if steps else None

    def step_dir(self, step: int) -> Path | None:
        return self.step_dirs().get(int(step))

    def purge_tmp(self) -> None:
        """Remove staging dirs a KILLED writer left behind, called at
        init. Staging names embed the writer's PID
        (``.tmp-<step>-<pid>-<uuid>``): a dir whose owner is still alive
        in another process (e.g. ``ntxent-eval`` opening a directory a
        trainer is actively writing) is someone's in-flight save, not
        debris, and deleting it would fail that checkpoint."""
        try:
            entries = list(self.root.iterdir())
        except OSError:
            return
        for p in entries:
            if not (p.is_dir() and p.name.startswith(_TMP_PREFIX)):
                continue
            pid = _staging_pid(p.name)
            if pid is not None and pid != os.getpid() \
                    and _pid_alive(pid):
                logger.info("keeping checkpoint staging dir %s: its "
                            "writer (pid %d) is still alive", p, pid)
                continue
            logger.warning("purging abandoned checkpoint staging dir "
                           "%s (killed mid-save)", p)
            shutil.rmtree(p, ignore_errors=True)

    # -- physical write --------------------------------------------------
    def save(self, step: int, snapshot: _Snapshot,
             data_state: dict | None = None, force: bool = False) -> bool:
        """Atomically write one step dir. Raises OSError on filesystem
        trouble (the facade turns that into the skip-a-checkpoint
        contract). ``force`` replaces an existing step dir. On success,
        ``last_write_manifest`` holds (step, manifest) computed from the
        bytes just written — the facade records it without re-reading a
        possibly multi-GB file from disk."""
        if self.fault_hook is not None:
            self.fault_hook()
        step = int(step)
        final = self.root / str(step)
        tmp = self.root / _staging_name(step)
        tmp.mkdir()
        try:
            files: dict[str, list] = {}

            def write(name: str, payload: bytes) -> None:
                with open(tmp / name, "wb") as f:
                    f.write(payload)
                files[name] = [len(payload), zlib.crc32(payload)]

            blob = flax_ser.msgpack_serialize(snapshot.state_dict)
            write(_STATE_FILE, blob)
            delay = _write_delay_s()
            if delay:
                time.sleep(delay)
            if data_state is not None:
                write(_DATA_STATE_FILE, json.dumps(data_state).encode())
            if snapshot.topology is not None:
                # The elastic-restore sidecar: logical PartitionSpec tree
                # + mesh identity, CRC'd like every other payload file.
                write(_TOPOLOGY_FILE,
                      json.dumps(snapshot.topology).encode())
            write(_META_FILE,
                  json.dumps({"step": step, "format": 1}).encode())
            for p in tmp.iterdir():
                _fsync_path(p)
            _fsync_path(tmp)
            if final.exists():
                if not force:
                    # Same-step re-save without force: the existing dir
                    # is the truth; drop the staging copy.
                    shutil.rmtree(tmp, ignore_errors=True)
                    return False
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_path(self.root)
            self.last_write_manifest = (step, {"files": files})
            return True
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            raise

    def delete(self, step: int) -> None:
        step_dir = self.step_dir(step)
        if step_dir is not None:
            shutil.rmtree(step_dir)

    # Lifecycle parity with the old orbax-backed manager: the native
    # backend has no background machinery of its own (AsyncCheckpointer
    # owns the writer thread), so both are no-ops.
    def wait_until_finished(self) -> None:
        return None

    def close(self) -> None:
        return None


def _read_step_payload(step_dir: Path) -> tuple[bytes, dict | None]:
    with open(step_dir / _STATE_FILE, "rb") as f:
        blob = f.read()
    data_state = None
    ds_path = step_dir / _DATA_STATE_FILE
    if ds_path.exists():
        with open(ds_path) as f:
            data_state = json.load(f)
    return blob, data_state


def _place_like(template: Any, restored: Any) -> Any:
    """Place restored host values onto the template's shardings (the
    elastic-resume contract the orbax path provided: the restore template
    decides device layout, including resharding across mesh sizes)."""

    def place(t, v):
        if isinstance(t, jax.Array):
            return jax.device_put(v, t.sharding)
        return v

    return jax.tree.map(place, template, restored)


def _from_bytes_tolerant(template: Any, blob: bytes) -> Any:
    """``flax.serialization.from_bytes`` that survives FIELD drift
    between the template and the checkpoint (ISSUE 12).

    The quantized-collective error-feedback residual added a TrainState
    field (``ef_residual``) that pre-quantization checkpoints do not
    carry — and a strict ``from_state_dict`` refuses the structural
    mismatch, turning every old checkpoint into a crash for exactly the
    runs the feature targets (resume an existing run with
    ``--collective-dtype int8``). ``ef_residual`` is the ONLY
    reconciled field — it is carry-over compression noise, reset to
    zeros on topology changes anyway, so it is never worth failing a
    restore over. Every OTHER structural mismatch (a missing param,
    opt_state, step — top-level or nested) stays a loud
    ``from_state_dict`` failure: that is corruption, not drift.

    * the template has the field, the checkpoint lacks it entirely or
      saved it as None (a pre-quantization / float32-era save): the
      template's fresh zeros are used, with a warning;
    * the checkpoint carries residual state the template has no field
      or ``None`` for (resuming an int8 run at float32): dropped, with
      a warning;
    * leaf shapes disagree (a topology change resized the per-device
      stack): reset to the template's zeros, with a warning.
    """
    state_dict = flax_ser.msgpack_restore(blob)
    template_sd = flax_ser.to_state_dict(template)
    if isinstance(state_dict, dict) and isinstance(template_sd, dict):
        if "ef_residual" in template_sd \
                and "ef_residual" not in state_dict:
            logger.warning(
                "checkpoint carries no error-feedback residual state "
                "(slim save — the default — or saved before the field "
                "existed); starting at zero residual")
            state_dict["ef_residual"] = template_sd["ef_residual"]
        elif "ef_residual" in state_dict \
                and "ef_residual" not in template_sd:
            logger.warning(
                "checkpoint carries error-feedback residual state the "
                "current run's state has no field for; dropping it")
            state_dict.pop("ef_residual")
        saved_ef = state_dict.get("ef_residual")
        template_ef = template_sd.get("ef_residual")
        if (saved_ef is None) != (template_ef is None):
            # A float32-era save (field None) restored into an
            # error-feedback run, or the reverse: the residual is
            # carry-over compression noise, never worth failing a
            # restore over.
            logger.warning(
                "checkpoint %s error-feedback residual state; starting "
                "at zero residual",
                "carries no" if saved_ef is None else "carries")
            state_dict["ef_residual"] = template_ef
        elif saved_ef is not None and template_ef is not None:
            t_leaves = jax.tree_util.tree_leaves(template_ef)
            s_leaves = jax.tree_util.tree_leaves(saved_ef)
            shapes_differ = len(t_leaves) != len(s_leaves) or any(
                getattr(t, "shape", None) != getattr(s, "shape", None)
                for t, s in zip(t_leaves, s_leaves))
            if shapes_differ:
                logger.warning(
                    "checkpoint's error-feedback residual does not match "
                    "the current topology; resetting to zero residual")
                state_dict["ef_residual"] = template_ef
    return flax_ser.from_state_dict(template, state_dict)


def _template_mesh(template: Any):
    """The mesh the template's committed leaves live on (None when no
    leaf carries a NamedSharding — a fresh single-device template)."""
    from jax.sharding import NamedSharding

    for leaf in jax.tree_util.tree_leaves(template):
        sharding = getattr(leaf, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return sharding.mesh
    return None


def _topology_differs(recorded: dict | None, ambient: dict) -> bool:
    """Did the world change between save and restore? Device count is
    the primary signal; mesh shape/axis names are compared only when
    BOTH sides actually had a mesh — a side with no NamedSharding leaves
    records shape=None, and treating None != [8] as a topology change
    would stamp every uncommitted-template restore on an unchanged host
    (eval/serve paths) as a spurious ``gather_replace``, polluting the
    very counter the elastic audit treats as proof of a real re-shard."""
    if not recorded:
        return False
    if recorded.get("device_count") != ambient.get("device_count"):
        return True
    if recorded.get("shape") is None or ambient.get("shape") is None:
        return False
    return recorded.get("shape") != ambient.get("shape") \
        or recorded.get("axis_names") != ambient.get("axis_names")


def _place_elastic(template: Any, restored: Any, mesh, topology: dict):
    """Re-place host-gathered values under the AMBIENT mesh after a
    topology change. The template's committed shardings stay
    authoritative (the new incarnation's train step was built for them);
    the recorded logical spec tree decides placement only for leaves the
    template left uncommitted — resolved against the new mesh with
    missing axes / non-dividing dims falling back toward replication
    (parallel/mesh.py resolve_restore_specs)."""
    from jax.sharding import NamedSharding

    template_sd = flax_ser.to_state_dict(template)
    restored_sd = flax_ser.to_state_dict(restored)
    specs = resolve_restore_specs(topology, mesh, restored_sd)

    def place(t, v, spec):
        sharding = getattr(t, "sharding", None)
        if isinstance(sharding, NamedSharding):
            return jax.device_put(v, sharding)
        if isinstance(t, (jax.Array, np.ndarray)):
            # Uncommitted template leaf (fresh device array or host
            # numpy): the recorded logical spec decides placement.
            return jax.device_put(v, NamedSharding(mesh, spec))
        return v

    placed = jax.tree.map(place, template_sd, restored_sd, specs)
    return flax_ser.from_state_dict(template, placed)


class CheckpointManager:
    """Crash-safe checkpoint store for TrainState pytrees.

    Synchronous facade over the native atomic backend; wrap in
    ``AsyncCheckpointer`` to move serialization off the train loop.

    ``retry_policy`` (resilience.RetryPolicy) retries the physical write/
    read on transient errors. ``verify_writes=True`` (default) records a
    per-save content manifest used by ``verify`` / ``latest_valid_step``
    / the restore fallback. ``max_to_keep``/``keep_every`` set the
    ``RetentionPolicy`` (``max_to_keep=None`` keeps everything).
    ``mirror_dir`` replicates every save to a secondary directory and
    lets restore fall back to it when the primary copy is corrupt or
    missing. ``fault_hook`` (chaos) runs at the start of every physical
    write — ``FaultInjector.on_checkpoint_write`` raises ENOSPC through
    it for the ``diskfull@N`` plan entry.
    """

    def __init__(self, directory: str | Path, max_to_keep: int | None = 3,
                 save_interval_steps: int = 1, retry_policy=None,
                 verify_writes: bool = True,
                 keep_every: int | None = None,
                 mirror_dir: str | Path | None = None,
                 fault_hook: Callable | None = None,
                 save_ef_residual: bool = False):
        self.directory = Path(directory).absolute()
        self.retry_policy = retry_policy
        self.verify_writes = verify_writes
        # Opt-in persistence of the P-stacked error-feedback residual
        # (ISSUE 13 satellite): droppable carry-over noise by default —
        # see snapshot_state.
        self.save_ef_residual = save_ef_residual
        self.save_interval_steps = max(1, int(save_interval_steps))
        self.retention = RetentionPolicy(keep_last=max_to_keep,
                                         keep_every=keep_every)
        self.manager = _NativeBackend(self.directory,
                                      fault_hook=fault_hook)
        self.mirror_dir = Path(mirror_dir).absolute() \
            if mirror_dir is not None else None
        self._mirror = _NativeBackend(self.mirror_dir) \
            if self.mirror_dir is not None else None
        self._has_any_step = False  # should_save's cached probe

    def _call(self, fn, *args, **kwargs):
        if self.retry_policy is not None:
            return self.retry_policy.call(fn, *args, **kwargs)
        return fn(*args, **kwargs)

    # -- content manifests -------------------------------------------------
    def _manifest_path(self, root: Path | None = None) -> Path:
        return (root or self.directory) / _MANIFEST_NAME

    def _load_manifests(self, root: Path | None = None) -> dict:
        try:
            with open(self._manifest_path(root)) as f:
                return json.load(f)
        except (OSError, json.JSONDecodeError):
            return {}

    def _store_manifests(self, manifests: dict,
                         root: Path | None = None) -> None:
        target = self._manifest_path(root)
        tmp = target.with_suffix(".tmp")
        with open(tmp, "w") as f:
            json.dump(manifests, f)
        os.replace(tmp, target)

    def _step_dir(self, step: int) -> Path | None:
        return self.manager.step_dir(step)

    def _compute_manifest(self, step_dir: Path | None) -> dict | None:
        if step_dir is None or not step_dir.is_dir():
            return None
        files = {}
        for p in sorted(step_dir.rglob("*")):
            if p.is_file():
                rel = str(p.relative_to(step_dir))
                files[rel] = [p.stat().st_size, _crc32_file(p)]
        return {"files": files}

    def _record_manifest(self, step: int, root: Path | None = None,
                         manifest: dict | None = None) -> None:
        backend = self._mirror if root is not None \
            and root == self.mirror_dir else self.manager
        if manifest is None:
            # Prefer the CRCs the writer computed from the bytes it just
            # wrote: re-reading a multi-GB checkpoint from disk to
            # manifest it would double the save's IO.
            last = getattr(self.manager, "last_write_manifest", None)
            if last is not None and last[0] == int(step):
                manifest = last[1]
            else:
                manifest = self._compute_manifest(backend.step_dir(step))
        if manifest is None:
            logger.warning("no step dir found for step %d; skipping "
                           "checksum manifest", step)
            return
        manifests = self._load_manifests(root)
        manifests[str(step)] = manifest
        live = {str(s) for s in backend.all_steps()}
        manifests = {k: v for k, v in manifests.items() if k in live}
        self._store_manifests(manifests, root)

    def _verify_in(self, backend: _NativeBackend, root: Path,
                   step: int) -> bool:
        recorded = self._load_manifests(root).get(str(step))
        step_dir = backend.step_dir(step)
        if recorded is None:
            # No manifest (verify_writes off, or a crash between rename
            # and manifest update): an existing, atomically-renamed step
            # is complete — unverifiable is not invalid.
            if step_dir is None:
                return False
            logger.debug("step %d has no checksum manifest; treating as "
                         "valid", step)
            return True
        actual = self._compute_manifest(step_dir)
        if actual is None:
            return False
        want, got = recorded["files"], actual["files"]
        for rel, meta in want.items():
            if rel not in got or got[rel] != meta:
                logger.error(
                    "checkpoint step %d failed verification at %s "
                    "(want size/crc %s, got %s)", step, rel, meta,
                    got.get(rel))
                return False
        return True

    def verify(self, step: int) -> bool:
        """Re-checksum a saved step against its manifest.

        True for steps with no recorded manifest (unverifiable, not
        invalid — atomic renames mean an existing step dir is complete).
        False on any missing file, size drift, or CRC mismatch.
        """
        return self._verify_in(self.manager, self.directory, step)

    def mirror_verify(self, step: int) -> bool:
        """``verify`` against the mirror copy (False without a mirror)."""
        if self._mirror is None:
            return False
        return self._verify_in(self._mirror, self.mirror_dir, step)

    def latest_valid_step(self) -> int | None:
        """Newest step that passes ``verify`` in the primary or the
        mirror (the supervisor's rollback target); None when nothing
        verifies anywhere."""
        candidates = set(self.manager.all_steps())
        if self._mirror is not None:
            candidates |= set(self._mirror.all_steps())
        for step in sorted(candidates, reverse=True):
            if self.verify(step) and self._step_dir(step) is not None:
                return int(step)
            if self.mirror_verify(step):
                return int(step)
        return None

    def delete_step(self, step: int, reason: str = "corrupt") -> None:
        """Remove a step and its manifest entry (primary only — the
        mirror keeps its copy as the redundancy this feature exists for).

        The manifest entry is dropped only once the files are actually
        gone: a failed deletion must keep failing ``verify`` (a
        manifest-less step counts as valid, so popping the entry while
        the truncated files survive would launder corruption into the
        restore fallback's 'newest valid' answer).
        """
        try:
            self.manager.delete(step)
        except Exception:
            step_dir = self._step_dir(step)
            if step_dir is not None:
                shutil.rmtree(step_dir, ignore_errors=True)
        if self._step_dir(step) is not None:
            logger.error("could not delete %s checkpoint at step %d; "
                         "keeping its manifest so it stays invalid",
                         reason, step)
            return
        manifests = self._load_manifests()
        if manifests.pop(str(step), None) is not None:
            try:
                self._store_manifests(manifests)
            except OSError as e:
                # Housekeeping only: a stale entry for a deleted step
                # just makes verify() return False for it (dir gone) —
                # never worth raising out of a save/restore.
                logger.error("manifest rewrite after deleting step %d "
                             "failed (%s)", step, e)
        logger.warning("deleted %s checkpoint at step %d", reason, step)

    # -- retention + replication -------------------------------------------
    def gc(self, just_saved: int | None = None) -> list[int]:
        """Apply the retention policy; returns the steps deleted.
        ``just_saved`` marks a step written (and manifested) moments ago
        as valid without re-reading its bytes — GC runs after every save
        and must not re-CRC the newest multi-GB checkpoint each time."""
        steps = self.manager.all_steps()

        def is_valid(step: int) -> bool:
            if just_saved is not None and step == int(just_saved):
                return True
            return self.verify(step)

        kept = self.retention.keep(steps, is_valid)
        deleted = []
        for step in steps:
            if step in kept:
                continue
            self.delete_step(step, reason="retired")
            if self._step_dir(step) is None:
                deleted.append(step)
                _GC_DELETED.inc()
        if self._mirror is not None:
            m_steps = self._mirror.all_steps()

            def m_is_valid(step: int) -> bool:
                # The just-replicated copy is byte-identical to the
                # just-written primary: no re-CRC of a fresh multi-GB
                # mirror copy on every save.
                if just_saved is not None and step == int(just_saved):
                    return True
                return self.mirror_verify(step)

            m_kept = self.retention.keep(m_steps, m_is_valid)
            m_manifests = self._load_manifests(self.mirror_dir)
            changed = False
            for step in m_steps:
                if step in m_kept:
                    continue
                try:
                    self._mirror.delete(step)
                except OSError:
                    continue
                if m_manifests.pop(str(step), None) is not None:
                    changed = True
            if changed:
                try:
                    self._store_manifests(m_manifests, self.mirror_dir)
                except OSError:
                    pass
        if deleted:
            logger.info("retention GC removed steps %s (policy %s)",
                        deleted, self.retention)
        return deleted

    def _replicate(self, step: int) -> None:
        """Copy one saved step to the mirror (atomic: stage + rename).
        Mirror trouble must never fail the primary save — it is logged,
        counted, and the next save tries again."""
        if self._mirror is None:
            return
        src = self._step_dir(step)
        if src is None:
            return
        tmp = self.mirror_dir / _staging_name(step)
        try:
            shutil.copytree(src, tmp)
            for p in tmp.rglob("*"):
                if p.is_file():
                    _fsync_path(p)
            _fsync_path(tmp)
            final = self.mirror_dir / str(int(step))
            if final.exists():
                shutil.rmtree(final)
            os.rename(tmp, final)
            _fsync_path(self.mirror_dir)
            if self.verify_writes:
                # The copy holds byte-identical files: record the
                # primary's manifest rather than re-CRCing the copy.
                self._record_manifest(
                    step, root=self.mirror_dir,
                    manifest=self._load_manifests().get(str(step)))
            _MIRROR_COPIES.inc()
        except OSError as e:
            shutil.rmtree(tmp, ignore_errors=True)
            _MIRROR_FAILURES.inc()
            logger.error("mirror replication of step %d failed (%s) — "
                         "primary save stands", step, e)

    # -- save / restore ----------------------------------------------------
    def should_save(self, step: int, force: bool = False) -> bool:
        """The save-cadence filter (``fit``'s step hook calls ``save``
        every step; this keeps the interval semantics in one place).
        The FIRST save of an empty directory always lands — a fresh run
        gets a restore point immediately instead of running a full
        interval exposed (the cadence orbax used). The directory probe
        behind that rule is cached once a step exists: this method runs
        on the train hot path every step. Pure query — accepting a save
        goes through ``_claim_save`` so the first-save rule fires once
        even while an async writer is still committing it."""
        return self._cadence(step, force, claim=False)

    def _claim_save(self, step: int, force: bool = False) -> bool:
        return self._cadence(step, force, claim=True)

    def _cadence(self, step: int, force: bool, claim: bool) -> bool:
        if force:
            return True
        if int(step) % self.save_interval_steps == 0:
            return True
        if self._has_any_step:
            return False
        if self.manager.latest_step() is not None:
            self._has_any_step = True
            return False
        # Empty directory: this save IS the first one. A claiming caller
        # marks it accepted NOW, not at commit time: an async writer may
        # still be serializing it when the next step's hook probes again,
        # and without the claim that probe would accept a duplicate
        # "first save" whose eventual cadence-filtered False reads as a
        # write failure. A failed claim is released in ``save``'s error
        # path so the rule can fire again.
        if claim:
            self._has_any_step = True
        return True

    def save(self, step: int, state: Any, force: bool = False,
             data_state: dict | None = None, emergency: bool = False,
             _prefiltered: bool = False) -> bool:
        """Save the TrainState, optionally with input-pipeline state.

        ``data_state`` (a small JSON-able dict, e.g.
        StreamingLoader.state()) rides along in the step dir so resume
        can reposition the data iterator exactly instead of replaying
        host batches. ``state`` may be a live (device) pytree or a
        ``snapshot_state`` result.

        Returns False — after logging, bumping
        ``checkpoint_save_failures_total``, and emitting a ``checkpoint``
        event with ``ok=false`` — when the write hits a filesystem error
        (transient NFS/GCS blips survive a missed checkpoint; the next
        cadence point saves again). Raising here would kill a healthy
        training run over a recoverable IO fault.
        """
        step = int(step)
        if jax.process_count() > 1 and jax.process_index() != 0:
            return False  # single-writer: process 0 owns the directory
        if not _prefiltered and not self._claim_save(step, force):
            return False
        t0 = time.perf_counter()
        try:
            snapshot = snapshot_state(
                state, keep_ef_residual=self.save_ef_residual)
            saved = self._call(self.manager.save, step, snapshot,
                               data_state=data_state, force=force)
        except (OSError, RetryBudgetExceeded) as e:
            # RetryBudgetExceeded wraps the root OSError once a budgeted
            # retry_policy's wall clock runs out — same recoverable class,
            # and the skip-a-checkpoint contract must not depend on which
            # limit (attempts vs budget) tripped first.
            logger.error("checkpoint save at step %d failed (%s: %s) — "
                         "continuing without it", step,
                         type(e).__name__, e)
            _SAVE_FAILURES.inc()
            obs_events.emit("checkpoint", action="save", step=step,
                            ok=False, error=f"{type(e).__name__}: {e}")
            # Release a first-save claim should_save made for this call:
            # the directory is still empty, so the rule must fire again.
            self._has_any_step = self.manager.latest_step() is not None
            return False
        if saved:
            self._has_any_step = True
            if self.verify_writes:
                try:
                    self._record_manifest(step)
                except OSError as e:
                    logger.error("checksum manifest for step %d failed "
                                 "(%s); step stays unverifiable", step, e)
            try:
                self._replicate(step)
                self.gc(just_saved=step)
            except OSError as e:
                # Post-save housekeeping (replication, retention) must
                # not turn a DURABLE save into a dead training run.
                logger.error("post-save housekeeping for step %d failed "
                             "(%s) — the save itself stands", step, e)
            duration_ms = (time.perf_counter() - t0) * 1e3
            _SAVES.inc()
            _SAVE_MS.observe(duration_ms)
            obs_events.emit("checkpoint", action="save", step=step,
                            ok=True, forced=bool(force),
                            emergency=bool(emergency),
                            duration_ms=round(duration_ms, 3),
                            verified=bool(self.verify_writes))
            logger.info("checkpoint saved at step %d -> %s%s", step,
                        self.directory,
                        " (emergency)" if emergency else "")
        return saved

    def _restore_sources(self, step: int):
        """(backend, root, label) candidates for reading ``step``, primary
        first, mirror as the fallback the replication tier exists for."""
        yield self.manager, self.directory, "primary"
        if self._mirror is not None:
            yield self._mirror, self.mirror_dir, "mirror"

    def _load_step(self, step: int,
                   state_template: Any) -> tuple[Any, dict | None, str]:
        """Deserialize a step from the first WORKING source. Passing the
        CRC check is necessary but not sufficient (a lost manifest makes
        a torn file unverifiable-therefore-'valid'), so deserialization
        failure also disqualifies a source and the search falls through
        to the mirror. Raises ``_UnreadableStepError`` when a source
        PASSED verification but could not be read (a foreign/older
        checkpoint format, or a torn manifest-less file) — the fallback
        loop must NOT delete those, a CRC-clean foreign-format directory
        is not corruption — and FileNotFoundError when no source has a
        CRC-valid copy at all."""
        verified_but_unreadable = False
        for backend, root, label in self._restore_sources(step):
            step_dir = backend.step_dir(step)
            if step_dir is None:
                continue
            if not self._verify_in(backend, root, step):
                continue
            try:
                blob, data_state = self._call(_read_step_payload,
                                              step_dir)
                restored_host = _from_bytes_tolerant(state_template, blob)
            except (OSError, ValueError, KeyError, TypeError) as e:
                verified_but_unreadable = True
                logger.error(
                    "checkpoint step %d in %s is unreadable despite "
                    "passing verification (%s: %s)", step, root,
                    type(e).__name__, e)
                continue
            if label == "mirror":
                _MIRROR_RESTORES.inc()
                logger.warning("restoring step %d from the MIRROR (%s): "
                               "primary copy corrupt or missing", step,
                               self.mirror_dir)
            return restored_host, data_state, label
        if verified_but_unreadable:
            raise _UnreadableStepError(
                f"step {step} in {self.directory} passes verification "
                "but cannot be deserialized (foreign checkpoint format, "
                "or torn bytes with no manifest to catch them)")
        raise FileNotFoundError(
            f"step {step} has no valid copy in {self.directory}"
            + (f" or {self.mirror_dir}" if self._mirror else ""))

    def _load_topology(self, step: int) -> dict | None:
        """The step's recorded save-time topology (spec tree + mesh
        identity), from the first source that has it; None for
        pre-elastic checkpoints (no ``topology.json``)."""
        for backend, _root, _label in self._restore_sources(step):
            step_dir = backend.step_dir(step)
            if step_dir is None:
                continue
            path = step_dir / _TOPOLOGY_FILE
            try:
                with open(path) as f:
                    return json.load(f)
            except FileNotFoundError:
                continue
            except (OSError, json.JSONDecodeError) as e:
                logger.warning("unreadable %s for step %d (%s); treating "
                               "as pre-elastic", path, step, e)
                continue
        return None

    def restore(self, state_template: Any, step: int | None = None,
                mesh=None) -> Any:
        state, _ = self.restore_with_data_state(state_template, step,
                                                mesh=mesh)
        return state

    def restore_with_data_state(
            self, state_template: Any,
            step: int | None = None,
            mesh=None) -> tuple[Any, dict | None]:
        """(state, data_state-or-None), leaves placed onto the template's
        shardings.

        With ``step=None`` the newest step is verified first; corrupt
        primary steps fall back to their mirror copy, then — deleting the
        corrupt primary — to the newest older VALID step (the rollback
        path the supervisor leans on). An explicit ``step`` is restored
        as-is after a verification failure is logged — the caller asked
        for that exact step.

        Elastic restore: every step carries its save-time topology
        (``topology.json``: logical PartitionSpec tree + mesh shape/axis
        names/device count). When that differs from the ambient world —
        ``mesh``, or the mesh the template's committed leaves live on —
        the host-gathered values are re-placed under the NEW mesh's
        NamedShardings (``reshard="gather_replace"`` on the restore
        event, ``checkpoint_reshard_total``/``checkpoint_reshard_ms`` in
        the registry): a checkpoint taken on N devices restores onto M.
        Pre-elastic checkpoints (no topology sidecar) keep the old
        behavior — template placement, with a warning.
        """
        t0 = time.perf_counter()
        chosen: tuple[Any, dict | None, str] | None = None
        if step is None:
            candidates = set(self.manager.all_steps())
            if self._mirror is not None:
                candidates |= set(self._mirror.all_steps())
            if not candidates:
                raise FileNotFoundError(
                    f"no checkpoint in {self.directory}")
            unreadable: _UnreadableStepError | None = None
            for cand in sorted(candidates, reverse=True):
                try:
                    chosen = self._load_step(cand, state_template)
                    step = cand
                    break
                except _UnreadableStepError as e:
                    # NOT corruption we can prove: deleting here would
                    # destroy e.g. a whole directory of older-format
                    # checkpoints one candidate at a time. Skip it, keep
                    # the bytes, and surface the reason if nothing works.
                    unreadable = e
                    obs_events.emit("checkpoint", action="fallback",
                                    step=int(cand), ok=False,
                                    reason="unreadable")
                except FileNotFoundError:
                    logger.error("checkpoint at step %d is corrupt in "
                                 "every replica; falling back to the "
                                 "previous one", cand)
                    _FALLBACKS.inc()
                    obs_events.emit("checkpoint", action="fallback",
                                    step=int(cand), ok=False)
                    self.delete_step(cand)
            if chosen is None:
                if unreadable is not None:
                    raise unreadable
                raise FileNotFoundError(
                    f"no VALID checkpoint left in {self.directory} "
                    "(all candidates failed checksum verification)")
        else:
            if not self.verify(step) and not self.mirror_verify(step):
                logger.error("explicitly requested checkpoint step %d "
                             "fails verification; restoring it anyway",
                             step)
                step_dir = self._step_dir(step)
                source = "primary"
                if step_dir is None and self._mirror is not None:
                    # The caller asked for this exact step: honor that
                    # from the mirror when the primary copy is gone.
                    step_dir = self._mirror.step_dir(step)
                    source = "mirror"
                if step_dir is None:
                    raise FileNotFoundError(
                        f"no checkpoint for step {step} in "
                        f"{self.directory}")
                blob, data_state = self._call(_read_step_payload,
                                              step_dir)
                chosen = (_from_bytes_tolerant(state_template, blob),
                          data_state, source)
            else:
                chosen = self._load_step(step, state_template)
        restored_host, data_state, source = chosen
        reshard = "none"
        topology = self._load_topology(step)
        ambient_mesh = mesh if mesh is not None \
            else _template_mesh(state_template)
        if topology is None:
            logger.warning(
                "checkpoint step %d carries no topology metadata "
                "(pre-elastic save); restoring onto the template's "
                "placement", step)
            restored = _place_like(state_template, restored_host)
        elif _topology_differs(topology.get("mesh"),
                               mesh_topology(ambient_mesh)):
            reshard = "gather_replace"
            t_reshard = time.perf_counter()
            if ambient_mesh is not None:
                restored = _place_elastic(state_template, restored_host,
                                          ambient_mesh, topology)
            else:
                # The new world has no mesh (single-device restore of a
                # mesh-born save): the host-gathered values land on the
                # template's placement, which IS the re-shard here.
                restored = _place_like(state_template, restored_host)
            _RESHARDS.inc()
            _RESHARD_MS.observe((time.perf_counter() - t_reshard) * 1e3)
            logger.warning(
                "checkpoint step %d re-sharded onto a changed topology: "
                "saved on %s, restoring onto %s", step,
                topology.get("mesh"), mesh_topology(ambient_mesh))
        else:
            restored = _place_like(state_template, restored_host)
        _RESTORES.inc()
        obs_events.emit(
            "checkpoint", action="restore", step=int(step), ok=True,
            source=source, reshard=reshard,
            duration_ms=round((time.perf_counter() - t0) * 1e3, 3))
        return restored, data_state

    def truncate_after(self, step: int) -> list[int]:
        """Delete every step NEWER than ``step``, in the primary AND the
        mirror. This is the explicit-rewind path (``fit(restore_step=)``):
        a replay from a historical step owns the timeline from there —
        leaving the old lineage's future steps on disk would (a) make
        every cadence save of the replay a silent no-op (an existing step
        dir wins over a non-forced save) and (b) hand any crash-mid-
        replay restart the OLD lineage's newest step as its "newest
        valid" resume point, discarding exactly the rollback the caller
        asked for. Unlike ``delete_step`` (corruption path, where the
        mirror copy is the redundancy being kept), rewind must clear both
        replicas — a stale future surviving in the mirror would still win
        the newest-valid race. Returns the deleted steps.
        """
        step = int(step)
        deleted = set()
        for s in [s for s in self.manager.all_steps() if s > step]:
            self.delete_step(s, reason="rewind")
            if self._step_dir(s) is None:
                deleted.add(s)
        if self._mirror is not None:
            m_manifests = self._load_manifests(self.mirror_dir)
            changed = False
            for s in [s for s in self._mirror.all_steps() if s > step]:
                try:
                    self._mirror.delete(s)
                except OSError:
                    continue
                deleted.add(s)
                if m_manifests.pop(str(s), None) is not None:
                    changed = True
            if changed:
                try:
                    self._store_manifests(m_manifests, self.mirror_dir)
                except OSError:
                    pass
        return sorted(deleted)

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return self.manager.all_steps()

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()


class AsyncCheckpointer:
    """Bounded background writer around a ``CheckpointManager``.

    ``save`` snapshots the state to host on the caller's thread (one
    device→host copy) and enqueues the serialization + atomic write +
    manifest + replication + GC on a single writer thread. Outstanding
    WORK (queued + in-flight) is bounded at ``max_pending``: the train
    loop blocks — before taking the next snapshot, so at most
    ``max_pending`` host copies exist — only when that much work is
    already outstanding (`checkpoint_save_blocked_ms` records the stall
    when it happens; `checkpoint_queue_depth` and
    `checkpoint_save_overlap_ms` ride the obs registry).

    Write failures keep the skip-a-checkpoint contract (counter + event,
    never an exception on the train loop); the last failure is kept in
    ``last_error`` for callers that want to escalate.

    ``emergency_save`` is the preemption path: drain the writer, then
    save synchronously on the caller's thread — used by ``trainer.fit``
    when a PreemptionGuard stop lands, so the final step is durable
    before the process exits its grace window.
    """

    def __init__(self, manager: CheckpointManager, max_pending: int = 1):
        self.manager = manager
        self.max_pending = max(1, int(max_pending))
        self._queue: queue_mod.Queue = queue_mod.Queue(
            maxsize=self.max_pending)
        self.last_error: BaseException | None = None
        self._closed = False
        self._thread = threading.Thread(target=self._writer, daemon=True,
                                        name="ckpt-writer")
        self._thread.start()

    # -- writer thread ---------------------------------------------------
    def _writer(self) -> None:
        while True:
            job = self._queue.get()
            try:
                if job is None:
                    return
                step, snapshot, data_state, force, t_enqueue = job
                t0 = time.perf_counter()
                try:
                    # The cadence filter already ran at accept time
                    # (_prefiltered) — re-running it here would misread
                    # a claimed first save as "skip". A False return can
                    # then only mean a benign duplicate-step skip or a
                    # real write failure; the failure counter is what
                    # distinguishes them.
                    failures_before = _SAVE_FAILURES.value
                    ok = self.manager.save(step, snapshot, force=force,
                                           data_state=data_state,
                                           _prefiltered=True)
                    if not ok and _SAVE_FAILURES.value > failures_before:
                        self.last_error = OSError(
                            f"async save at step {step} failed (see "
                            "checkpoint_save_failures_total)")
                except BaseException as e:  # never kill the writer
                    self.last_error = e
                    logger.exception("async checkpoint writer: save at "
                                     "step %d died", step)
                _OVERLAP_MS.observe((time.perf_counter() - t0) * 1e3)
            finally:
                self._queue.task_done()
                _QUEUE_DEPTH.set(self._queue.qsize())

    # -- train-loop surface ----------------------------------------------
    def save(self, step: int, state: Any, force: bool = False,
             data_state: dict | None = None) -> bool:
        """Accept a save: snapshot now, write in the background. Returns
        True when the save was enqueued (the outcome lands in the
        counters/events; ``last_error`` carries the newest failure)."""
        if self._closed:
            raise RuntimeError("AsyncCheckpointer is closed")
        step = int(step)
        if jax.process_count() > 1 and jax.process_index() != 0:
            # Single-writer rule, checked BEFORE the snapshot: non-zero
            # processes must neither pay the device->host copy nor hit
            # snapshot_state's fully-addressable check on sharded state.
            return False
        if not self.manager._claim_save(step, force):
            return False
        if self._queue.unfinished_tasks >= self.max_pending:
            # Bounded WORK, not just queue slots: a popped-but-still-
            # writing save counts (unfinished_tasks covers queued AND
            # in-flight jobs), and the wait happens BEFORE the snapshot —
            # otherwise max_pending+1 full host copies of the state
            # would be alive at once. This is the only point an async
            # save can stall the train loop.
            t0 = time.perf_counter()
            self._queue.join()
            _BLOCKED_MS.observe((time.perf_counter() - t0) * 1e3)
        snapshot = snapshot_state(
            state, keep_ef_residual=self.manager.save_ef_residual)
        self._queue.put((step, snapshot, data_state, force,
                         time.perf_counter()))
        _QUEUE_DEPTH.set(self._queue.qsize())
        _ASYNC_SAVES.inc()
        return True

    def emergency_save(self, step: int, state: Any,
                       data_state: dict | None = None) -> bool:
        """Best-effort synchronous save (SIGTERM/preemption path): drain
        pending writes, then write THIS state before returning. Never
        raises on filesystem trouble — at preemption time a failed save
        must still let the clean-exit path run."""
        try:
            self.wait_until_finished()
            return self.manager.save(step, state, force=True,
                                     data_state=data_state,
                                     emergency=True)
        except Exception:
            logger.exception("emergency checkpoint save at step %d died",
                             step)
            return False

    # -- delegation -------------------------------------------------------
    @property
    def directory(self) -> Path:
        return self.manager.directory

    def should_save(self, step: int, force: bool = False) -> bool:
        return self.manager.should_save(step, force)

    def verify(self, step: int) -> bool:
        self.wait_until_finished()
        return self.manager.verify(step)

    def latest_valid_step(self) -> int | None:
        self.wait_until_finished()
        return self.manager.latest_valid_step()

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def all_steps(self) -> list[int]:
        return self.manager.all_steps()

    def delete_step(self, step: int, reason: str = "corrupt") -> None:
        self.manager.delete_step(step, reason)

    def truncate_after(self, step: int) -> list[int]:
        self.wait_until_finished()
        return self.manager.truncate_after(step)

    def restore(self, state_template: Any, step: int | None = None,
                mesh=None):
        self.wait_until_finished()
        return self.manager.restore(state_template, step, mesh=mesh)

    def restore_with_data_state(self, state_template: Any,
                                step: int | None = None, mesh=None):
        self.wait_until_finished()
        return self.manager.restore_with_data_state(state_template, step,
                                                    mesh=mesh)

    def wait_until_finished(self) -> None:
        self._queue.join()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.wait_until_finished()
        self._queue.put(None)
        self._thread.join(timeout=10.0)
        self.manager.close()
