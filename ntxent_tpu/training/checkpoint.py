"""Orbax checkpoint/resume for multi-day pretraining runs.

The reference has no persistence beyond benchmark JSON (SURVEY.md §5.4);
the BASELINE.json configs[2-4] runs (ImageNet/v5e-32 and up) require real
checkpoint/resume. Orbax handles multi-host coordination and atomic writes."""

from __future__ import annotations

import logging
from pathlib import Path
from typing import Any

import orbax.checkpoint as ocp

logger = logging.getLogger(__name__)

__all__ = ["CheckpointManager"]


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager for TrainState pytrees."""

    def __init__(self, directory: str | Path, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self.directory = Path(directory).absolute()
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True,
            ),
        )

    def save(self, step: int, state: Any, force: bool = False) -> bool:
        saved = self.manager.save(
            step, args=ocp.args.StandardSave(state), force=force)
        if saved:
            logger.info("checkpoint saved at step %d -> %s", step,
                        self.directory)
        return saved

    def restore(self, state_template: Any, step: int | None = None) -> Any:
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.directory}")
        return self.manager.restore(
            step, args=ocp.args.StandardRestore(state_template))

    def latest_step(self) -> int | None:
        return self.manager.latest_step()

    def wait_until_finished(self):
        self.manager.wait_until_finished()

    def close(self):
        self.manager.close()
