"""SimCLR two-view augmentation pipeline, pure JAX (runs on device).

The reference contains no augmentation code (SURVEY.md §0.2); SimCLR's
recipe (Chen et al. 2020, §A) is: random resized crop + horizontal flip +
color jitter (brightness/contrast/saturation/hue, p=0.8) + grayscale (p=0.2)
+ Gaussian blur (p=0.5). Everything here is jit/vmap-friendly with static
shapes: crops use ``jax.image.scale_and_translate`` (traced scale/offset,
static output), hue rotates chroma in YIQ space, blur is a separable
depthwise conv — so the whole two-view pipeline fuses into the device step
instead of bottlenecking host CPU (the ">=50% MFU is input-bound territory"
risk called out in SURVEY.md §7.4)."""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["augment_pair", "augment_batch_pair", "random_resized_crop",
           "color_jitter", "random_grayscale", "gaussian_blur",
           "random_flip"]

# numpy, not jnp: a module-level device array would initialize the JAX
# backends (and block on accelerator discovery) at import time.
_RGB_TO_Y = np.array([0.299, 0.587, 0.114], np.float32)


def random_resized_crop(key, image, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
    """Crop a random area/aspect box and resize back to the input size."""
    h, w, _ = image.shape
    k_area, k_ratio, k_x, k_y = jax.random.split(key, 4)
    area = jax.random.uniform(k_area, (), minval=scale[0], maxval=scale[1])
    log_ratio = jax.random.uniform(
        k_ratio, (), minval=jnp.log(ratio[0]), maxval=jnp.log(ratio[1]))
    aspect = jnp.exp(log_ratio)
    crop_h = jnp.clip(jnp.sqrt(area / aspect) * h, 1.0, h)
    crop_w = jnp.clip(jnp.sqrt(area * aspect) * w, 1.0, w)
    y0 = jax.random.uniform(k_y, (), maxval=1.0) * (h - crop_h)
    x0 = jax.random.uniform(k_x, (), maxval=1.0) * (w - crop_w)
    # Map the crop box back onto the full canvas: out = scale*in + translate.
    sy, sx = h / crop_h, w / crop_w
    return jax.image.scale_and_translate(
        image, (h, w, image.shape[2]), (0, 1),
        jnp.array([sy, sx]), jnp.array([-y0 * sy, -x0 * sx]),
        method="bilinear",
    )


def random_flip(key, image):
    return jnp.where(jax.random.bernoulli(key), image[:, ::-1, :], image)


def _adjust_saturation(image, factor):
    gray = jnp.tensordot(image, _RGB_TO_Y, axes=1)[..., None]
    return gray + factor * (image - gray)


def _adjust_hue(image, delta):
    """Rotate chroma in YIQ space by ``delta`` (radians-scale factor)."""
    yiq_from_rgb = jnp.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.322],
                              [0.211, -0.523, 0.312]])
    rgb_from_yiq = jnp.linalg.inv(yiq_from_rgb)
    yiq = image @ yiq_from_rgb.T
    cos, sin = jnp.cos(delta), jnp.sin(delta)
    zero, one = jnp.float32(0.0), jnp.float32(1.0)
    rot = jnp.stack([
        jnp.stack([one, zero, zero]),
        jnp.stack([zero, cos, -sin]),
        jnp.stack([zero, sin, cos]),
    ])
    return (yiq @ rot.T) @ rgb_from_yiq.T


def color_jitter(key, image, strength: float = 1.0):
    """SimCLR color jitter: brightness/contrast/saturation 0.8s, hue 0.2s,
    applied in random order (order randomization approximated by fixed order
    with independent strengths — the distortion family is the same)."""
    kb, kc, ks, kh = jax.random.split(key, 4)
    b = 0.8 * strength
    image = image * jax.random.uniform(kb, (), minval=1 - b, maxval=1 + b)
    mean = jnp.mean(jnp.tensordot(image, _RGB_TO_Y, axes=1))
    image = mean + (image - mean) * jax.random.uniform(
        kc, (), minval=1 - b, maxval=1 + b)
    image = _adjust_saturation(image, jax.random.uniform(
        ks, (), minval=1 - b, maxval=1 + b))
    # torchvision hue=h rotates by h * 2*pi radians (SimCLR uses h=0.2*s).
    image = _adjust_hue(image, jax.random.uniform(
        kh, (), minval=-0.2 * strength, maxval=0.2 * strength) * 2 * jnp.pi)
    return jnp.clip(image, 0.0, 1.0)


def random_grayscale(key, image, p: float = 0.2):
    gray = jnp.tensordot(image, _RGB_TO_Y, axes=1)[..., None]
    gray = jnp.broadcast_to(gray, image.shape)
    return jnp.where(jax.random.bernoulli(key, p), gray, image)


def gaussian_blur(key, image, kernel_size: int = 0, p: float = 0.5):
    """Separable Gaussian blur with sigma ~ U(0.1, 2.0), SimCLR-standard.
    kernel_size defaults to ~10% of image size (odd)."""
    h = image.shape[0]
    if kernel_size <= 0:
        kernel_size = max(3, (h // 10) | 1)
    k_sigma, k_apply = jax.random.split(key)
    sigma = jax.random.uniform(k_sigma, (), minval=0.1, maxval=2.0)
    r = kernel_size // 2
    xs = jnp.arange(-r, r + 1, dtype=jnp.float32)
    kern = jnp.exp(-0.5 * (xs / sigma) ** 2)
    kern = kern / jnp.sum(kern)
    img = jnp.moveaxis(image, -1, 0)[:, None]  # (C, 1, H, W)
    blurred = jax.lax.conv_general_dilated(
        img, kern.reshape(1, 1, -1, 1), (1, 1), "SAME")
    blurred = jax.lax.conv_general_dilated(
        blurred, kern.reshape(1, 1, 1, -1), (1, 1), "SAME")
    blurred = jnp.moveaxis(blurred[:, 0], 0, -1)
    return jnp.where(jax.random.bernoulli(k_apply, p), blurred, image)


def augment_one(key, image, strength: float = 1.0, blur: bool = True):
    """One SimCLR view from one image (H, W, C) in [0, 1]."""
    k_crop, k_flip, k_jit, k_jit_p, k_gray, k_blur = jax.random.split(key, 6)
    image = random_resized_crop(k_crop, image)
    image = random_flip(k_flip, image)
    jittered = color_jitter(k_jit, image, strength)
    image = jnp.where(jax.random.bernoulli(k_jit_p, 0.8), jittered, image)
    image = random_grayscale(k_gray, image)
    if blur:
        image = gaussian_blur(k_blur, image)
    return image


def augment_pair(key, image, strength: float = 1.0, blur: bool = True):
    """Two independent SimCLR views of one image."""
    k1, k2 = jax.random.split(key)
    return (augment_one(k1, image, strength, blur),
            augment_one(k2, image, strength, blur))


@partial(jax.jit, static_argnames=("strength", "blur"))
def augment_batch_pair(key, images, strength: float = 1.0, blur: bool = True):
    """Two views for a batch (B, H, W, C) -> ((B, H, W, C), (B, H, W, C))."""
    keys = jax.random.split(key, images.shape[0])
    return jax.vmap(partial(augment_pair, strength=strength, blur=blur)
                    )(keys, images)
