"""Input pipelines: array-backed datasets with on-device augmentation.

The reference has no data code (SURVEY.md §0.2). Design: the host only
shuffles indices and slices raw uint8 arrays; the SimCLR two-view
augmentation runs on device inside jit (training/augment.py), keeping the
host off the critical path (the input-bound-MFU risk, SURVEY.md §7.4).

Sources: in-memory arrays (.npz / numpy / anything array-like, e.g. CIFAR-10
batches loaded by the user) and a synthetic generator for benchmarks and
tests (no dataset downloads are assumed available)."""

from __future__ import annotations

import collections
import inspect
import threading
import time
import queue as queue_mod
from collections.abc import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from .augment import augment_batch_pair

__all__ = ["ArrayDataset", "synthetic_images", "two_view_iterator",
           "PrefetchIterator", "DevicePrefetcher"]


def synthetic_images(num: int, size: int = 32, channels: int = 3,
                     seed: int = 0) -> np.ndarray:
    """Deterministic fake image corpus in [0,1], uint8-backed like real data."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (num, size, size, channels),
                        dtype=np.uint8)


class ArrayDataset:
    """Shuffling batch sampler over a (N, H, W, C) uint8/float array."""

    def __init__(self, images: np.ndarray, batch_size: int, seed: int = 0,
                 drop_remainder: bool = True):
        if len(images) < batch_size:
            raise ValueError(f"dataset of {len(images)} < batch {batch_size}")
        self.images = images
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        self.drop_remainder = drop_remainder

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:  # epoch loop
            order = self.rng.permutation(len(self.images))
            end = (len(order) // self.batch_size) * self.batch_size \
                if self.drop_remainder else len(order)
            for start in range(0, end, self.batch_size):
                yield self.images[order[start:start + self.batch_size]]


def _to_float(batch: np.ndarray) -> jnp.ndarray:
    x = jnp.asarray(batch)
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32) / 255.0
    return x


def two_view_iterator(dataset: ArrayDataset, key: jax.Array,
                      blur: bool = True) -> Iterator[tuple]:
    """Yields (view1, view2) device batches with on-device augmentation."""
    for batch in dataset:
        key, sub = jax.random.split(key)
        yield augment_batch_pair(sub, _to_float(batch), blur=blur)


class PrefetchIterator:
    """Host-thread prefetch: keeps ``depth`` batches in flight so device
    steps never wait on host slicing (the role a native async loader plays
    in CUDA frameworks; JAX dispatch is already async once arrays are up)."""

    def __init__(self, iterator: Iterator, depth: int = 2):
        self.iterator = iterator
        self.queue: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        self.done = object()
        self.error: BaseException | None = None
        self._error_raised = False
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._fill, daemon=True)
        self.thread.start()

    def _fill(self):
        try:
            for item in self.iterator:
                while not self._stop.is_set():
                    try:
                        self.queue.put(item, timeout=0.25)
                        break
                    except queue_mod.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self.error = e
        finally:
            try:
                self.queue.put_nowait(self.done)
            except queue_mod.Full:
                pass  # consumer stopped; nothing is waiting for the sentinel

    def close(self, timeout: float = 5.0):
        """Stop the producer thread and release buffered batches.

        Joins the producer with ``timeout`` (a producer wedged in a blocking
        read must not wedge the consumer's shutdown too). A producer error
        the consumer never observed via ``__next__`` is re-raised here —
        an epoch abandoned mid-flight must not swallow the reason the
        producer died.
        """
        self._stop.set()
        while True:  # drain so the producer can observe the stop flag
            try:
                self.queue.get_nowait()
            except queue_mod.Empty:
                break
        self.thread.join(timeout=timeout)
        if self.error is not None and not self._error_raised:
            self._error_raised = True
            raise self.error

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None:
            # Already unwinding: don't let a pending producer error mask
            # the exception in flight; close() raising would replace it.
            try:
                self.close()
            except BaseException:
                pass
            return
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        item = self.queue.get()
        if item is self.done:
            if self.error is not None:
                # Surface the producer's ORIGINAL exception (type intact:
                # callers match on OSError/StopIteration-adjacent types,
                # e.g. a RetryPolicy-exhausted fetch), not a flattened
                # RuntimeError.
                self._error_raised = True
                raise self.error
            raise StopIteration
        return item


class DevicePrefetcher:
    """Device-side async pipeline stage: keeps ``depth`` batches ALREADY
    TRANSFERRED (or transferring) on the device ahead of the consumer.

    ``jax.device_put`` is non-blocking: issuing the transfer for batch
    k+1..k+depth while the step for batch k runs overlaps host->device
    copy with compute (the big_vision prefetch discipline). Compose with
    ``PrefetchIterator`` (host-thread fetch) for the full pipeline::

        host thread:   fetch k+2 | fetch k+3 | ...
        transfers:          put k+1  | put k+2 | ...
        device:        step k   | step k+1    | ...

    ``sharding`` (a ``NamedSharding``) makes this the sharded path's
    pipeline stage: batches arrive as COMMITTED global arrays laid out
    for the mesh, so the train step never pays a blocking per-step
    ``shard_batch``/``device_put`` re-placement (``parallel.mesh.
    sharded_prefetch`` builds this from a mesh). Leaves that are already
    committed ``jax.Array``s with the requested sharding pass through
    untouched — wrapping an iterator that places its own output (e.g.
    ``TwoViewPipeline(sharding=...)``) buffers it without re-placing.

    Checkpointable-iterator protocol: when the inner iterator exposes
    ``state()``/``restore()``, so does the prefetcher — ``state()``
    returns the position of the next batch the CONSUMER will receive
    (each buffered batch remembers the state captured before its pull),
    so a resumed run replays nothing and skips nothing despite the
    read-ahead. ``last_timing()`` reports the (host_fetch_s, transfer_s)
    split of the batch most recently yielded; ``train_loop`` feeds it to
    ``StepTimeline.record_step`` as the data-wait breakdown.
    """

    def __init__(self, iterator, depth: int = 2, sharding=None):
        self.iterator = iter(iterator)
        self.depth = max(1, int(depth))
        self.sharding = sharding
        self._stateful = hasattr(iterator, "state") \
            and hasattr(iterator, "restore")
        self._inner = iterator  # the stateful/closeable object itself
        self._buf: collections.deque = collections.deque()
        self._exhausted = False
        self._timing: tuple[float, float] | None = None
        if self._stateful:
            # Expose the checkpointable-iterator protocol only when the
            # inner iterator has it: trainer.fit keys on hasattr, and a
            # prefetcher over a stateless iterator must not pretend.
            self.state = self._state
            self.restore = self._restore

    def _placed(self, x) -> bool:
        return isinstance(x, jax.Array) and (
            self.sharding is None or x.sharding == self.sharding)

    def _put(self, item):
        # One device_put for the whole batch tree (it accepts pytrees):
        # per-leaf calls pay JAX dispatch overhead per view. Trees whose
        # every leaf is already placed pass through untouched — never
        # re-commit an iterator's own placement per step.
        if all(self._placed(leaf) for leaf in jax.tree.leaves(item)):
            return item
        if self.sharding is None:
            return jax.device_put(item)
        return jax.device_put(item, self.sharding)

    def _pull(self) -> None:
        st = self._inner.state() if self._stateful else None
        t0 = time.perf_counter()
        try:
            item = next(self.iterator)
        except StopIteration:
            self._exhausted = True
            return
        t1 = time.perf_counter()
        item = self._put(item)
        t2 = time.perf_counter()
        self._buf.append((item, st, t1 - t0, t2 - t1))

    def last_timing(self) -> tuple[float, float] | None:
        """(host_fetch_s, transfer_dispatch_s) of the batch the last
        ``__next__`` returned (None before the first). host_fetch is the
        blocking pull from the inner iterator; transfer is the
        ``device_put`` DISPATCH time (the copy itself is async — it rides
        under the steps that ran between pull and consumption)."""
        return self._timing

    def _state(self) -> dict:
        if self._buf:
            return self._buf[0][1]
        return self._inner.state()

    def _restore(self, state: dict) -> None:
        # Read-ahead is position-tagged, not position-free: batches pulled
        # for the OLD position are dropped and the inner iterator rebuilds
        # at the restored one.
        self._buf.clear()
        self._exhausted = False
        self._inner.restore(state)
        # Re-enter the inner iterator: a StreamingLoader-style __iter__
        # returns a generator that reads its offset only at creation (or
        # epoch boundaries), so the pre-restore generator would keep
        # yielding from the stale position. For self-iterating pipelines
        # (TwoViewPipeline et al.) this is an identity no-op.
        self.iterator = iter(self._inner)

    def close(self, timeout: float = 5.0) -> None:
        """Release buffered batches; propagate to a closeable inner
        iterator (e.g. PrefetchIterator's producer thread), including any
        pending producer error its ``close()`` re-raises."""
        self._buf.clear()
        inner_close = getattr(self._inner, "close", None)
        if inner_close is None:
            return
        # Decide the signature UP FRONT: a try/except TypeError around the
        # call would also swallow a producer error of type TypeError that
        # PrefetchIterator.close() re-raises — the exact contract this
        # propagation exists for.
        try:
            takes_arg = bool(inspect.signature(inner_close).parameters)
        except (TypeError, ValueError):  # builtins without signatures
            takes_arg = False
        if takes_arg:
            inner_close(timeout)
        else:
            inner_close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        if exc and exc[0] is not None:
            # Already unwinding (e.g. a DivergenceError headed for the
            # supervisor): an unseen producer error re-raised by the inner
            # close() must not REPLACE it — same policy as
            # PrefetchIterator.__exit__.
            try:
                self.close()
            except BaseException:
                pass
            return
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        while not self._exhausted and len(self._buf) < self.depth:
            self._pull()
        if not self._buf:
            raise StopIteration
        item, _, host_s, transfer_s = self._buf.popleft()
        self._timing = (host_s, transfer_s)
        return item
