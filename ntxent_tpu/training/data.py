"""Input pipelines: array-backed datasets with on-device augmentation.

The reference has no data code (SURVEY.md §0.2). Design: the host only
shuffles indices and slices raw uint8 arrays; the SimCLR two-view
augmentation runs on device inside jit (training/augment.py), keeping the
host off the critical path (the input-bound-MFU risk, SURVEY.md §7.4).

Sources: in-memory arrays (.npz / numpy / anything array-like, e.g. CIFAR-10
batches loaded by the user) and a synthetic generator for benchmarks and
tests (no dataset downloads are assumed available)."""

from __future__ import annotations

import threading
import queue as queue_mod
from collections.abc import Iterator

import jax
import jax.numpy as jnp
import numpy as np

from .augment import augment_batch_pair

__all__ = ["ArrayDataset", "synthetic_images", "two_view_iterator",
           "PrefetchIterator"]


def synthetic_images(num: int, size: int = 32, channels: int = 3,
                     seed: int = 0) -> np.ndarray:
    """Deterministic fake image corpus in [0,1], uint8-backed like real data."""
    rng = np.random.default_rng(seed)
    return rng.integers(0, 256, (num, size, size, channels),
                        dtype=np.uint8)


class ArrayDataset:
    """Shuffling batch sampler over a (N, H, W, C) uint8/float array."""

    def __init__(self, images: np.ndarray, batch_size: int, seed: int = 0,
                 drop_remainder: bool = True):
        if len(images) < batch_size:
            raise ValueError(f"dataset of {len(images)} < batch {batch_size}")
        self.images = images
        self.batch_size = batch_size
        self.rng = np.random.default_rng(seed)
        self.drop_remainder = drop_remainder

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:  # epoch loop
            order = self.rng.permutation(len(self.images))
            end = (len(order) // self.batch_size) * self.batch_size \
                if self.drop_remainder else len(order)
            for start in range(0, end, self.batch_size):
                yield self.images[order[start:start + self.batch_size]]


def _to_float(batch: np.ndarray) -> jnp.ndarray:
    x = jnp.asarray(batch)
    if x.dtype == jnp.uint8:
        x = x.astype(jnp.float32) / 255.0
    return x


def two_view_iterator(dataset: ArrayDataset, key: jax.Array,
                      blur: bool = True) -> Iterator[tuple]:
    """Yields (view1, view2) device batches with on-device augmentation."""
    for batch in dataset:
        key, sub = jax.random.split(key)
        yield augment_batch_pair(sub, _to_float(batch), blur=blur)


class PrefetchIterator:
    """Host-thread prefetch: keeps ``depth`` batches in flight so device
    steps never wait on host slicing (the role a native async loader plays
    in CUDA frameworks; JAX dispatch is already async once arrays are up)."""

    def __init__(self, iterator: Iterator, depth: int = 2):
        self.iterator = iterator
        self.queue: queue_mod.Queue = queue_mod.Queue(maxsize=depth)
        self.done = object()
        self.error: BaseException | None = None
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._fill, daemon=True)
        self.thread.start()

    def _fill(self):
        try:
            for item in self.iterator:
                while not self._stop.is_set():
                    try:
                        self.queue.put(item, timeout=0.25)
                        break
                    except queue_mod.Full:
                        continue
                if self._stop.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer thread
            self.error = e
        finally:
            try:
                self.queue.put_nowait(self.done)
            except queue_mod.Full:
                pass  # consumer stopped; nothing is waiting for the sentinel

    def close(self):
        """Stop the producer thread and release buffered batches."""
        self._stop.set()
        while True:  # drain so the producer can observe the stop flag
            try:
                self.queue.get_nowait()
            except queue_mod.Empty:
                break
        self.thread.join(timeout=5.0)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        item = self.queue.get()
        if item is self.done:
            if self.error is not None:
                raise RuntimeError("prefetch producer failed") from self.error
            raise StopIteration
        return item
