"""Preemption-aware training: catch SIGTERM, checkpoint, exit clean.

The failure-recovery subsystem the reference lacked entirely (SURVEY.md
§5.3: its only error handling was throw-on-CUDA-error and exception→exit(1)
in the harnesses, /root/reference/python/test.py:181-183,207-209). On Cloud
TPU the scheduler preempts VMs with a SIGTERM and a grace window; a
multi-day SimCLR pretraining run (BASELINE.json configs[2-4]) survives only
if the trainer turns that signal into a final checkpoint and a clean exit,
and the next incarnation resumes exactly (training/checkpoint.py +
datasets' checkpointable iterator state carry the resume).

``PreemptionGuard`` is deliberately signal-minimal: the handler only flips
a flag (async-signal-safe); all real work (device sync, checkpoint save)
happens on the main thread at the next step boundary via ``train_loop``'s
``stop_fn`` hook. Under async checkpointing the stop additionally routes
``fit``'s final save through ``AsyncCheckpointer.emergency_save`` — the
writer queue drains and the stopped step is written synchronously before
the grace window can expire (training/checkpoint.py).

The guard is also the clean-stop lever of the rest of the resilience layer
(resilience/supervisor.py): ``resilience.Supervisor`` installs one guard
per attempt and uses it both for real SIGTERMs and as the target of
``utils.watchdog.StallWatchdog`` escalation — a stalled attempt is stopped
at a step boundary, checkpointed, and restarted in-process from the last
valid checkpoint.
"""

from __future__ import annotations

import logging
import signal
import threading

logger = logging.getLogger(__name__)

__all__ = ["PreemptionGuard"]


class PreemptionGuard:
    """Context manager that converts SIGTERM into a stop request.

    (SIGTERM only by default — what cluster schedulers send. Pass
    ``signals=(signal.SIGTERM, signal.SIGINT)`` to also make Ctrl-C stop
    gracefully instead of raising KeyboardInterrupt mid-step.)

    Usage::

        with PreemptionGuard() as guard:
            state, hist = fit(..., stop_fn=guard.requested)
        if guard.preempted:
            sys.exit(0)   # checkpoint already saved by fit

    * Only installs handlers on the main thread of the main interpreter
      (Python requires it); elsewhere it degrades to a manual flag.
    * Chains to any previously installed handler so co-resident machinery
      (e.g. a cluster agent's own SIGTERM hook) still runs.
    * Re-entrant safe: a second signal while stopping is ignored rather
      than re-raising mid-checkpoint.
    """

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM,)):
        self._signals = signals
        self._event = threading.Event()
        self._previous: dict[int, object] = {}
        self._installed = False
        self._announced = False

    # -- flag surface ----------------------------------------------------
    def requested(self) -> bool:
        """True once a shutdown signal has arrived (train_loop stop_fn)."""
        if self._event.is_set() and not self._announced:
            # Log from the polling (main) thread, never from the handler:
            # logging's buffered streams are not reentrant, and a signal
            # landing mid-write would crash the very path this class exists
            # to protect.
            self._announced = True
            logger.warning("shutdown signal received: finishing current "
                           "step, saving checkpoint, then exiting")
            # Flight recorder (ISSUE 7): the signal path persists the
            # event tail NOW, from the polling thread (async-signal-safe
            # by construction — the handler only flipped the flag), so a
            # preempted run leaves its last N events on disk even when
            # --log-jsonl was never enabled. Best-effort: the checkpoint
            # save this poll unblocks must never wait on a full disk.
            try:
                from ..obs import events as _obs_events

                # routine=True: a SIGTERM is normal preemption, so the
                # dump lands only where telemetry already lives (the
                # --log-jsonl dir or NTXENT_FLIGHT_DIR), never the CWD.
                _obs_events.dump_flight(reason="signal", routine=True)
            except Exception:
                logger.exception("flight recorder dump failed on signal")
        return self._event.is_set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()

    def request(self) -> None:
        """Manual trigger (tests; cooperative shutdown from another thread)."""
        self._event.set()

    # -- handler lifecycle ----------------------------------------------
    def _handler(self, signum, frame):
        # Async-signal-safe: only flip the flag here. Logging happens on the
        # main thread at the next requested() poll (reentrant-I/O hazard),
        # and chaining skips Python's default SIGINT handler — invoking it
        # would raise KeyboardInterrupt mid-step, the exact behavior a guard
        # over SIGINT exists to prevent.
        first = not self._event.is_set()
        self._event.set()
        prev = self._previous.get(signum)
        if (first and callable(prev)
                and prev is not signal.default_int_handler):
            prev(signum, frame)

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for sig in self._signals:
                self._previous[sig] = signal.getsignal(sig)
                signal.signal(sig, self._handler)
            self._installed = True
        else:
            logger.warning("PreemptionGuard outside the main thread: no "
                           "signal handlers installed (manual request() "
                           "still works)")
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._installed = False
        return None
