"""Per-step training timeline: where each step's wall clock actually went.

``trainer.train_loop`` reports loss and steps/sec at log points; the
timeline makes every step's breakdown machine-readable (ISSUE 3): how
long the host waited on the input pipeline (``data_wait_ms``), how long
the device ran (``device_ms`` — the loop calls ``block_until_ready``
when a timeline is attached, the same documented per-step host sync a
step_guard already costs), and how long the step hook (checkpoint
cadence) took (``checkpoint_ms``). Each step lands in the process-wide
MetricsRegistry (histograms + gauges) and, when an EventLog is
installed, as one ``step`` event per ``event_every`` steps.

The timeline is also where two cross-cutting signals hang:

* **unguarded divergence observation** — the timeline reads the loss
  every step anyway, so a non-finite loss on a step WITHOUT the jit-side
  guard (no ``step_ok`` metric) still produces a ``divergence`` event
  and bumps the divergence counter; guarded runs get richer events from
  resilience.DivergenceGuard instead (``step_ok`` present suppresses
  the duplicate here);
* **slow-step profiler trigger** — per-step device time feeds the
  attached ``ProfilerTrigger`` (obs/profiler.py), which captures a
  jax.profiler trace when a step blows past its rolling median.

MFU: ``set_flops_per_step`` (train_loop forwards XLA's compiled cost
analysis) divided by the accelerator's peak — resolved lazily through
``trainer.peak_flops_per_chip`` so this module stays importable without
JAX.
"""

from __future__ import annotations

import logging
import math
import time

from . import events
from .registry import MetricsRegistry, default_registry

logger = logging.getLogger(__name__)

__all__ = ["StepTimeline"]


class StepTimeline:
    """Collects per-step timings from ``train_loop`` and publishes them.

    One instance per run (attempts share it: counters and the profiler's
    rolling window deliberately survive supervisor restarts, while the
    event log's ``attempt`` field distinguishes the records).
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 profiler=None, event_every: int = 1,
                 histogram_window: int = 2048, history=None):
        self.registry = registry or default_registry()
        self.profiler = profiler
        # Optional obs.MetricHistory (ISSUE 18): each step also lands
        # in the retained time-series plane, so a training process gets
        # the same rollup/anomaly machinery the serving fleet does.
        self.history = history
        self.event_every = max(1, int(event_every))
        self.flops_per_step: float | None = None
        self._peak_flops: float | None = None
        self._last_done: float | None = None
        r = self.registry
        self._steps = r.counter(
            "train_steps_total", "completed train steps")
        self._divergence = r.counter(
            "train_divergence_total",
            "steps whose loss or grad norm was non-finite")
        self._data_wait = r.histogram(
            "train_step_data_wait_ms",
            "host wait on the input pipeline per step",
            window=histogram_window)
        # Transfer-aware split of the data wait (ISSUE 4): where pipeline
        # time actually goes — producing the batch on the host vs moving
        # it to the device. Populated when the iterator reports the split
        # (data.DevicePrefetcher.last_timing); a plain iterator's wait is
        # recorded as all host fetch.
        self._host_fetch = r.histogram(
            "train_step_host_fetch_ms",
            "host time producing each consumed batch (slice/decode/"
            "augment dispatch)",
            window=histogram_window)
        self._transfer = r.histogram(
            "train_step_transfer_ms",
            "host->device transfer dispatch time per consumed batch "
            "(the copy itself rides under compute)",
            window=histogram_window)
        self._device = r.histogram(
            "train_step_device_ms",
            "device time per step (block_until_ready bracketed; "
            "dispatch-to-ready latency under metrics_lag)",
            window=histogram_window)
        # NB no per-step checkpoint histogram: most steps' hook time is
        # a microsecond no-op (the cadence filter saves rarely), so a
        # window of them would bury the real saves. checkpoint_save_ms
        # (training/checkpoint.py) measures actual saves; the per-step
        # hook time still rides every `step` event as checkpoint_ms.
        self._sps = r.gauge(
            "train_steps_per_sec", "instantaneous steps per second")
        self._loss = r.gauge("train_loss", "last step's loss")
        self._mfu = r.gauge(
            "train_mfu", "model FLOP utilization (0..1)")
        # Static per-compiled-step collective traffic (ISSUE 7): set once
        # from the comms-accounting delta bracketing the step compile
        # (parallel/mesh.py records op counts/bytes at trace time;
        # train_loop forwards the delta). The per-(op, axis) cumulative
        # counters live in collective_*_total; these gauges are the
        # per-STEP view the quantization/overlap ROADMAP items baseline
        # against. None until a compile has been bracketed.
        self._comms_bytes_per_step: float | None = None
        self._comms_bytes = r.gauge(
            "train_step_comms_bytes",
            "bytes moved per device per compiled step (trace-time "
            "static, ring-algorithm model)")
        self._comms_calls = r.gauge(
            "train_step_comms_calls",
            "collective ops per compiled step (trace-time static)")
        # Measured computation-collective overlap (ISSUE 19): the wall
        # clock the chunked ring schedule hides relative to the
        # monolithic transfer, captured on-chip by an A/B bracket
        # (trainer.measure_comms_overlap) — the byte census can't see
        # time, so this is the dynamic half of the overlap claim.
        self._overlap_ms = r.gauge(
            "train_step_comms_overlap_ms",
            "per-step wall clock hidden by the chunked ring schedule "
            "(monolithic minus chunked step time, block_until_ready "
            "bracketed; 0 until measured)")
        self._overlap_frac = r.gauge(
            "train_step_comms_overlap_frac",
            "overlap window as a fraction of the monolithic step time "
            "(0..1; 0 until measured)")

    # -- wiring ----------------------------------------------------------
    def set_flops_per_step(self, flops: float | None) -> None:
        self.flops_per_step = flops

    def new_attempt(self) -> None:
        """Reset the inter-step clock at a loop/attempt boundary
        (train_loop calls this on entry): without it, the first step
        after a supervisor restart would compute steps_per_sec over the
        whole backoff+restore+recompile gap — near-zero throughput
        reported at exactly the moment an operator inspects the run."""
        self._last_done = None

    def _mfu_of(self, steps_per_sec: float) -> float | None:
        if not self.flops_per_step:
            return None
        if self._peak_flops is None:
            try:  # lazy: keeps obs importable without JAX
                from ..training.trainer import peak_flops_per_chip

                self._peak_flops = peak_flops_per_chip()
            except Exception:
                self._peak_flops = float("nan")
        if not math.isfinite(self._peak_flops):
            return None
        return self.flops_per_step * steps_per_sec / self._peak_flops

    def record_compile(self, duration_ms: float,
                       flops: float | None) -> None:
        """One AOT step compile (train_loop's step-1 auto path)."""
        self.registry.counter(
            "train_compiles_total", "AOT train-step compiles").inc()
        events.emit("compile", duration_ms=round(duration_ms, 3),
                    flops=flops)

    def set_comms_per_step(self, profile: dict,
                           graph: dict | None = None) -> None:
        """Publish one compiled step's static collective profile.

        ``profile`` is a comms-accounting delta (``{(op, axis): (calls,
        bytes)}`` — parallel/mesh.CommsAccounting.delta) captured around
        the step's trace; an empty delta (single-device runs, steps with
        no hand-written collectives) leaves the series untouched —
        unless ``graph`` reports GSPMD traffic (a TP/FSDP step's
        collectives are ALL compiler-inserted, so the declared delta is
        legitimately empty while the graph is not).

        ``graph`` (ISSUE 14) is a graph-census summary
        (``analysis.graph.census.graph_remainder``: ``graph_bytes`` /
        ``declared_bytes`` / ``ad_bytes``, plus ``gspmd_bytes`` when an
        HLO census ran): the traffic the shims cannot see (AD duals,
        GSPMD-inserted collectives) lands on
        ``collective_graph_bytes_total{source="ad"|"gspmd"}`` and rides
        the ``comms_profile`` event, so /metrics stops under-reporting.
        The dict is plain floats — obs stays importable without JAX;
        the census itself lives in ``analysis/graph/``.
        """
        calls = sum(c for c, _ in profile.values())
        nbytes = sum(b for _, b in profile.values())
        graph = dict(graph) if graph else {}
        if graph:
            # One declaration of the counter family, shared with the
            # ntxent-audit CLI. census.py imports jax only inside the
            # census functions, so this lazy import keeps obs JAX-free.
            from ..analysis.graph.census import publish_graph_census

            publish_graph_census(
                float(graph.get("ad_bytes") or 0.0),
                float(graph.get("gspmd_bytes") or 0.0),
                registry=self.registry)
        if not calls and not graph.get("gspmd_bytes"):
            return
        self._comms_bytes_per_step = float(nbytes)
        self._comms_bytes.set(nbytes)
        self._comms_calls.set(calls)
        fields = {}
        for key in ("graph_bytes", "ad_bytes", "gspmd_bytes"):
            if graph.get(key) is not None:
                fields[key] = float(graph[key])
        events.emit("comms_profile", calls=int(calls),
                    bytes=float(nbytes),
                    by_op={f"{op}|{ax}": {"calls": int(c),
                                          "bytes": float(b)}
                           for (op, ax), (c, b) in sorted(profile.items())},
                    **fields)

    def set_comms_overlap(self, overlap_ms: float,
                          monolithic_ms: float | None = None,
                          chunked_ms: float | None = None,
                          chunks: int | None = None) -> None:
        """Publish one measured overlap window (ISSUE 19).

        ``overlap_ms`` is the per-step wall clock the chunked ring
        schedule hides — monolithic minus chunked step time, both
        block_until_ready bracketed (``trainer.measure_comms_overlap``
        produces the triple; callers may also feed profiler-derived
        windows). Clamped at 0: a chunked schedule slower than the
        monolithic one hides nothing (and the bench gate, not this
        series, is where that regression fails). The fraction series
        needs ``monolithic_ms``; without it only the ms gauge moves.
        """
        ms = max(float(overlap_ms), 0.0)
        self._overlap_ms.set(ms)
        fields = {"overlap_ms": round(ms, 3)}
        if monolithic_ms and monolithic_ms > 0.0:
            frac = min(max(ms / float(monolithic_ms), 0.0), 1.0)
            self._overlap_frac.set(frac)
            fields["overlap_frac"] = round(frac, 4)
            fields["monolithic_ms"] = round(float(monolithic_ms), 3)
        if chunked_ms is not None:
            fields["chunked_ms"] = round(float(chunked_ms), 3)
        if chunks is not None:
            fields["chunks"] = int(chunks)
        events.emit("comms_overlap", **fields)

    # -- per step --------------------------------------------------------
    def record_step(self, step: int, loss: float,
                    data_wait_s: float, device_s: float,
                    hook_s: float = 0.0, ok: bool | None = None,
                    grad_norm: float | None = None,
                    host_fetch_s: float | None = None,
                    transfer_s: float | None = None) -> None:
        """One completed step. ``ok=None`` means the step carried no
        jit-side guard (unguarded fast path).

        ``host_fetch_s``/``transfer_s`` split the input-pipeline time:
        producing the batch on the host vs dispatching its host->device
        transfer (``train_loop`` forwards ``DevicePrefetcher.
        last_timing``). With a prefetcher the split describes the batch
        consumed this step (whose fetch/transfer ran UNDER earlier
        steps), while ``data_wait_s`` stays the time this step actually
        blocked — near zero when the pipeline keeps up. ``host_fetch_s=
        None`` records the whole wait as host fetch; ``transfer_s=None``
        (no prefetcher: placement is buried in the iterator) leaves the
        transfer series untouched.

        Under ``train_loop(metrics_lag=1)`` records arrive one step after
        dispatch and ``device_s`` is dispatch-to-ready latency — the
        documented lag-1 semantics.
        """
        now = time.perf_counter()
        if self._last_done is not None:
            wall_s = max(now - self._last_done, 1e-9)
        else:
            wall_s = max(data_wait_s + device_s + hook_s, 1e-9)
        self._last_done = now
        steps_per_sec = 1.0 / wall_s

        self._steps.inc()
        self._data_wait.observe(data_wait_s * 1e3)
        if host_fetch_s is None:
            host_fetch_s = data_wait_s  # no split known: all host fetch
        self._host_fetch.observe(host_fetch_s * 1e3)
        if transfer_s is not None:
            self._transfer.observe(transfer_s * 1e3)
        self._device.observe(device_s * 1e3)
        self._sps.set(steps_per_sec)
        if math.isfinite(loss):
            self._loss.set(loss)
        mfu = self._mfu_of(steps_per_sec)
        if mfu is not None:
            self._mfu.set(mfu)

        diverged = not math.isfinite(loss) or (ok is False)
        if diverged:
            self._divergence.inc()
        if step % self.event_every == 0 or diverged:
            # Non-finite loss/grad_norm floats are stringified by the
            # EventLog itself (events._sanitize) — no per-site handling.
            fields = dict(step=int(step), loss=float(loss),
                          data_wait_ms=round(data_wait_s * 1e3, 3),
                          host_fetch_ms=round(host_fetch_s * 1e3, 3),
                          device_ms=round(device_s * 1e3, 3),
                          checkpoint_ms=round(hook_s * 1e3, 3),
                          steps_per_sec=round(steps_per_sec, 4))
            if transfer_s is not None:
                fields["transfer_ms"] = round(transfer_s * 1e3, 3)
            if self._comms_bytes_per_step is not None:
                fields["comms_bytes"] = self._comms_bytes_per_step
            if mfu is not None:
                fields["mfu"] = round(mfu, 4)
            if grad_norm is not None:
                fields["grad_norm"] = float(grad_norm)
            if ok is not None:
                fields["ok"] = bool(ok)
            events.emit("step", **fields)
        if diverged and ok is None:
            # Unguarded step: nobody else will record this. Guarded
            # steps get their divergence event from DivergenceGuard
            # (richer: tier decisions, scale), so skip the duplicate.
            events.emit("divergence", action="observed", step=int(step),
                        loss=float(loss), guarded=False)

        if self.history is not None:
            self.history.record("train_step_device_ms", device_s * 1e3)
            self.history.record("train_steps_per_sec", steps_per_sec)
            self.history.record("train_loss", loss)  # non-finite: dropped
            self.history.maybe_spill()

        if self.profiler is not None:
            self.profiler.on_step(int(step), device_s * 1e3)

    def close(self) -> None:
        if self.profiler is not None:
            self.profiler.close()
