"""On-demand jax.profiler capture: slow-step trigger + manual triggers.

Kernel- and comms-level tuning (Ragged Paged Attention, EQuARX — see
PAPERS.md) is only actionable with a device trace of the BAD steps, and
the bad steps are rare: tracing a whole multi-day run is not an option,
and by the time a human attaches a profiler the anomaly is gone.
``ProfilerTrigger`` watches the per-step device time the StepTimeline
feeds it and captures exactly the interesting window:

* **slow-step trigger** — a step slower than ``slow_factor`` x the
  rolling-median step time starts a ``jax.profiler`` trace of the NEXT
  ``capture_steps`` steps into ``trace_dir``. The median is over a
  bounded window, so gradual drift re-baselines; arming waits for
  ``warmup_steps`` SAMPLES so the step-1 AOT compile (orders of
  magnitude over steady state, and entirely expected) can never fire it.
* **manual triggers** — touching ``<trace_dir>/TRIGGER`` (checked once
  per step: one ``os.path.exists`` of host-side cost) or sending
  SIGUSR2 (installed only from the main thread) requests a capture of
  the next window, for "it feels slow right now" operator moments.

Every capture appends a ``trace`` event pointing at the artifact
directory, so the JSONL stream records both that a capture happened and
where to load it (TensorBoard/XProf). Profiler failures are logged and
disable further captures — diagnosis must never take training down.
"""

from __future__ import annotations

import logging
import os
import statistics
import threading
import time
from collections import deque

from . import events
from .registry import default_registry

logger = logging.getLogger(__name__)

__all__ = ["ProfilerTrigger"]


class ProfilerTrigger:
    """Feed ``on_step(step, duration_ms)`` once per step; captures fire
    on the following steps. Thread-safe (the manual ``request`` may come
    from a signal handler or another thread)."""

    def __init__(self, trace_dir: str, slow_factor: float = 3.0,
                 capture_steps: int = 5, warmup_steps: int = 5,
                 window: int = 50, trigger_file: str | None = None,
                 registry=None):
        if slow_factor <= 1.0:
            raise ValueError(f"slow_factor must be > 1, got {slow_factor}")
        if capture_steps < 1:
            raise ValueError("capture_steps must be >= 1")
        self.trace_dir = str(trace_dir)
        self.slow_factor = float(slow_factor)
        self.capture_steps = int(capture_steps)
        self.warmup_steps = int(warmup_steps)
        self.trigger_file = (trigger_file if trigger_file is not None
                             else os.path.join(self.trace_dir, "TRIGGER"))
        os.makedirs(self.trace_dir, exist_ok=True)  # TRIGGER touchable
        self._window: deque[float] = deque(maxlen=window)
        self._lock = threading.Lock()
        # Set by the SIGUSR2 handler WITHOUT taking the lock: the signal
        # runs on the main thread, which may already hold self._lock
        # inside on_step — request() there would self-deadlock. A bare
        # attribute store is atomic; on_step consumes it lock-free.
        self._signal_pending = False
        self._requested: str | None = None   # pending capture reason
        self._active_dir: str | None = None  # capture in flight
        self._remaining = 0
        self._started_step = 0
        self._last_step = 0
        self._disabled = False
        self._captures = (registry or default_registry()).counter(
            "profiler_captures_total", "on-demand jax.profiler captures")

    # -- triggers --------------------------------------------------------
    def request(self, reason: str = "manual") -> None:
        """Ask for a capture of the next ``capture_steps`` steps
        (idempotent while one is pending/active)."""
        with self._lock:
            if self._requested is None and self._active_dir is None:
                self._requested = reason

    def install_sigusr2(self) -> bool:
        """SIGUSR2 -> request(); False when not installable (non-main
        thread, e.g. a supervised attempt worker)."""
        import signal

        def on_signal(*_):
            # Flag only — no lock: the handler can interrupt on_step
            # while it already holds self._lock (see __init__).
            self._signal_pending = True

        try:
            signal.signal(signal.SIGUSR2, on_signal)
            return True
        except ValueError:
            logger.warning("SIGUSR2 trigger unavailable off the main "
                           "thread; use the trigger file %s",
                           self.trigger_file)
            return False

    def _check_trigger_file(self) -> None:
        try:
            if not os.path.exists(self.trigger_file):
                return
            # Consume the file only when the request can actually be
            # accepted: removing it during an active/pending capture
            # would silently drop the operator's ask — leaving it in
            # place coalesces it into the next free window instead.
            with self._lock:
                busy = (self._requested is not None
                        or self._active_dir is not None)
            if busy:
                return
            os.remove(self.trigger_file)
            self.request("trigger_file")
        except OSError:
            pass

    # -- per-step driver -------------------------------------------------
    def on_step(self, step: int, duration_ms: float) -> None:
        if self._disabled:
            return
        if self._signal_pending:
            self._signal_pending = False
            self.request("sigusr2")
        self._check_trigger_file()
        self._last_step = int(step)
        with self._lock:
            if self._active_dir is not None:
                self._remaining -= 1
                if self._remaining <= 0:
                    self._stop_locked(step)
                # Captured steps stay out of the baseline window: trace
                # overhead inflates them, and a capture must not shift
                # the very median it was judged against.
                return
            baseline = (statistics.median(self._window)
                        if len(self._window) >= self.warmup_steps else None)
            reason = self._requested
            if reason is None and baseline is not None \
                    and duration_ms > self.slow_factor * baseline:
                reason = (f"slow_step:{duration_ms:.1f}ms>"
                          f"{self.slow_factor:g}x median "
                          f"{baseline:.1f}ms")
            if reason is not None:
                self._requested = None
                self._start_locked(step, reason)
                return
            self._window.append(duration_ms)

    # -- capture lifecycle (lock held) -----------------------------------
    def _start_locked(self, step: int, reason: str) -> None:
        stamp = time.strftime("%Y%m%d-%H%M%S")
        target = os.path.join(self.trace_dir, f"step{step}-{stamp}")
        try:
            import jax

            os.makedirs(target, exist_ok=True)
            jax.profiler.start_trace(target)
        except Exception as e:
            logger.error("profiler capture failed to start (%s: %s) — "
                         "disabling further captures", type(e).__name__, e)
            self._disabled = True
            return
        self._active_dir = target
        self._remaining = self.capture_steps
        self._started_step = step
        logger.warning("profiler: capturing %d steps to %s (%s)",
                       self.capture_steps, target, reason)
        events.emit("trace", action="start", step=int(step),
                    reason=reason, trace_dir=target,
                    capture_steps=self.capture_steps)

    def _stop_locked(self, step: int) -> None:
        target, self._active_dir = self._active_dir, None
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            logger.error("profiler stop_trace failed (%s: %s) — "
                         "disabling further captures", type(e).__name__, e)
            self._disabled = True
            return
        self._captures.inc()
        logger.info("profiler: capture complete -> %s", target)
        # The trigger step itself is not captured (capture covers the
        # NEXT steps), so coverage is the span after _started_step.
        events.emit("trace", action="complete", step=int(step),
                    trace_dir=target,
                    steps_captured=int(step) - self._started_step)

    def close(self) -> None:
        """End any in-flight capture (run teardown); the `complete`
        event reports how far the truncated capture actually got."""
        with self._lock:
            if self._active_dir is not None:
                self._stop_locked(max(self._last_step,
                                      self._started_step))
