"""Structured event log: typed JSONL records with run/attempt identity.

The machine-readable counterpart of the framework's log lines (ISSUE 3):
the divergence guard, RetryPolicy, checkpoint manager, supervisor, and
the per-step training timeline all append here, so a stalled or
slowly-degrading run can be diagnosed AFTER the fact from one stream
instead of grepping free-form logger output.

Record shape (one JSON object per line)::

    {"event": "step", "t": 12.345678, "wall": 1791234567.123,
     "run_id": "a1b2c3d4", "attempt": 0, ...event-specific fields}

* ``t`` is a MONOTONIC offset (seconds since the log opened): ordering
  and intervals survive wall-clock jumps (NTP slew mid-run must not
  reorder a timeline); ``wall`` is epoch time for cross-run correlation.
* ``run_id`` is fixed per EventLog; ``attempt`` is bumped by the
  supervisor at restart boundaries (``set_attempt``), so records from a
  rolled-back attempt are distinguishable from its replacement's.
* Core event types are ``EVENT_TYPES``; unknown types are accepted (the
  stream is extensible — bench records ride the same writer) but typos
  in the core vocabulary would be silent, so callers should prefer it.

The writer is thread-safe and append-only; each record is one
``write()`` of a complete line onto a line-buffered handle, so
concurrent writers (watchdog thread, checkpoint thread, train loop)
never interleave bytes and a reader can tail the file mid-run.

Mirror-to-logger mode (``mirror_logger=True``) duplicates every record
onto ``logging`` as ``key=value`` pairs via
``utils.logging_utils.format_kv`` — human-greppable without running a
JSON parser over the console.

A process-wide hub (``install``/``get_event_log``/``emit``) lets deep
instrumentation sites (retry loops, the watchdog thread) publish without
plumbing an EventLog handle through every constructor; with nothing
installed, ``emit`` is a cheap no-op.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import uuid
from collections import deque

logger = logging.getLogger(__name__)

__all__ = ["EVENT_TYPES", "EventLog", "install", "get_event_log", "emit",
           "set_attempt", "read_events", "dump_flight"]

# The core vocabulary. step: one completed train step's timeline.
# retry: a transient fault survived by RetryPolicy. divergence: a
# non-finite step (guarded skip/backoff/rollback, or observed unguarded).
# restart: a supervisor attempt boundary. checkpoint: save/restore/
# fallback/delete. compile: an AOT step compile. trace: a profiler
# capture artifact. span: one timed causal interval (obs/trace.py —
# serving request stages, or any `with trace.span(...)` block).
# rollout: a serving worker's checkpoint swap/rollback (serving/
# worker.py). fleet: a supervision lifecycle action (spawn/death/eject/
# restart — serving/fleet.py). alert: an SLO or canary-verdict breach/
# resolution (obs/slo.py, router rollback) — the typed record the
# flight recorder and /alerts surface. comms_profile: a compiled step's
# static per-collective traffic profile (obs/timeline.py). bench: one
# bench.py measurement record riding the run's stream. Both were
# emitted-but-undeclared until the telemetry-schema lint (ISSUE 13)
# made every literal emit type check against this tuple; runtime still
# accepts unknown types (extensibility), the LINTER is now the typo
# guard. index: a retrieval-tier index lifecycle action (ISSUE 15,
# ntxent_tpu/retrieval/ — build/seal/compact/activate/promote/rollback/
# drop/stale/rebuild). autoscale: a fleet-sizing control action
# (ISSUE 16, serving/autoscale.py — scale_up/drain_start/drain_done/
# hold decisions with the signal snapshot that drove them). anomaly: a
# history-series changepoint (ISSUE 18, obs/history.py — rolling
# median+MAD breach/resolution; the firing transition also trips the
# flight recorder, like an SLO breach). forecast: a predictive
# scale-up trigger (ISSUE 18 — the Holt-Winters lead-time forecast
# that crossed the controller's pressure bound, recorded with the
# horizon and projected values that drove it). comms_overlap: one
# measured computation-collective overlap window (ISSUE 19,
# obs/timeline.py — the monolithic-vs-chunked on-chip A/B that prices
# the ring schedule's hidden transfer time; the CPU census pins bytes,
# this event pins the milliseconds).
EVENT_TYPES = ("step", "retry", "divergence", "restart", "checkpoint",
               "compile", "trace", "span", "rollout", "fleet", "alert",
               "comms_profile", "bench", "index", "autoscale",
               "anomaly", "forecast", "comms_overlap")


class EventLog:
    """Append-only typed JSONL writer with optional logger mirror.

    ``path=None`` keeps records in a bounded in-memory tail only (tests;
    metrics-only runs) — ``emit`` stays cheap either way.

    ``async_io=True`` moves the file write — and since ISSUE 10 the
    JSON serialization too — off the emitting thread: one daemon
    writer drains a bounded queue of record dicts, serializes them,
    and writes onto the same line-buffered handle (records still never
    interleave — single consumer — and the file stays tail-able within
    the writer's ~0.2 s poll; bursts past 64 queued records wake it
    immediately). This is the
    mode for emitters on latency-critical paths: the serving stack's
    span emits ride the micro-batcher's dispatch loop, where a
    per-record flush syscall measurably backs up the bounded request
    queue under burst load (ISSUE 7; serving_smoke's concurrency phase
    is the regression test). Overflow drops the OLDEST queued record
    and counts it (``dropped_writes``) — backpressure from a slow disk
    must throttle telemetry, never requests. The in-memory tail (and so
    the flight recorder) always sees every record. ``close()`` drains
    the queue before closing, so nothing is lost on a clean shutdown.
    """

    def __init__(self, path: str | None = None, run_id: str | None = None,
                 mirror_logger: bool = False, tail: int = 256,
                 async_io: bool = False, write_queue_max: int = 4096):
        self.path = path
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.mirror_logger = mirror_logger
        self.dropped_writes = 0
        self._attempt = 0
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._counts: dict[str, int] = {}
        self._tail: deque[dict] = deque(maxlen=tail)
        self._fh = None
        self._write_queue: deque[str] | None = None
        self._write_queue_max = int(write_queue_max)
        self._writer: threading.Thread | None = None
        self._writer_wake = threading.Event()
        self._inflight = 0
        self._closing = False
        if path is not None:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            # Line-buffered append: one write per record, tail-able live.
            self._fh = open(path, "a", buffering=1)
            if async_io:
                self._write_queue = deque()
                self._writer = threading.Thread(
                    target=self._drain_writes, daemon=True,
                    name="ntxent-eventlog-writer")
                self._writer.start()

    # -- identity --------------------------------------------------------
    def set_attempt(self, attempt: int) -> None:
        """Stamp subsequent records with a supervisor attempt ordinal."""
        with self._lock:
            self._attempt = int(attempt)

    @property
    def attempt(self) -> int:
        return self._attempt

    # -- writing ---------------------------------------------------------
    def emit(self, event: str, **fields) -> dict:
        """Append one record; returns the record (tests; chaining)."""
        record = {
            "event": str(event),
            "t": round(time.monotonic() - self._t0, 6),
            "wall": round(time.time(), 6),
            "run_id": self.run_id,
            "attempt": self._attempt,
            **fields,
        }
        # Serialize only when a sink will consume the bytes AND the
        # serialization must happen HERE: the path=None metrics-only
        # mode promises emit stays cheap, and async mode defers even
        # the json.dumps to the writer thread (ISSUE 10: the obs
        # overhead gate measured per-emit serialization as the
        # dominant telemetry cost on serving's span-per-hop paths —
        # the record dict is freshly built and never mutated after
        # emit, so handing it over is safe).
        line = (json.dumps(_sanitize(record), sort_keys=False,
                           default=_jsonable)
                if self._fh is not None and self._write_queue is None
                else None)
        with self._lock:
            self._counts[record["event"]] = \
                self._counts.get(record["event"], 0) + 1
            self._tail.append(record)
            if self._fh is not None:
                if self._write_queue is not None:
                    # Async mode: hand the RECORD to the writer thread;
                    # the emitter pays neither serialization nor
                    # filesystem. The wake is batched: the writer polls
                    # every 0.2 s anyway, so emits only signal it when
                    # a burst is piling up — a per-emit futex wake
                    # measurably taxes a 2-core host (the obs bench).
                    if len(self._write_queue) >= self._write_queue_max:
                        self._write_queue.popleft()
                        self.dropped_writes += 1
                    self._write_queue.append(record)
                    if len(self._write_queue) >= 64:
                        self._writer_wake.set()
                elif line is not None:
                    try:
                        self._fh.write(line + "\n")
                    except OSError as e:  # a full disk must not kill
                        # training
                        logger.error("event log write failed (%s); "
                                     "record dropped: %s", e, line[:200])
        if self.mirror_logger:
            # Lazy import keeps this module loadable WITHOUT package
            # context (bench.py's parent loads it by file path so the
            # JAX-importing package __init__ never runs there).
            from ..utils.logging_utils import format_kv

            logger.info("%s", format_kv(record))
        return record

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    # -- flight recorder -------------------------------------------------
    def dump_flight(self, directory: str | None = None,
                    reason: str = "manual",
                    routine: bool = False) -> str | None:
        """Write the bounded in-memory tail to ``flight_<ts>.jsonl``.

        The postmortem path for runs that did NOT enable ``--log-jsonl``:
        the tail ring exists on every EventLog (path=None included), so a
        stall escalation or a shutdown signal can still leave the last N
        typed events on disk. Target directory: explicit arg, then
        ``NTXENT_FLIGHT_DIR``, then the log file's own directory, then
        the CWD. ``routine=True`` (the graceful-preemption path: SIGTERM
        on a preemptible VM is normal, not a fault) skips the CWD
        fallback — an expected shutdown must not litter the working
        directory; a stall escalation dumps unconditionally. Returns the
        written path, or None when skipped, the ring is empty, or the
        write failed (a postmortem helper must never take the process
        down on a full disk).
        """
        with self._lock:
            records = list(self._tail)
        if not records:
            return None
        directory = (directory or os.environ.get("NTXENT_FLIGHT_DIR")
                     or (os.path.dirname(os.path.abspath(self.path))
                         if self.path else None))
        if directory is None:
            if routine:
                return None
            directory = "."
        ts = time.strftime("%Y%m%d-%H%M%S")
        path = os.path.join(directory,
                            f"flight_{ts}-{uuid.uuid4().hex[:6]}.jsonl")
        header = {"event": "flight", "reason": str(reason),
                  "run_id": self.run_id, "attempt": self._attempt,
                  "records": len(records),
                  "wall": round(time.time(), 6)}
        try:
            os.makedirs(directory, exist_ok=True)
            with open(path, "w") as f:
                for record in [header] + records:
                    f.write(json.dumps(_sanitize(record),
                                       default=_jsonable) + "\n")
        except OSError as e:
            logger.error("flight recorder dump to %s failed: %s", path, e)
            return None
        logger.warning("flight recorder: dumped last %d events to %s "
                       "(reason: %s)", len(records), path, reason)
        return path

    def tail(self, n: int = 20) -> list[dict]:
        with self._lock:
            return list(self._tail)[-n:]

    def _drain_writes(self) -> None:
        """Writer-thread loop (async_io): batch-drain queued lines onto
        the line-buffered handle. Single consumer — records never
        interleave, exactly as in the synchronous mode. ``_inflight``
        stays nonzero from pop to write-complete so ``flush`` cannot
        return while a popped batch has yet to reach the file. A failed
        write REQUEUES the popped batch at the front of the queue and
        retries after a short backoff — a transient EIO/ENOSPC on one
        syscall must cost a retry, not a whole popped batch (up to
        ``write_queue_max`` records, where sync mode would lose exactly
        one). The queue bound still holds: requeue overflow drops the
        oldest records into ``dropped_writes``, and once ``close()`` has
        latched ``_closing`` a failing final attempt drops-and-counts
        instead of retrying forever against a dead disk."""
        while True:
            self._writer_wake.wait(0.2)
            self._writer_wake.clear()
            raw: list[dict] = []
            with self._lock:
                while self._write_queue:
                    raw.append(self._write_queue.popleft())
                self._inflight = len(raw)
                fh = self._fh
                closing = self._closing
            # Serialization happens HERE, off every emitting thread and
            # outside the lock (ISSUE 10: per-emit json.dumps was the
            # measured hot-path cost the async mode exists to remove).
            # Guarded per record: one unserializable field must cost
            # ONE record (dropped and counted), never the writer
            # thread — a dead writer silently ends the whole stream.
            lines = []
            ok_raw = []  # what a failed WRITE may requeue: never the
            #              record that already failed to serialize
            for rec in raw:
                try:
                    line = json.dumps(_sanitize(rec), sort_keys=False,
                                      default=_jsonable)
                except Exception as e:  # noqa: BLE001
                    with self._lock:
                        self.dropped_writes += 1
                    logger.error("event log record unserializable "
                                 "(%s); dropped", e)
                    continue
                lines.append(line)
                ok_raw.append(rec)
            failed = False
            if lines and fh is not None:
                try:
                    fh.write("\n".join(lines) + "\n")
                except (OSError, ValueError) as e:  # full disk / closed
                    failed = True
                    with self._lock:
                        closing = closing or self._closing
                        if closing or self._write_queue is None:
                            self.dropped_writes += len(lines)
                        else:
                            for rec in reversed(ok_raw):
                                self._write_queue.appendleft(rec)
                            while (len(self._write_queue)
                                   > self._write_queue_max):
                                self._write_queue.popleft()
                                self.dropped_writes += 1
                    logger.error("event log async write failed (%s); "
                                 "%d record(s) %s", e, len(lines),
                                 "dropped" if closing else "requeued")
            with self._lock:
                self._inflight = 0
            if closing and not lines:
                return
            if failed and not closing:
                time.sleep(0.05)  # back off a sick disk before retrying

    def flush(self, timeout_s: float = 5.0) -> bool:
        """Block until queued async writes have reached the file (no-op
        in synchronous mode) — tests and pre-export sync points.

        Returns True when everything queued at call time is in the
        file; False when the timeout expired or nothing can drain the
        remainder (writer thread dead after ``close()``, or writes
        still failing) — a pre-export sync point must be able to tell
        a truncated file from a synced one instead of proceeding on
        silence."""
        deadline = time.monotonic() + timeout_s
        while True:
            with self._lock:
                pending = bool(self._write_queue) or self._inflight > 0
            if not pending:
                return True
            writer = self._writer
            if writer is None or not writer.is_alive():
                return False
            if time.monotonic() >= deadline:
                return False
            self._writer_wake.set()
            time.sleep(0.005)

    def close(self) -> None:
        writer = self._writer
        if writer is not None:
            with self._lock:
                self._closing = True
            self._writer_wake.set()
            writer.join(5.0)  # drains the queue before the handle closes
            self._writer = None
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _sanitize(obj):
    """Strict-JSON safety, enforced HERE for every emitter: the format
    has no NaN/inf literal, so non-finite floats become their repr
    strings instead of json.dumps's invalid bare ``NaN`` tokens (one
    rule at the write point, not re-implemented per call site)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _jsonable(value):
    """Last-resort JSON coercion: numpy/jax scalars -> finite float,
    everything else -> repr (an unserializable field must not drop the
    record, and must not smuggle a bare NaN past _sanitize either)."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return repr(value)
    return f if math.isfinite(f) else repr(f)


def read_events(path: str, event: str | None = None) -> list[dict]:
    """Parse a JSONL event file (optionally one event type); skips
    corrupt lines rather than failing the whole read — a live tail can
    catch a record mid-write only if the writer died inside write()."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if event is None or record.get("event") == event:
                out.append(record)
    return out


# -- process-wide hub ----------------------------------------------------
_hub_lock = threading.Lock()
_event_log: EventLog | None = None


def install(event_log: EventLog | None) -> EventLog | None:
    """Install (or clear, with None) the process-wide event log; returns
    the previous one so tests can restore it."""
    global _event_log
    with _hub_lock:
        previous, _event_log = _event_log, event_log
    return previous


def get_event_log() -> EventLog | None:
    return _event_log


def emit(event: str, **fields) -> None:
    """Publish to the installed event log, if any (cheap no-op
    otherwise) — the spelling deep instrumentation sites use."""
    log = _event_log
    if log is not None:
        log.emit(event, **fields)


def set_attempt(attempt: int) -> None:
    log = _event_log
    if log is not None:
        log.set_attempt(attempt)


def dump_flight(reason: str = "manual", directory: str | None = None,
                routine: bool = False) -> str | None:
    """Dump the installed event log's tail ring (no-op without one) —
    the spelling the supervisor's stall escalation and the preemption
    guard's signal path use."""
    log = _event_log
    if log is not None:
        return log.dump_flight(directory, reason=reason, routine=routine)
    return None
