"""Structured event log: typed JSONL records with run/attempt identity.

The machine-readable counterpart of the framework's log lines (ISSUE 3):
the divergence guard, RetryPolicy, checkpoint manager, supervisor, and
the per-step training timeline all append here, so a stalled or
slowly-degrading run can be diagnosed AFTER the fact from one stream
instead of grepping free-form logger output.

Record shape (one JSON object per line)::

    {"event": "step", "t": 12.345678, "wall": 1791234567.123,
     "run_id": "a1b2c3d4", "attempt": 0, ...event-specific fields}

* ``t`` is a MONOTONIC offset (seconds since the log opened): ordering
  and intervals survive wall-clock jumps (NTP slew mid-run must not
  reorder a timeline); ``wall`` is epoch time for cross-run correlation.
* ``run_id`` is fixed per EventLog; ``attempt`` is bumped by the
  supervisor at restart boundaries (``set_attempt``), so records from a
  rolled-back attempt are distinguishable from its replacement's.
* Core event types are ``EVENT_TYPES``; unknown types are accepted (the
  stream is extensible — bench records ride the same writer) but typos
  in the core vocabulary would be silent, so callers should prefer it.

The writer is thread-safe and append-only; each record is one
``write()`` of a complete line onto a line-buffered handle, so
concurrent writers (watchdog thread, checkpoint thread, train loop)
never interleave bytes and a reader can tail the file mid-run.

Mirror-to-logger mode (``mirror_logger=True``) duplicates every record
onto ``logging`` as ``key=value`` pairs via
``utils.logging_utils.format_kv`` — human-greppable without running a
JSON parser over the console.

A process-wide hub (``install``/``get_event_log``/``emit``) lets deep
instrumentation sites (retry loops, the watchdog thread) publish without
plumbing an EventLog handle through every constructor; with nothing
installed, ``emit`` is a cheap no-op.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
import uuid
from collections import deque

logger = logging.getLogger(__name__)

__all__ = ["EVENT_TYPES", "EventLog", "install", "get_event_log", "emit",
           "set_attempt", "read_events"]

# The core vocabulary. step: one completed train step's timeline.
# retry: a transient fault survived by RetryPolicy. divergence: a
# non-finite step (guarded skip/backoff/rollback, or observed unguarded).
# restart: a supervisor attempt boundary. checkpoint: save/restore/
# fallback/delete. compile: an AOT step compile. trace: a profiler
# capture artifact.
EVENT_TYPES = ("step", "retry", "divergence", "restart", "checkpoint",
               "compile", "trace")


class EventLog:
    """Append-only typed JSONL writer with optional logger mirror.

    ``path=None`` keeps records in a bounded in-memory tail only (tests;
    metrics-only runs) — ``emit`` stays cheap either way.
    """

    def __init__(self, path: str | None = None, run_id: str | None = None,
                 mirror_logger: bool = False, tail: int = 256):
        self.path = path
        self.run_id = run_id or uuid.uuid4().hex[:8]
        self.mirror_logger = mirror_logger
        self._attempt = 0
        self._lock = threading.Lock()
        self._t0 = time.monotonic()
        self._counts: dict[str, int] = {}
        self._tail: deque[dict] = deque(maxlen=tail)
        self._fh = None
        if path is not None:
            parent = os.path.dirname(os.path.abspath(path))
            os.makedirs(parent, exist_ok=True)
            # Line-buffered append: one write per record, tail-able live.
            self._fh = open(path, "a", buffering=1)

    # -- identity --------------------------------------------------------
    def set_attempt(self, attempt: int) -> None:
        """Stamp subsequent records with a supervisor attempt ordinal."""
        with self._lock:
            self._attempt = int(attempt)

    @property
    def attempt(self) -> int:
        return self._attempt

    # -- writing ---------------------------------------------------------
    def emit(self, event: str, **fields) -> dict:
        """Append one record; returns the record (tests; chaining)."""
        record = {
            "event": str(event),
            "t": round(time.monotonic() - self._t0, 6),
            "wall": round(time.time(), 6),
            "run_id": self.run_id,
            "attempt": self._attempt,
            **fields,
        }
        # Serialize only when a sink will consume the bytes: the
        # path=None metrics-only mode promises emit stays cheap.
        line = (json.dumps(_sanitize(record), sort_keys=False,
                           default=_jsonable)
                if self._fh is not None else None)
        with self._lock:
            self._counts[record["event"]] = \
                self._counts.get(record["event"], 0) + 1
            self._tail.append(record)
            if self._fh is not None and line is not None:
                try:
                    self._fh.write(line + "\n")
                except OSError as e:  # a full disk must not kill training
                    logger.error("event log write failed (%s); record "
                                 "dropped: %s", e, line[:200])
        if self.mirror_logger:
            # Lazy import keeps this module loadable WITHOUT package
            # context (bench.py's parent loads it by file path so the
            # JAX-importing package __init__ never runs there).
            from ..utils.logging_utils import format_kv

            logger.info("%s", format_kv(record))
        return record

    def counts(self) -> dict[str, int]:
        with self._lock:
            return dict(self._counts)

    def tail(self, n: int = 20) -> list[dict]:
        with self._lock:
            return list(self._tail)[-n:]

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.close()
                finally:
                    self._fh = None

    def __enter__(self) -> "EventLog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _sanitize(obj):
    """Strict-JSON safety, enforced HERE for every emitter: the format
    has no NaN/inf literal, so non-finite floats become their repr
    strings instead of json.dumps's invalid bare ``NaN`` tokens (one
    rule at the write point, not re-implemented per call site)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else repr(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _jsonable(value):
    """Last-resort JSON coercion: numpy/jax scalars -> finite float,
    everything else -> repr (an unserializable field must not drop the
    record, and must not smuggle a bare NaN past _sanitize either)."""
    try:
        f = float(value)
    except (TypeError, ValueError):
        return repr(value)
    return f if math.isfinite(f) else repr(f)


def read_events(path: str, event: str | None = None) -> list[dict]:
    """Parse a JSONL event file (optionally one event type); skips
    corrupt lines rather than failing the whole read — a live tail can
    catch a record mid-write only if the writer died inside write()."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except ValueError:
                continue
            if event is None or record.get("event") == event:
                out.append(record)
    return out


# -- process-wide hub ----------------------------------------------------
_hub_lock = threading.Lock()
_event_log: EventLog | None = None


def install(event_log: EventLog | None) -> EventLog | None:
    """Install (or clear, with None) the process-wide event log; returns
    the previous one so tests can restore it."""
    global _event_log
    with _hub_lock:
        previous, _event_log = _event_log, event_log
    return previous


def get_event_log() -> EventLog | None:
    return _event_log


def emit(event: str, **fields) -> None:
    """Publish to the installed event log, if any (cheap no-op
    otherwise) — the spelling deep instrumentation sites use."""
    log = _event_log
    if log is not None:
        log.emit(event, **fields)


def set_attempt(attempt: int) -> None:
    log = _event_log
    if log is not None:
        log.set_attempt(attempt)
