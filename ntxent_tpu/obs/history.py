"""Fleet time-series plane: retained metrics history + its consumers.

Every federation tick (obs/aggregate.py) builds a rich merged view of
the fleet — and forgets it the moment the next tick lands. Nothing in
the stack could answer "what did queue depth look like over the last
ten minutes", so trajectory questions (is the diurnal ramp coming? did
cache hit rate start sagging an hour ago?) were structurally
unanswerable (ISSUE 18). This module is the retained plane:

* ``MetricHistory`` — an embedded per-series time-series store:
  append-only ring buffers with STAGED DOWNSAMPLING (raw samples →
  10 s rollups → 1 m rollups of min/max/mean/last/n), so memory stays
  bounded while the retained horizon grows with coarseness. Optional
  durable spill reuses the checkpoint tier's stage-fsync-rename idiom
  (training/checkpoint.py): a router restart reopens with history
  intact. Served as ``GET /metrics/history`` on the fleet router.
* ``HistoryRecorder`` — the ``FleetAggregator.on_merge`` hook that
  reduces each merged registry into scalar series samples
  (gauge sums/maxes, windowed counter rates, pooled histogram
  quantiles via the one exact-window quantile rule, delta ratios like
  cache hit rate) and records them.
* ``AnomalyDetector`` — the ProfilerTrigger rule generalized: a
  rolling median + MAD per watched series, armed only after a warmup
  sample count, anomalous samples excluded from their own baseline.
  A breach fires a typed ``anomaly`` event, an ``AlertStore`` entry,
  and ONE flight-recorder dump per incident — the same alert path SLO
  breaches ride.
* ``Forecaster`` — Holt-Winters-style double (optionally triple, with
  an additive seasonal term) exponential smoothing over an
  irregularly-ticked series. ``AutoscaleController`` feeds it the
  request-rate and queue-depth series and reads a ``--predict-horizon``
  lead-time forecast, so scale-up can fire BEFORE a diurnal ramp
  breaches; the forecast is hard-bounded (``bound_min``/``bound_max``)
  so a wild model can never demand absurd capacity, and the
  controller's cooldowns/max_workers still gate every action.

Stdlib only (the obs-package rule): the store runs in the router
process, which never imports JAX.
"""

from __future__ import annotations

import json
import logging
import math
import os
import statistics
import threading
import time
import uuid
from collections import deque
from dataclasses import dataclass, field

from . import events
from .registry import MetricsRegistry, quantile
from .slo import AlertStore, counter_total, histogram_quantile

logger = logging.getLogger(__name__)

__all__ = ["MetricHistory", "HistoryRecorder", "SeriesSpec",
           "AnomalyDetector", "Forecaster", "DEFAULT_SERIES",
           "gauge_reduce", "ingest_timeline"]

# The two rollup resolutions, coarsest-retained last. Names are the
# query vocabulary (``?step=raw|10s|1m``); seconds are the bucket
# widths the rollup accumulators seal on.
ROLLUP_STEPS = (("10s", 10.0), ("1m", 60.0))
_SPILL_FILE = "history.json"
_SPILL_VERSION = 1


def _fsync_path(path: str) -> None:
    """fsync a file or directory (same contract as the checkpoint
    tier's helper, re-spelled here because training/checkpoint.py
    imports JAX and obs must not; ``NTXENT_CKPT_NO_FSYNC=1`` is the
    same bench-only skip)."""
    if os.environ.get("NTXENT_CKPT_NO_FSYNC") == "1":
        return
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _new_bucket(t_start: float, value: float) -> dict:
    return {"t": t_start, "n": 1, "sum": value, "min": value,
            "max": value, "last": value}


def _bucket_add(bucket: dict, value: float) -> None:
    bucket["n"] += 1
    bucket["sum"] += value
    if value < bucket["min"]:
        bucket["min"] = value
    if value > bucket["max"]:
        bucket["max"] = value
    bucket["last"] = value


def _bucket_view(bucket: dict) -> dict:
    """The query shape of one rollup point (mean derived, sum kept
    internal so repeated queries can't drift it)."""
    return {"t": bucket["t"], "n": bucket["n"],
            "min": bucket["min"], "max": bucket["max"],
            "mean": bucket["sum"] / bucket["n"],
            "last": bucket["last"]}


class _Series:
    """One series' staged storage: raw ring + one sealed ring and one
    open accumulator per rollup resolution."""

    __slots__ = ("raw", "rings", "open")

    def __init__(self, raw_len: int, rollup_len: int):
        self.raw: deque = deque(maxlen=raw_len)
        self.rings: dict[str, deque] = {
            name: deque(maxlen=rollup_len) for name, _ in ROLLUP_STEPS}
        self.open: dict[str, dict | None] = {
            name: None for name, _ in ROLLUP_STEPS}

    def append(self, t: float, value: float) -> None:
        self.raw.append((t, value))
        for name, step_s in ROLLUP_STEPS:
            start = math.floor(t / step_s) * step_s
            bucket = self.open[name]
            if bucket is None:
                self.open[name] = _new_bucket(start, value)
            elif start > bucket["t"]:
                self.rings[name].append(bucket)
                self.open[name] = _new_bucket(start, value)
            else:
                # Same bucket — or a clock regression, which folds into
                # the open bucket rather than rewriting sealed history.
                _bucket_add(bucket, value)

    def points(self, step: str) -> list[dict]:
        if step == "raw":
            return [{"t": t, "value": v} for t, v in self.raw]
        out = [_bucket_view(b) for b in self.rings[step]]
        if self.open[step] is not None:
            # The open bucket is part of the truth: a query must see
            # every recorded sample, sealed or not.
            out.append(_bucket_view(self.open[step]))
        return out

    def dump(self) -> dict:
        return {"raw": [[t, v] for t, v in self.raw],
                "rings": {name: list(ring)
                          for name, ring in self.rings.items()},
                "open": {name: b for name, b in self.open.items()}}

    def load(self, state: dict) -> None:
        for t, v in state.get("raw") or []:
            self.raw.append((float(t), float(v)))
        for name, _ in ROLLUP_STEPS:
            for b in (state.get("rings") or {}).get(name) or []:
                self.rings[name].append(dict(b))
            open_b = (state.get("open") or {}).get(name)
            self.open[name] = dict(open_b) if open_b else None


class MetricHistory:
    """Bounded embedded time-series store with staged downsampling.

    Memory is bounded by construction: ``max_series`` series, each
    holding ``raw_len`` raw samples + ``rollup_len`` sealed buckets
    per rollup resolution (new series past the cap are dropped and
    counted — an unbounded series vocabulary must degrade the history,
    never the process).

    ``spill_dir`` arms durability: ``maybe_spill`` (called by the
    recorder once per ``spill_interval_s``) stages the full store as
    JSON, fsyncs, and renames into place — the checkpoint tier's
    crash-atomicity idiom — and a fresh ``MetricHistory`` over the same
    directory reopens with everything the last spill saw.
    """

    def __init__(self, raw_len: int = 720, rollup_len: int = 720,
                 max_series: int = 256, spill_dir: str | None = None,
                 spill_interval_s: float = 30.0,
                 registry: MetricsRegistry | None = None,
                 clock=time.time):
        if raw_len < 1 or rollup_len < 1:
            raise ValueError("raw_len and rollup_len must be >= 1")
        if max_series < 1:
            raise ValueError(f"max_series must be >= 1, got {max_series}")
        self.raw_len = int(raw_len)
        self.rollup_len = int(rollup_len)
        self.max_series = int(max_series)
        self.spill_dir = spill_dir
        self.spill_interval_s = float(spill_interval_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._series: dict[str, _Series] = {}
        self._last_spill: float | None = None
        r = registry
        self._g_series = r.gauge(
            "obs_history_series", "series retained in the history "
            "store") if r is not None else None
        self._c_samples = r.counter(
            "obs_history_samples_total",
            "samples recorded into the history store") \
            if r is not None else None
        self._c_dropped = r.counter(
            "obs_history_dropped_series_total",
            "series refused at the max_series cap") \
            if r is not None else None
        self._c_spills = r.counter(
            "obs_history_spills_total",
            "durable spills written") if r is not None else None
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
            self._reopen()

    # -- writing ---------------------------------------------------------
    def record(self, series: str, value: float,
               t: float | None = None) -> bool:
        """Append one sample; returns False when the series was refused
        at the cap or the value is not a finite number."""
        try:
            value = float(value)
        except (TypeError, ValueError):
            return False
        if not math.isfinite(value):
            return False
        t = self.clock() if t is None else float(t)
        with self._lock:
            state = self._series.get(series)
            if state is None:
                if len(self._series) >= self.max_series:
                    if self._c_dropped is not None:
                        self._c_dropped.inc()
                    return False
                state = self._series[series] = _Series(
                    self.raw_len, self.rollup_len)
                if self._g_series is not None:
                    self._g_series.set(len(self._series))
            state.append(t, value)
        if self._c_samples is not None:
            self._c_samples.inc()
        return True

    # -- reading ---------------------------------------------------------
    def series_names(self) -> list[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, series: str, step: str = "raw",
              window_s: float | None = None) -> dict:
        """Points for one series at one resolution, newest-last.

        ``step``: ``"raw"`` | ``"10s"`` | ``"1m"`` (numeric spellings
        ``10``/``60`` accepted). ``window_s`` keeps only points whose
        timestamp is within that many seconds of the newest point —
        relative to the DATA, not the wall clock, so a replayed
        timeline queries the same way a live fleet does. Raises
        ``KeyError`` on an unknown series, ``ValueError`` on a bad
        step/window.
        """
        step = _canonical_step(step)
        if window_s is not None:
            window_s = float(window_s)
            if not math.isfinite(window_s) or window_s <= 0:
                raise ValueError(f"window must be > 0, got {window_s}")
        with self._lock:
            state = self._series.get(series)
            if state is None:
                raise KeyError(series)
            points = state.points(step)
        if window_s is not None and points:
            edge = points[-1]["t"] - window_s
            points = [p for p in points if p["t"] >= edge]
        return {"series": series, "step": step, "points": points}

    def snapshot(self) -> dict:
        """Store-level stats for the router's metrics_dict."""
        with self._lock:
            n_series = len(self._series)
            n_raw = sum(len(s.raw) for s in self._series.values())
        return {"series": n_series, "raw_samples": n_raw,
                "max_series": self.max_series,
                "spill_dir": self.spill_dir}

    # -- durability ------------------------------------------------------
    def spill(self) -> str | None:
        """Stage-fsync-rename the whole store into ``spill_dir``;
        returns the final path (None when durability is off or the
        write failed — history durability must never take the router
        down on a full disk)."""
        if self.spill_dir is None:
            return None
        with self._lock:
            payload = {"version": _SPILL_VERSION,
                       "saved_at": self.clock(),
                       "series": {name: s.dump()
                                  for name, s in self._series.items()}}
        final = os.path.join(self.spill_dir, _SPILL_FILE)
        tmp = os.path.join(
            self.spill_dir,
            f".tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            _fsync_path(tmp)
            os.replace(tmp, final)
            _fsync_path(self.spill_dir)
        except OSError as e:
            logger.error("history spill to %s failed: %s", final, e)
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self._last_spill = self.clock()
        if self._c_spills is not None:
            self._c_spills.inc()
        return final

    def maybe_spill(self) -> str | None:
        """Spill when the interval elapsed (the recorder's per-tick
        call site — cheap no-op in between)."""
        if self.spill_dir is None:
            return None
        now = self.clock()
        if self._last_spill is not None \
                and now - self._last_spill < self.spill_interval_s:
            return None
        return self.spill()

    def close(self) -> None:
        """Final spill (teardown path)."""
        self.spill()

    def _reopen(self) -> None:
        path = os.path.join(self.spill_dir, _SPILL_FILE)
        if not os.path.exists(path):
            return
        try:
            with open(path) as f:
                payload = json.load(f)
            series = payload.get("series") or {}
        except (OSError, ValueError) as e:
            logger.warning("history spill at %s unreadable (%s) — "
                           "starting empty", path, e)
            return
        with self._lock:
            for name in sorted(series)[:self.max_series]:
                state = _Series(self.raw_len, self.rollup_len)
                try:
                    state.load(series[name])
                except (TypeError, ValueError, KeyError):
                    continue  # one bad series must not void the rest
                self._series[name] = state
            if self._g_series is not None:
                self._g_series.set(len(self._series))
        logger.info("history reopened from %s: %d series", path,
                    len(series))


def _canonical_step(step) -> str:
    if step in (None, "", "raw"):
        return "raw"
    for name, step_s in ROLLUP_STEPS:
        if step == name:
            return name
        try:
            if float(step) == step_s:
                return name
        except (TypeError, ValueError):
            pass
    valid = ["raw"] + [name for name, _ in ROLLUP_STEPS]
    raise ValueError(f"unknown step {step!r} (want one of {valid})")


# -- reducing a merged registry into scalar series -----------------------


def gauge_reduce(registry: MetricsRegistry, name: str,
                 mode: str = "sum") -> float | None:
    """Reduce every label-set of a gauge (the federated per-instance
    view) to one scalar: ``sum`` (additive state like queue depth) or
    ``max`` (per-process ceilings like RSS). None when absent."""
    values = [float(e.get("value", 0.0))
              for e in registry.dump_state()["metrics"]
              if e["name"] == name and e["kind"] == "gauge"]
    if not values:
        return None
    return sum(values) if mode == "sum" else max(values)


@dataclass
class SeriesSpec:
    """How one history series is extracted from a merged registry.

    ``mode``:

    * ``gauge_sum`` / ``gauge_max`` — reduce the gauge's label-sets;
    * ``counter_rate`` — per-second delta of a cumulative counter
      between successive ticks (the request-rate series);
    * ``quantile`` — pooled exact-window quantile ``q`` of a histogram
      (optionally label-filtered);
    * ``ratio`` — ``d(metric) / (d(metric) + d(denom))`` per tick —
      the hit-rate shape (hits vs misses);
    * ``per`` — ``d(metric) / d(denom)`` per tick — the unit-economy
      shape (bytes per query);
    * ``gauge_labeled`` — one history series PER value of
      ``label_key`` (named ``{name}.{label_value}``), so per-instance
      state like ``retrieval_shard_up{shard=N}`` stays per-instance:
      a sum would hide one dead shard among N-1 live ones, exactly the
      signal the anomaly detector exists to catch (ISSUE 20).
    """

    name: str
    metric: str
    mode: str = "gauge_sum"
    labels: dict = field(default_factory=dict)
    q: float = 0.99
    denom: str | None = None
    label_key: str | None = None

    def __post_init__(self):
        if self.mode not in ("gauge_sum", "gauge_max", "counter_rate",
                             "quantile", "ratio", "per",
                             "gauge_labeled"):
            raise ValueError(f"unknown series mode {self.mode!r}")
        if self.mode in ("ratio", "per") and not self.denom:
            raise ValueError(f"series {self.name!r} mode {self.mode!r} "
                             "needs a denom metric")
        if self.mode == "gauge_labeled" and not self.label_key:
            raise ValueError(f"series {self.name!r} mode gauge_labeled "
                             "needs a label_key")


# The default watch set: the series the ISSUE 18 detector/forecaster
# consumers are specified over. Extraction is skip-on-absent, so a
# fleet without (say) retrieval attached simply never grows those
# series.
DEFAULT_SERIES = (
    SeriesSpec("fleet_request_rate", "fleet_requests_total",
               mode="counter_rate"),
    SeriesSpec("serving_queue_depth", "serving_queue_depth",
               mode="gauge_sum"),
    SeriesSpec("fleet_p99_ms", "fleet_latency_ms", mode="quantile",
               labels={"stage": "total"}, q=0.99),
    # q=1.0 is the pooled window MAX under the exact-window quantile
    # rule — the series a short stall actually moves (a 3 s wedge hangs
    # a handful of requests: invisible to p99 over hundreds of samples,
    # unmissable here). Matches loadgen's per-second timeline key.
    SeriesSpec("fleet_latency_max_ms", "fleet_latency_ms",
               mode="quantile", labels={"stage": "total"}, q=1.0),
    SeriesSpec("fleet_cache_hit_rate", "fleet_cache_hits_total",
               mode="ratio", denom="fleet_cache_misses_total"),
    SeriesSpec("fleet_shadow_drift_p99", "fleet_shadow_drift",
               mode="quantile", q=0.99),
    SeriesSpec("retrieval_recall_probe", "retrieval_recall_probe",
               mode="gauge_max"),
    SeriesSpec("retrieval_scan_bytes_per_query",
               "retrieval_scan_bytes_total", mode="per",
               denom="retrieval_scan_queries_total"),
    SeriesSpec("serving_worker_rss_bytes", "serving_worker_rss_bytes",
               mode="gauge_max"),
    SeriesSpec("serving_compile_cache_entries",
               "serving_compile_cache_entries", mode="gauge_max"),
    # Per-shard liveness (ISSUE 20): one series per shard id, so a
    # single shard dropping 1.0 -> 0.0 is a step the detector flags
    # even while the plane as a whole keeps answering.
    SeriesSpec("retrieval_shard_up", "retrieval_shard_up",
               mode="gauge_labeled", label_key="shard"),
)


class HistoryRecorder:
    """The ``FleetAggregator.on_merge`` hook feeding the store.

    Each tick: reduce the merged registry through every ``SeriesSpec``,
    record the resulting samples, hand each to the detector (when
    armed), and let the store spill if its interval elapsed. Never
    raises — a history bug must not poison federation (the aggregator
    guards hooks too; this is belt and braces for direct callers).
    """

    def __init__(self, history: MetricHistory,
                 series: tuple[SeriesSpec, ...] = DEFAULT_SERIES,
                 detector: "AnomalyDetector | None" = None,
                 clock=time.time):
        self.history = history
        self.series = tuple(series)
        self.detector = detector
        self.clock = clock
        # (t, value) per counter-shaped metric, for rates and deltas.
        self._prev: dict[str, tuple[float, float]] = {}

    def on_merge(self, merged: MetricsRegistry) -> dict[str, float]:
        try:
            return self._tick(merged)
        except Exception:  # noqa: BLE001 — see class docstring.
            logger.exception("history recorder tick failed")
            return {}

    def _tick(self, merged: MetricsRegistry) -> dict[str, float]:
        now = self.clock()
        out: dict[str, float] = {}
        for spec in self.series:
            if spec.mode == "gauge_labeled":
                for name, value in self._extract_labeled(spec, merged):
                    out[name] = value
                    self.history.record(name, value, t=now)
                    if self.detector is not None:
                        self.detector.observe(name, value, t=now)
                continue
            value = self._extract(spec, merged, now)
            if value is None:
                continue
            out[spec.name] = value
            self.history.record(spec.name, value, t=now)
            if self.detector is not None:
                self.detector.observe(spec.name, value, t=now)
        self.history.maybe_spill()
        return out

    def _delta(self, key: str, total: float, now: float,
               ) -> tuple[float, float] | None:
        prev = self._prev.get(key)
        self._prev[key] = (now, total)
        if prev is None:
            return None
        dt = now - prev[0]
        if dt <= 0:
            return None
        return total - prev[1], dt

    def _extract_labeled(self, spec: SeriesSpec,
                         merged: MetricsRegistry,
                         ) -> list[tuple[str, float]]:
        """Expand a ``gauge_labeled`` spec: one ``(series_name,
        value)`` per distinct ``label_key`` value of the gauge, named
        ``{spec.name}.{label_value}``. Label-sets missing the key are
        skipped (they belong to some other instrumentation)."""
        out: list[tuple[str, float]] = []
        for e in merged.dump_state()["metrics"]:
            if e["name"] != spec.metric or e["kind"] != "gauge":
                continue
            lv = e.get("labels", {}).get(spec.label_key)
            if lv is None:
                continue
            out.append((f"{spec.name}.{lv}",
                        float(e.get("value", 0.0))))
        return out

    def _extract(self, spec: SeriesSpec, merged: MetricsRegistry,
                 now: float) -> float | None:
        if spec.mode in ("gauge_sum", "gauge_max"):
            return gauge_reduce(merged, spec.metric,
                                "sum" if spec.mode == "gauge_sum"
                                else "max")
        if spec.mode == "quantile":
            value, n = histogram_quantile(merged, spec.metric, spec.q,
                                          labels=spec.labels)
            return value if n else None
        total = counter_total(merged, spec.metric)
        if spec.mode == "counter_rate":
            d = self._delta(spec.name, total, now)
            return None if d is None else max(0.0, d[0]) / d[1]
        denom_total = counter_total(merged, spec.denom)
        d_num = self._delta(f"{spec.name}:num", total, now)
        d_den = self._delta(f"{spec.name}:den", denom_total, now)
        if d_num is None or d_den is None:
            return None
        if spec.mode == "ratio":
            events_n = d_num[0] + d_den[0]
            return None if events_n <= 0 else d_num[0] / events_n
        return None if d_den[0] <= 0 else d_num[0] / d_den[0]


def ingest_timeline(history: MetricHistory, timeline: list[dict],
                    t0: float = 0.0) -> int:
    """Round-trip a ``scripts/loadgen.py --timeline`` summary into the
    store: each per-second bucket is keyed by history series names
    (ISSUE 18 schema alignment), so a captured replay can be loaded
    and queried exactly like a live fleet's history. Returns the
    number of samples recorded."""
    n = 0
    for bucket in timeline:
        t = t0 + float(bucket.get("t", 0))
        for key, value in bucket.items():
            if key == "t":
                continue
            if history.record(str(key), value, t=t):
                n += 1
    return n


class AnomalyDetector:
    """Rolling median + MAD changepoint watch over history series.

    The ProfilerTrigger rule generalized (obs/profiler.py): per watched
    series, keep a bounded window of NORMAL samples; a sample further
    than ``mad_factor`` scaled deviations from the rolling median is
    anomalous and stays OUT of the window (an incident must not shift
    the baseline it is judged against). Arming waits for ``warmup``
    samples so a cold start's ramp can never fire it. The deviation
    scale is ``max(MAD, rel_floor*|median|, abs_floor)`` — a perfectly
    flat series (MAD 0) still needs a materially sized spike to page.

    Breach side effects mirror the SLO engine's: ``AlertStore.fire``
    (alert name ``anomaly:<series>``), a typed ``anomaly`` event, and
    ONE flight dump per incident; ``clear_ticks`` consecutive normal
    samples resolve it.
    """

    def __init__(self, store: AlertStore | None = None,
                 window: int = 64, warmup: int = 20,
                 mad_factor: float = 6.0, rel_floor: float = 0.05,
                 abs_floor: float = 1e-9, clear_ticks: int = 8,
                 watch: set[str] | None = None,
                 registry: MetricsRegistry | None = None):
        if warmup < 2:
            raise ValueError(f"warmup must be >= 2, got {warmup}")
        if mad_factor <= 0:
            raise ValueError("mad_factor must be > 0")
        self.store = store
        # None = judge every series the recorder feeds; a set restricts
        # the watch to the configured names (an operator scoping the
        # pager to the series that matter on their rig).
        self.watch = set(watch) if watch is not None else None
        self.window = int(window)
        self.warmup = int(warmup)
        self.mad_factor = float(mad_factor)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self.clear_ticks = int(clear_ticks)
        self.registry = registry
        self._lock = threading.Lock()
        self._windows: dict[str, deque] = {}
        self._clear_streak: dict[str, int] = {}
        self._firing: set[str] = set()
        self._counters: dict[str, object] = {}

    def _count(self, series: str) -> None:
        if self.registry is None:
            return
        counter = self._counters.get(series)
        if counter is None:
            counter = self._counters[series] = self.registry.counter(
                "obs_anomalies_total",
                "anomaly incidents fired, by series",
                labels={"series": series})
        counter.inc()

    def firing(self) -> list[str]:
        with self._lock:
            return sorted(self._firing)

    def observe(self, series: str, value: float,
                t: float | None = None) -> bool:
        """Judge one sample; returns True when it OPENED an incident
        (refreshes and normal samples return False)."""
        if self.watch is not None and series not in self.watch:
            return False
        try:
            value = float(value)
        except (TypeError, ValueError):
            return False
        if not math.isfinite(value):
            return False
        with self._lock:
            win = self._windows.get(series)
            if win is None:
                win = self._windows[series] = deque(maxlen=self.window)
            if len(win) < self.warmup:
                win.append(value)
                return False
            med = statistics.median(win)
            mad = statistics.median(abs(x - med) for x in win)
            scale = max(mad, self.rel_floor * abs(med), self.abs_floor)
            threshold = self.mad_factor * scale
            breach = abs(value - med) > threshold
            if not breach:
                win.append(value)
                streak = self._clear_streak.get(series, 0) + 1
                self._clear_streak[series] = streak
                resolved = (series in self._firing
                            and streak >= self.clear_ticks)
                if resolved:
                    self._firing.discard(series)
            else:
                self._clear_streak[series] = 0
                opened = series not in self._firing
                if opened:
                    self._firing.add(series)
        if breach:
            if opened:
                self._fire(series, value, med, threshold)
            return opened
        if resolved:
            self._resolve(series)
        return False

    def _fire(self, series: str, value: float, median: float,
              threshold: float) -> None:
        name = f"anomaly:{series}"
        if self.store is not None:
            self.store.fire(name, reason="series anomaly",
                            value=round(value, 6),
                            threshold=round(median + threshold, 6),
                            kind="anomaly", series=series,
                            median=round(median, 6))
        events.emit("anomaly", series=series, state="firing",
                    value=round(value, 6), median=round(median, 6),
                    threshold=round(threshold, 6))
        events.dump_flight(reason=f"anomaly:{series}")
        self._count(series)
        logger.warning("ANOMALY %s: value=%.6g median=%.6g "
                       "(threshold ±%.6g)", series, value, median,
                       threshold)

    def _resolve(self, series: str) -> None:
        if self.store is not None:
            self.store.resolve(f"anomaly:{series}")
        events.emit("anomaly", series=series, state="resolved")
        logger.info("anomaly resolved: %s", series)


class Forecaster:
    """Holt-Winters exponential smoothing over an irregular tick stream.

    Double smoothing (level + per-second trend) by default; passing
    ``season_s`` adds an additive seasonal term over ``season_buckets``
    phase buckets (triple smoothing — the diurnal shape). Updates are
    dt-normalized so federation-tick jitter doesn't masquerade as
    trend. Pure stdlib, O(1) per observation.

    ``forecast(horizon_s)`` is HARD-BOUNDED to ``[bound_min,
    bound_max]`` — the controller consuming it additionally keeps its
    own cooldowns and ``max_workers`` gates, so a wild forecast can
    propose, never command.
    """

    def __init__(self, alpha: float = 0.5, beta: float = 0.1,
                 gamma: float = 0.3, season_s: float | None = None,
                 season_buckets: int = 24, min_samples: int = 8,
                 bound_min: float = 0.0,
                 bound_max: float | None = None):
        for name, v in (("alpha", alpha), ("beta", beta),
                        ("gamma", gamma)):
            if not 0.0 < v <= 1.0:
                raise ValueError(f"{name} must be in (0, 1], got {v}")
        if season_s is not None and season_s <= 0:
            raise ValueError("season_s must be > 0")
        self.alpha = float(alpha)
        self.beta = float(beta)
        self.gamma = float(gamma)
        self.season_s = float(season_s) if season_s is not None else None
        self.season_buckets = int(season_buckets)
        self.min_samples = int(min_samples)
        self.bound_min = float(bound_min)
        self.bound_max = (float(bound_max) if bound_max is not None
                          else None)
        self.n = 0
        self._level = 0.0
        self._trend = 0.0  # value units per second
        self._last_t: float | None = None
        self._season = ([0.0] * self.season_buckets
                        if self.season_s is not None else None)

    def _bucket(self, t: float) -> int:
        phase = (t % self.season_s) / self.season_s
        return min(self.season_buckets - 1,
                   int(phase * self.season_buckets))

    def observe(self, t: float, value: float) -> None:
        try:
            value = float(value)
        except (TypeError, ValueError):
            return
        if not math.isfinite(value):
            return
        t = float(t)
        if self._last_t is None:
            self._level = value
            self._trend = 0.0
            self._last_t = t
            self.n = 1
            return
        dt = t - self._last_t
        if dt <= 0:
            return  # out-of-order tick: ignore, never rewind
        s = self._season[self._bucket(t)] if self._season is not None \
            else 0.0
        predicted = self._level + self._trend * dt
        level = (self.alpha * (value - s)
                 + (1.0 - self.alpha) * predicted)
        self._trend = (self.beta * ((level - self._level) / dt)
                       + (1.0 - self.beta) * self._trend)
        self._level = level
        if self._season is not None:
            i = self._bucket(t)
            self._season[i] = (self.gamma * (value - level)
                               + (1.0 - self.gamma) * s)
        self._last_t = t
        self.n += 1

    def forecast(self, horizon_s: float) -> float | None:
        """Projected value ``horizon_s`` past the last observation;
        None until ``min_samples`` observations have landed (an unfed
        forecaster must read as 'no opinion', not as zero)."""
        if self.n < self.min_samples or self._last_t is None:
            return None
        value = self._level + self._trend * float(horizon_s)
        if self._season is not None:
            value += self._season[self._bucket(
                self._last_t + float(horizon_s))]
        if value < self.bound_min:
            value = self.bound_min
        if self.bound_max is not None and value > self.bound_max:
            value = self.bound_max
        return value
