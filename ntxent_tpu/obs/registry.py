"""Process-wide metrics registry: counters, gauges, exact-window histograms.

One vocabulary for the whole framework (ISSUE 3): training steps,
resilience events (retries, divergence skips, supervisor restarts), and
the serving stack all publish here instead of terminating in bare log
lines. Everything is stdlib — the registry must be importable (and
scrapeable) in processes that never touch JAX, e.g. bench.py's parent.

Design notes:

* **Get-or-create identity.** ``registry.counter("x", labels={...})``
  returns the same object for the same (name, labels) pair, so
  instrumentation sites never need to coordinate creation order.
* **Per-metric locks.** Each metric guards its own few fields; the
  registry lock covers only the name->metric dict. A scrape therefore
  never holds one global lock while rebuilding the whole export (the
  double-locking ServingMetrics.to_dict used to pay per scrape).
* **Exact-window histograms.** ``Histogram`` generalizes the serving
  stack's LatencyWindow: cumulative count/sum never reset (rates stay
  computable from deltas) while percentiles are EXACT over a bounded
  sliding window — at smoke-run sample counts, bucket-midpoint error
  would swamp the p50/p95 gap the numbers exist to show. The quantile
  rule is the single source for p50/p95/p99 everywhere (``quantile``).
* **Prometheus text + JSON.** ``render_prometheus`` emits the exposition
  format (histograms as summaries with exact quantiles);
  ``collect`` returns the same values as a JSON-able dict — the two
  exports are views of one store, never parallel bookkeeping.
"""

from __future__ import annotations

import math
import re
import threading
from collections import deque

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry",
           "quantile", "default_registry", "prometheus_name"]

_NAME_OK = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_OK = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_NAME_FIX = re.compile(r"[^a-zA-Z0-9_:]")

DEFAULT_QUANTILES = (0.5, 0.95, 0.99)


def prometheus_name(name: str) -> str:
    """A legal exposition-format metric name (invalid chars -> '_')."""
    fixed = _NAME_FIX.sub("_", name)
    if not fixed or not _NAME_OK.match(fixed):
        fixed = "_" + fixed
    return fixed


def _escape_label_value(value: str) -> str:
    return (value.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _label_suffix(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape_label_value(v)}"'
                     for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def quantile(ordered: list[float], q: float) -> float:
    """Exact nearest-rank quantile over a SORTED sample.

    The one percentile rule for the whole framework (serving latency
    p50/p95/p99 and training-step timings alike): nearest-rank on the
    sorted window, index ``min(n-1, floor(q*n))``. For the window sizes
    used here it tracks ``statistics.quantiles(..., method='inclusive')``
    to within one sample — tests/test_obs.py pins the agreement.
    """
    n = len(ordered)
    if n == 0:
        raise ValueError("quantile of an empty sample")
    return ordered[min(n - 1, int(q * n))]


class _Metric:
    """Shared identity/rendering plumbing for the three metric kinds."""

    kind = "untyped"

    def __init__(self, name: str, help: str = "",
                 labels: dict[str, str] | None = None):
        self.name = prometheus_name(name)
        self.help = help
        self.labels = {str(k): str(v) for k, v in (labels or {}).items()}
        for k in self.labels:
            if not _LABEL_OK.match(k):
                raise ValueError(f"illegal Prometheus label name {k!r}")
        self._lock = threading.Lock()

    def label_suffix(self) -> str:
        return _label_suffix(self.labels)


class Counter(_Metric):
    """Monotone float counter (``inc`` only; negative increments refused)."""

    kind = "counter"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc {amount})")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge(_Metric):
    """Set/add instantaneous value."""

    kind = "gauge"

    def __init__(self, name, help="", labels=None):
        super().__init__(name, help, labels)
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram(_Metric):
    """Cumulative count/sum + bounded window for exact percentiles.

    The MetricsRegistry generalization of serving's LatencyWindow (which
    is now an alias over this class): ``observe`` appends to a
    ``maxlen``-bounded deque so memory stays fixed on long-lived
    processes, while count/sum accumulate forever.
    """

    kind = "summary"

    def __init__(self, name, help="", labels=None, window: int = 2048,
                 quantiles: tuple[float, ...] = DEFAULT_QUANTILES):
        super().__init__(name, help, labels)
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.quantiles = tuple(quantiles)
        self.count = 0
        self.total = 0.0
        self._window: deque[float] = deque(maxlen=window)

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            self._window.append(value)

    # LatencyWindow compatibility spelling.
    def record(self, value: float) -> None:
        self.observe(value)

    def percentiles(self) -> dict[float, float]:
        """{q: exact value} over the current window ({} when empty)."""
        with self._lock:
            ordered = sorted(self._window)
        if not ordered:
            return {}
        return {q: quantile(ordered, q) for q in self.quantiles}

    def snapshot(self) -> dict:
        """JSON view, shaped like LatencyWindow.snapshot always was
        (count / mean_ms-style keys are the caller's naming; here the
        keys are unit-neutral with *_ms spelled by ``snapshot_ms``)."""
        with self._lock:
            ordered = sorted(self._window)
            count, total = self.count, self.total
        if not ordered:
            return {"count": count}
        out = {"count": count,
               "mean": round(total / count, 4)}
        for q in self.quantiles:
            out[f"p{int(q * 100)}"] = round(quantile(ordered, q), 4)
        out["max"] = round(ordered[-1], 4)
        out["window"] = len(ordered)
        return out

    def snapshot_ms(self) -> dict:
        """The serving wire shape: millisecond-suffixed keys."""
        snap = self.snapshot()
        return {(k if k in ("count", "window") else f"{k}_ms"): v
                for k, v in snap.items()}


class MetricsRegistry:
    """Name -> metric store with get-or-create semantics.

    ``counter``/``gauge``/``histogram`` return the existing metric when
    the (name, labels) pair is already registered — re-registering with
    a DIFFERENT kind is a programming error and raises. ``collect`` and
    ``render_prometheus`` are consistent views of the same objects.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[tuple, _Metric] = {}

    def _get_or_create(self, cls, name, help, labels, **kwargs):
        key = (prometheus_name(name),
               tuple(sorted((labels or {}).items())))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{existing.kind}, not {cls.kind}")
                return existing
            metric = cls(name, help=help, labels=labels, **kwargs)
            self._metrics[key] = metric
            return metric

    def counter(self, name: str, help: str = "",
                labels: dict | None = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: dict | None = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: dict | None = None, window: int = 2048,
                  quantiles: tuple[float, ...] = DEFAULT_QUANTILES,
                  ) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   window=window, quantiles=quantiles)

    def _sorted_metrics(self) -> list[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def collect(self) -> dict:
        """JSON-able snapshot: name -> value (labeled series nest under
        a ``{label=value,...}`` key; histograms export their summary)."""
        out: dict = {}
        for m in self._sorted_metrics():
            value = (m.snapshot() if isinstance(m, Histogram)
                     else m.value)
            if m.labels:
                series = out.setdefault(m.name, {})
                if not isinstance(series, dict) or "count" in series:
                    # A bare metric already claimed the name; nest it.
                    series = out[m.name] = {"": series}
                series[m.label_suffix()] = value
            else:
                out[m.name] = value
        return out

    def dump_state(self) -> dict:
        """Raw-state view for cross-process federation (ISSUE 10).

        ``collect``/``render_prometheus`` are presentation views; a
        FEDERATOR (obs/aggregate.py) needs the underlying state —
        histogram windows included — because the fleet-level percentile
        must come from the one exact-window quantile rule applied to
        the POOLED samples, not from averaging per-worker percentiles
        (the p99 of a fleet is not the mean of its workers' p99s).
        Shape::

            {"metrics": [{"name", "kind", "labels", ...state...}]}

        where counters/gauges carry ``value`` and histograms carry
        ``count``/``sum``/``window`` (the bounded recent-sample list)
        + ``quantiles``. Served over HTTP as
        ``/metrics?format=state``.
        """
        out: list[dict] = []
        for m in self._sorted_metrics():
            entry = {"name": m.name, "kind": m.kind,
                     "labels": dict(m.labels)}
            if isinstance(m, Histogram):
                with m._lock:
                    entry["count"] = m.count
                    entry["sum"] = m.total
                    entry["window"] = list(m._window)
                entry["quantiles"] = list(m.quantiles)
            else:
                entry["value"] = m.value
            out.append(entry)
        return {"metrics": out}

    def render_prometheus(self) -> str:
        """Exposition-format text (version 0.0.4). Histograms render as
        summaries with their exact-window quantiles plus _sum/_count."""
        lines: list[str] = []
        seen_headers: set[str] = set()
        for m in self._sorted_metrics():
            if m.name not in seen_headers:
                seen_headers.add(m.name)
                if m.help:
                    esc = m.help.replace("\\", r"\\").replace("\n", r"\n")
                    lines.append(f"# HELP {m.name} {esc}")
                lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                pcts = m.percentiles()
                base = dict(m.labels)
                for q, v in pcts.items():
                    suffix = _label_suffix({**base, "quantile": str(q)})
                    lines.append(f"{m.name}{suffix} {_fmt(v)}")
                suffix = m.label_suffix()
                with m._lock:
                    count, total = m.count, m.total
                lines.append(f"{m.name}_sum{suffix} {_fmt(total)}")
                lines.append(f"{m.name}_count{suffix} {count}")
            else:
                lines.append(f"{m.name}{m.label_suffix()} "
                             f"{_fmt(m.value)}")
        return "\n".join(lines) + ("\n" if lines else "")


def _fmt(value: float) -> str:
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    if float(value).is_integer() and abs(value) < 2 ** 53:
        return str(int(value))
    return repr(float(value))


_DEFAULT = MetricsRegistry()


def default_registry() -> MetricsRegistry:
    """The process-wide registry every instrumentation site publishes to
    (training, resilience, and serving share one export path)."""
    return _DEFAULT
