"""Metric federation: N per-process registries -> one fleet view.

The fleet (serving/fleet.py + serving/router.py) runs N worker
processes and a router, each publishing its own ``MetricsRegistry``
over its own ``/metrics``. No single scrape can answer "is the fleet
healthy" — the router's counters say nothing about worker queue
depths, and a worker's padding waste says nothing about its siblings.
This module is the missing layer (ISSUE 10): a ``FleetAggregator``
scrapes every target's raw-state view (``/metrics?format=state`` —
``MetricsRegistry.dump_state``) each tick and merges them into ONE
registry published on the router's ``/metrics/fleet``.

Merge rules (one rule per metric kind, the issue's contract):

* **counters sum** — ``serving_requests_total`` across workers is the
  fleet's request count; same (name, labels) series accumulate;
* **gauges label** — a queue depth is per-process state; summing two
  queue depths answers nothing, so each instance's gauge re-exports
  with an ``instance`` label (``serving_queue_depth{instance="w0"}``);
* **histograms pool** — count/sum add, and the bounded sample windows
  CONCATENATE so fleet percentiles come from the one exact-window
  quantile rule (obs/registry.quantile) applied to the pooled samples.
  The p99 of a fleet is not the mean of its workers' p99s; pooling the
  raw windows is what keeps the serving stack's "percentiles are
  exact" property true one level up.

Failure model: a worker dying mid-scrape (the killworker chaos case)
must yield a PARTIAL-but-valid federated view, never a 500 — the
failed target's last-good state is kept, marked stale via
``fleet_fed_instance_up{instance=...} 0``, and dropped entirely only
after ``stale_after`` consecutive failures (a restarted worker's
counters restart from zero; carrying a dead incarnation's totals
forever would double-count its replacement).

Everything here is stdlib + urllib (the obs-package rule): the
aggregator runs in the router process, which never imports JAX.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request

from .registry import Histogram, MetricsRegistry

logger = logging.getLogger(__name__)

__all__ = ["merge_states", "FleetAggregator"]

# Meta-series the merged view carries about the federation itself.
_UP_GAUGE = "fleet_fed_instance_up"
_SCRAPES = "fleet_fed_scrapes_total"
_FAILURES = "fleet_fed_scrape_failures_total"
_INSTANCES = "fleet_fed_instances"


def merge_states(states: dict[str, dict],
                 stale: set[str] | None = None) -> MetricsRegistry:
    """Merge per-instance ``dump_state`` dicts into a fresh registry.

    ``states``: instance name -> the dict its ``/metrics?format=state``
    returned. ``stale``: instances whose state is a retained last-good
    copy (scrape failed this tick) — included in the merge (partial
    beats absent) but marked down in ``fleet_fed_instance_up``.

    Malformed entries (a worker answering mid-restart with garbage)
    are skipped per-metric, never fatal: a federated scrape must stay
    valid when one worker is not.
    """
    merged = MetricsRegistry()
    stale = stale or set()
    for instance in sorted(states):
        merged.gauge(_UP_GAUGE,
                     "1 = instance scraped this tick, 0 = stale "
                     "(last-good state retained)",
                     labels={"instance": instance}).set(
            0 if instance in stale else 1)
        metrics = (states[instance] or {}).get("metrics")
        if not isinstance(metrics, list):
            continue
        for entry in metrics:
            try:
                _merge_entry(merged, instance, entry)
            except (KeyError, TypeError, ValueError) as e:
                logger.debug("federation: skipping malformed metric "
                             "from %s: %r (%s)", instance, entry, e)
    merged.gauge(_INSTANCES,
                 "instances contributing to this federated view").set(
        len(states))
    return merged


def _merge_entry(merged: MetricsRegistry, instance: str,
                 entry: dict) -> None:
    name = str(entry["name"])
    kind = entry.get("kind")
    labels = {str(k): str(v)
              for k, v in (entry.get("labels") or {}).items()}
    if kind == "counter":
        merged.counter(name, labels=labels).inc(float(entry["value"]))
    elif kind == "gauge":
        # Per-process state: re-label, never sum. The instance label is
        # appended (it must not collide with a real label the metric
        # already carries — 'instance' is reserved for federation).
        merged.gauge(name, labels={**labels, "instance": instance}).set(
            float(entry["value"]))
    elif kind == "summary":
        window = [float(v) for v in (entry.get("window") or [])]
        h = merged.histogram(name, labels=labels,
                             window=max(1, _POOL_WINDOW))
        _pool_histogram(h, int(entry.get("count", len(window))),
                        float(entry.get("sum", 0.0)), window)
    # Unknown kinds are dropped (forward compatibility: an older router
    # federating a newer worker must not crash on a new metric kind).


# Pooled-window bound: large enough that every contributor's full
# default window (2048) survives for a handful of workers; bounded so
# a huge fleet cannot make one scrape quadratic.
_POOL_WINDOW = 8192


def _pool_histogram(h: Histogram, count: int, total: float,
                    window: list[float]) -> None:
    """Accumulate one contributor into a merged histogram: cumulative
    count/sum add; the recent-sample windows concatenate (deque bound
    applies — the pooled window stays bounded by _POOL_WINDOW)."""
    with h._lock:
        h.count += max(0, count)
        h.total += total
        h._window.extend(window)


class FleetAggregator:
    """Scrape every target each tick; publish one merged registry.

    ``targets_fn() -> dict[instance, base_url]`` resolves the live
    scrape set per tick (the router passes a closure over its
    ``WorkerPool``, so membership tracks restarts without re-wiring).
    ``local()`` states (e.g. the router's own registry) merge in
    without an HTTP hop.

    The merged view is rebuilt from scratch each tick — counters in the
    SOURCE registries are cumulative, so rebuilding (not accumulating)
    is what makes the federated counter equal the sum of the current
    per-worker values instead of a sum over history.
    """

    def __init__(self, targets_fn, local: dict | None = None,
                 interval_s: float = 2.0, timeout_s: float = 2.0,
                 stale_after: int = 5):
        self.targets_fn = targets_fn
        # instance -> MetricsRegistry scraped in-process (no HTTP).
        self.local = dict(local or {})
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.stale_after = int(stale_after)
        self.scrapes = 0
        self.failures = 0
        # _lock guards the published view; _scrape_lock serializes
        # whole ticks — merged()'s cold path runs on HTTP request
        # threads concurrently with the background tick, and a tick
        # mutates the last-good/streak tables and re-enters on_merge
        # hooks (the SLOEngine's burn-rate rings are single-evaluator
        # state: two interleaved evaluations would append out-of-order
        # timestamps and double-count breach streaks).
        self._lock = threading.Lock()
        self._scrape_lock = threading.Lock()
        self._last_good: dict[str, dict] = {}
        self._fail_streak: dict[str, int] = {}
        self._merged: MetricsRegistry = MetricsRegistry()
        self._merged_at: float | None = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # SLO engines (obs/slo.py) subscribe here: called with the
        # freshly merged registry after every tick, on the aggregator
        # thread (evaluations must never ride a request handler).
        self.on_merge = []

    # -- scraping ----------------------------------------------------------
    def _scrape(self, url: str) -> dict | None:
        req = urllib.request.Request(
            url.rstrip("/") + "/metrics?format=state")
        try:
            with urllib.request.urlopen(
                    req, timeout=self.timeout_s) as resp:
                state = json.loads(resp.read())
            return state if isinstance(state, dict) else None
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def scrape_once(self) -> MetricsRegistry:
        """One federation tick: scrape, merge, publish; returns the
        merged registry (tests and /metrics/fleet's cold path drive
        this directly). Ticks are serialized — concurrent callers
        queue on the scrape lock and the late ones return the view the
        first one just published instead of re-scraping."""
        entered_at = time.monotonic()
        with self._scrape_lock:
            with self._lock:
                at, merged = self._merged_at, self._merged
            if at is not None and at >= entered_at:
                # A tick completed while we waited for the lock: its
                # view is fresher than our intent — serve it.
                return merged
            return self._scrape_once_locked()

    def _scrape_once_locked(self) -> MetricsRegistry:
        targets = dict(self.targets_fn() or {})
        states: dict[str, dict] = {}
        stale: set[str] = set()
        for instance, url in sorted(targets.items()):
            self.scrapes += 1
            state = self._scrape(url)
            if state is not None:
                states[instance] = state
                self._last_good[instance] = state
                self._fail_streak[instance] = 0
                continue
            self.failures += 1
            streak = self._fail_streak.get(instance, 0) + 1
            self._fail_streak[instance] = streak
            last = self._last_good.get(instance)
            if last is not None and streak < self.stale_after:
                # Partial-but-valid: the dead worker's last-good state
                # stays in the view, visibly stale — a mid-scrape
                # SIGKILL must not blank the fleet's history of it.
                states[instance] = last
                stale.add(instance)
            else:
                self._last_good.pop(instance, None)
        # Instances that left the target set entirely (removed from the
        # pool) drop out of _last_good so a scaled-down fleet's view
        # shrinks with it.
        for gone in set(self._last_good) - set(targets):
            self._last_good.pop(gone, None)
            self._fail_streak.pop(gone, None)
        for instance, registry in sorted(self.local.items()):
            states[instance] = registry.dump_state()
        merged = merge_states(states, stale=stale)
        merged.counter(_SCRAPES, "federation scrape attempts").inc(
            self.scrapes)
        merged.counter(_FAILURES,
                       "federation scrapes that failed").inc(
            self.failures)
        with self._lock:
            self._merged = merged
            self._merged_at = time.monotonic()
        for hook in list(self.on_merge):
            try:
                hook(merged)
            except Exception:  # noqa: BLE001 — a bad SLO evaluation
                # must not kill federation.
                logger.exception("federation: on_merge hook failed")
        return merged

    # -- readers -----------------------------------------------------------
    def merged(self, max_age_s: float | None = None) -> MetricsRegistry:
        """Latest merged registry; scrapes on demand when nothing has
        been published yet or the view is older than ``max_age_s``
        (the /metrics/fleet cold path — a scraper must get data, not
        an empty registry, before the first background tick)."""
        with self._lock:
            at, merged = self._merged_at, self._merged
        if at is None or (max_age_s is not None
                          and time.monotonic() - at > max_age_s):
            return self.scrape_once()
        return merged

    def snapshot(self) -> dict:
        with self._lock:
            age = (time.monotonic() - self._merged_at
                   if self._merged_at is not None else None)
        return {"scrapes": self.scrapes, "failures": self.failures,
                "age_s": round(age, 3) if age is not None else None,
                "stale": sorted(i for i, s in self._fail_streak.items()
                                if s > 0 and i in self._last_good)}

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetAggregator":
        if self._thread is not None:
            raise RuntimeError("aggregator already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-fed-scraper")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception:  # noqa: BLE001 — federation must survive
                # any single bad tick.
                logger.exception("federation: scrape tick failed")

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.interval_s * 2 + 5.0)
            self._thread = None
