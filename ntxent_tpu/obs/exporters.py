"""Metric exporters: Prometheus text over stdlib HTTP, shared format
negotiation.

Two consumers, one store (obs/registry.py):

* ``MetricsServer`` — the training-side scrape endpoint
  (``ntxent-train --metrics-port``): a daemon ThreadingHTTPServer whose
  ``/metrics`` answers Prometheus text by default (that is what a
  scraper expects) with ``?format=json`` / ``Accept: application/json``
  for the collect() dict, plus ``/healthz``. Stdlib only — the training
  process gains no dependency and the server thread never touches JAX.
* ``choose_format`` / ``PROMETHEUS_CONTENT_TYPE`` — the negotiation rule
  shared with the serving stack's ``/metrics`` (serving keeps JSON as
  its default for backward compatibility; training defaults to
  Prometheus): an explicit ``format=`` query wins, then the Accept
  header, then the endpoint's default.
"""

from __future__ import annotations

import json
import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from .registry import MetricsRegistry, default_registry

logger = logging.getLogger(__name__)

__all__ = ["MetricsServer", "choose_format", "PROMETHEUS_CONTENT_TYPE"]

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def choose_format(path: str, accept: str | None,
                  default: str = "json") -> str:
    """'json', 'prometheus', or 'state' for a /metrics request.

    Priority: explicit ``?format=prometheus|json|state`` query, then
    the Accept header (``application/json`` vs ``text/plain`` /
    ``openmetrics``), then ``default``. Unknown values fall back to the
    default rather than erroring — a scrape endpoint should never 400
    over a header. ``state`` (the raw ``dump_state`` federation view,
    obs/aggregate.py's scrape format) is reachable ONLY by explicit
    query: no Accept header should ever switch a dashboard onto the
    internal shape.
    """
    query = parse_qs(urlparse(path).query)
    explicit = (query.get("format") or [None])[0]
    if explicit in ("prometheus", "json", "state"):
        return explicit
    accept = (accept or "").lower()
    if "application/json" in accept:
        return "json"
    if "openmetrics" in accept or "text/plain" in accept:
        return "prometheus"
    return default


class MetricsServer:
    """Tiny scrape endpoint over a MetricsRegistry.

    ``port=0`` binds an ephemeral port (resolved on ``start()`` and
    logged — scripts/obs_smoke.sh greps the log line for it).
    """

    def __init__(self, registry: MetricsRegistry | None = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.registry = registry or default_registry()
        self.host, self.port = host, int(port)
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    def start(self) -> "MetricsServer":
        if self._httpd is not None:
            raise RuntimeError("metrics server already started")
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), _make_handler(self.registry))
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ntxent-metrics-http")
        self._thread.start()
        logger.info("metrics endpoint: http://%s:%d/metrics "
                    "(prometheus; ?format=json for JSON)",
                    self.host, self.port)
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._thread = None

    def __enter__(self) -> "MetricsServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


def _make_handler(registry: MetricsRegistry):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _reply(self, code: int, content_type: str,
                   body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            route = urlparse(self.path).path
            if route == "/metrics":
                fmt = choose_format(self.path,
                                    self.headers.get("Accept"),
                                    default="prometheus")
                if fmt == "json":
                    self._reply(200, "application/json",
                                json.dumps(registry.collect()).encode())
                elif fmt == "state":
                    self._reply(200, "application/json",
                                json.dumps(
                                    registry.dump_state()).encode())
                else:
                    self._reply(200, PROMETHEUS_CONTENT_TYPE,
                                registry.render_prometheus().encode())
            elif route == "/healthz":
                self._reply(200, "application/json",
                            b'{"status": "ok"}')
            else:
                self._reply(404, "application/json",
                            json.dumps(
                                {"error": f"no route {route!r}"}).encode())

    return Handler
