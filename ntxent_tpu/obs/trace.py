"""Span tracing: the causal layer over the typed event stream (ISSUE 7).

PR 3 gave every run metrics (counters/histograms) and flat typed events;
what neither answers is *where a particular step's or request's wall
clock went, in order, with parentage*. This module adds exactly that
without a new sink: spans are just one more typed event (``span``) on
the existing ``EventLog`` hub, so they ride the same JSONL file, the
same run/attempt identity, and the same zero-cost no-op path when no
log is installed.

Two producers, one consumer:

* **producers** — ``span(name, ...)`` is a context manager carrying ids
  and parents on a thread-local stack (nested spans link automatically
  within a thread); ``emit_span(name, dur_ms, ...)`` is the measured
  form for intervals whose start was recorded with a plain monotonic
  read (e.g. a request's queue wait, emitted by the batcher worker at
  dispatch). Serving threads a ``request_id`` (minted at HTTP ingest,
  echoed as ``X-Request-Id``) through queue -> batch-coalesce ->
  device-chunk -> respond; training needs NO span producer at all —
  the ``step`` events the StepTimeline already emits carry the
  data-wait/device/checkpoint split, and the exporter below synthesizes
  step spans from them.
* **consumer** — ``export_chrome_trace`` converts any run's JSONL into
  Chrome-trace/Perfetto ``trace.json`` (the ``ntxent-trace`` console
  script): spans become complete (``ph="X"``) slices, step events
  become a ``step N`` slice with data_wait/device/checkpoint children,
  and the remaining typed events (checkpoint, divergence, retry,
  restart, compile, trace) become instants on their emitting thread's
  track — so a chaos run's restarts and a serving run's coalescing are
  *visible*, not grepped.

Lane model: spans that carry a ``request_id`` share one track per
request (the request's queue wait drawn under its root span even though
the batcher emitted it from the worker thread); everything else tracks
by the emitting thread's name. Training steps get their own track.

Everything here is stdlib (the obs-package rule): the exporter runs in
processes that never initialize a backend — including bench.py's
parent and CI.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time
import uuid
import zlib

from . import events

__all__ = ["span", "emit_span", "current_span_id", "new_request_id",
           "export_chrome_trace", "export_merged_chrome_trace",
           "validate_chrome_trace", "main"]

_local = threading.local()


def _stack() -> list:
    stack = getattr(_local, "stack", None)
    if stack is None:
        stack = _local.stack = []
    return stack


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def new_request_id() -> str:
    """Request identity minted at serving ingest (the ``X-Request-Id``
    value). Same alphabet as span ids; kept as its own spelling so call
    sites say what they mean."""
    return uuid.uuid4().hex[:16]


def current_span_id() -> str | None:
    """Innermost open span on THIS thread (None outside any span)."""
    stack = _stack()
    return stack[-1][0] if stack else None


class span:
    """Context manager: one timed span, emitted as a ``span`` event on
    exit (so ``dur_ms`` is known and the record's own ``t`` marks the
    END; exporters recover the start as ``t - dur_ms/1e3``).

    Nesting is automatic within a thread (ids/parents ride a
    thread-local stack); pass ``parent_id`` explicitly to link across
    threads. Extra keyword attrs land verbatim on the event (and in the
    exported slice's ``args``). With no EventLog installed the emit is
    the hub's cheap no-op — the stack bookkeeping is a list append/pop.
    """

    def __init__(self, name: str, parent_id: str | None = None,
                 request_id: str | None = None, **attrs):
        self.name = str(name)
        self.span_id = new_span_id()
        self._explicit_parent = parent_id
        self.request_id = request_id
        self.attrs = attrs
        self._t0: float | None = None

    def __enter__(self) -> "span":
        stack = _stack()
        self.parent_id = (self._explicit_parent
                          if self._explicit_parent is not None
                          else (stack[-1][0] if stack else None))
        stack.append((self.span_id, self.name))
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        dur_ms = (time.perf_counter() - self._t0) * 1e3
        stack = _stack()
        # Pop OUR frame even if an inner span leaked (never raise from
        # telemetry teardown).
        if stack and stack[-1][0] == self.span_id:
            stack.pop()
        elif stack:
            for i in range(len(stack) - 1, -1, -1):
                if stack[i][0] == self.span_id:
                    del stack[i:]
                    break
        fields = dict(self.attrs)
        if exc_type is not None:
            fields["error"] = exc_type.__name__
        emit_span(self.name, dur_ms, span_id=self.span_id,
                  parent_id=self.parent_id, request_id=self.request_id,
                  **fields)
        return None


def emit_span(name: str, dur_ms: float, span_id: str | None = None,
              parent_id: str | None = None, request_id: str | None = None,
              **attrs) -> None:
    """Emit one measured span ending NOW (the record's ``t`` is the end
    time; ``dur_ms`` reaches back to the start). The spelling for
    intervals bracketed by plain monotonic reads — a request's queue
    wait, a device chunk timed around a retry loop."""
    fields = {"name": str(name), "span_id": span_id or new_span_id(),
              "dur_ms": round(float(dur_ms), 3),
              "thread": threading.current_thread().name}
    if parent_id is not None:
        fields["parent_id"] = parent_id
    if request_id is not None:
        fields["request_id"] = request_id
    fields.update(attrs)
    events.emit("span", **fields)


# ---------------------------------------------------------------------------
# Chrome-trace / Perfetto export
# ---------------------------------------------------------------------------

# `step` and `span` records get dedicated handling below; every other
# typed event (retry, divergence, restart, checkpoint, compile, trace,
# bench, ... and any future type — the stream is extensible, and an
# exporter that drops what it does not recognize hides exactly the novel
# thing being debugged) renders as an instant on its source track.

# A serving log mints one request_id per request — unbounded over a real
# run, and Perfetto draws one track per tid, so a lane per id makes an
# hour of production traffic unusably tall (plus one thread_name
# metadata record each). Distinct ids get their own lane up to this
# pool size; past it, ids hash onto the existing pool (request_id stays
# in every slice's args, so attribution survives the multiplexing).
REQUEST_LANES_MAX = 64


class _Lanes:
    """name -> stable tid assignment plus the thread_name metadata
    records Perfetto uses to label tracks — one instance per PROCESS
    lane (``pid``).

    Timebase: a single file's records carry ``t`` (monotonic offset
    since that log opened), which is the right axis for one process but
    meaningless ACROSS processes — each log opened at a different
    moment. The merged exporter therefore passes ``ts0_wall`` (the
    earliest wall clock over all files) and slices align on ``wall``
    instead; single-file export keeps the monotonic axis (wall-clock
    jumps must not reorder a one-process timeline).
    """

    def __init__(self, pid: int = 1, ts0_wall: float | None = None,
                 process_name: str | None = None):
        self.pid = pid
        self.ts0_wall = ts0_wall
        self._tids: dict[str, int] = {}
        self.meta: list[dict] = []
        self._req_pool: list[int] = []
        self._req_map: dict[str, int] = {}
        if process_name is not None:
            self.meta.append({
                "ph": "M", "pid": pid, "tid": 0, "name": "process_name",
                "args": {"name": process_name},
            })

    def ts_us(self, rec: dict) -> float:
        if self.ts0_wall is not None and "wall" in rec:
            return (float(rec["wall"]) - self.ts0_wall) * 1e6
        return float(rec["t"]) * 1e6

    def tid(self, label: str) -> int:
        tid = self._tids.get(label)
        if tid is None:
            tid = self._tids[label] = len(self._tids) + 1
            self.meta.append({
                "ph": "M", "pid": self.pid, "tid": tid,
                "name": "thread_name",
                "args": {"name": label},
            })
        return tid

    def request_tid(self, request_id: str) -> int:
        tid = self._req_map.get(request_id)
        if tid is None:
            if len(self._req_pool) < REQUEST_LANES_MAX:
                tid = self.tid(f"req:{request_id}")
                self._req_pool.append(tid)
            else:
                # Stable across exports: crc32, not the salted hash().
                tid = self._req_pool[zlib.crc32(request_id.encode())
                                     % len(self._req_pool)]
            self._req_map[request_id] = tid
        return tid


def _span_events(rec: dict, lanes: _Lanes) -> list[dict]:
    dur_ms = float(rec.get("dur_ms", 0.0))
    end_us = lanes.ts_us(rec)
    tid = (lanes.request_tid(str(rec["request_id"]))
           if rec.get("request_id")
           else lanes.tid(str(rec.get("thread", "main"))))
    args = {k: v for k, v in rec.items()
            if k not in ("event", "t", "wall", "name", "dur_ms", "thread")}
    return [{
        "ph": "X", "pid": lanes.pid, "tid": tid, "cat": "span",
        "name": str(rec.get("name", "span")),
        "ts": round(end_us - dur_ms * 1e3, 3),
        "dur": round(max(dur_ms * 1e3, 0.001), 3),
        "args": args,
    }]


def _step_events(rec: dict, lanes: _Lanes) -> list[dict]:
    """One `step` record -> a step slice with its data-wait/device/
    checkpoint children laid out sequentially (the StepTimeline's
    breakdown is phase durations, not timestamps; sequential layout is
    exactly the host loop's order: fetch, dispatch/run, hook)."""
    tid = lanes.tid("train")
    parts = [("data_wait", float(rec.get("data_wait_ms", 0.0))),
             ("device", float(rec.get("device_ms", 0.0))),
             ("checkpoint", float(rec.get("checkpoint_ms", 0.0)))]
    total_ms = sum(d for _, d in parts)
    end_us = lanes.ts_us(rec)
    start_us = end_us - total_ms * 1e3
    args = {k: rec[k] for k in ("step", "loss", "steps_per_sec", "mfu",
                                "grad_norm", "ok", "attempt",
                                "comms_bytes", "host_fetch_ms",
                                "transfer_ms") if k in rec}
    out = [{
        "ph": "X", "pid": lanes.pid, "tid": tid, "cat": "step",
        "name": f"step {rec.get('step', '?')}",
        "ts": round(start_us, 3), "dur": round(max(total_ms * 1e3, 1), 3),
        "args": args,
    }]
    cursor = start_us
    for name, dur in parts:
        if dur <= 0:
            continue
        out.append({
            "ph": "X", "pid": lanes.pid, "tid": tid, "cat": "step_phase",
            "name": name, "ts": round(cursor, 3),
            "dur": round(dur * 1e3, 3), "args": {},
        })
        cursor += dur * 1e3
    return out


def _instant_event(rec: dict, lanes: _Lanes) -> dict:
    args = {k: v for k, v in rec.items() if k not in ("event", "t", "wall")}
    label = str(rec.get("thread", rec["event"]))
    name = rec["event"]
    if rec.get("action"):
        name = f"{name}:{rec['action']}"
    return {
        "ph": "i", "pid": lanes.pid, "tid": lanes.tid(label), "s": "t",
        "cat": rec["event"], "name": name,
        "ts": round(lanes.ts_us(rec), 3), "args": args,
    }


def _render_records(records: list[dict], lanes: _Lanes,
                    run_id: str | None,
                    run_ids: set[str]) -> list[dict]:
    out: list[dict] = []
    for rec in records:
        if "t" not in rec or "event" not in rec:
            continue
        if run_id is not None and rec.get("run_id") != run_id:
            continue
        if rec.get("run_id"):
            run_ids.add(rec["run_id"])
        kind = rec["event"]
        if kind == "span":
            out.extend(_span_events(rec, lanes))
        elif kind == "step":
            out.extend(_step_events(rec, lanes))
        else:
            out.append(_instant_event(rec, lanes))
    return out


def export_chrome_trace(jsonl_path: str, run_id: str | None = None) -> dict:
    """Convert an EventLog JSONL file into a Chrome-trace dict
    (``{"traceEvents": [...]}``) that Perfetto / chrome://tracing loads
    directly. ``run_id`` filters a file that several processes appended
    to (training + serving sharing one path keep distinct run ids)."""
    records = events.read_events(jsonl_path)
    lanes = _Lanes()
    run_ids: set[str] = set()
    trace_events = _render_records(records, lanes, run_id, run_ids)
    trace_events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": lanes.meta + trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "source": jsonl_path,
            "run_ids": sorted(run_ids),
            "exporter": "ntxent-trace",
        },
    }


def _process_label(path: str, taken: set[str]) -> str:
    """A human lane label from a JSONL filename (``w0.jsonl`` -> ``w0``),
    deduplicated — two files named alike must not merge lanes."""
    import os

    base = os.path.basename(str(path))
    label = base[:-len(".jsonl")] if base.endswith(".jsonl") else base
    label = label or "events"
    candidate, n = label, 1
    while candidate in taken:
        n += 1
        candidate = f"{label}#{n}"
    taken.add(candidate)
    return candidate


def export_merged_chrome_trace(paths: list[str],
                               run_id: str | None = None) -> dict:
    """Stitch SEVERAL processes' JSONL logs into ONE Chrome trace
    (``ntxent-trace --merge``): each file becomes its own process lane
    (pid + ``process_name`` metadata — router, w0, w1, ...), and all
    lanes share one wall-clock timebase so a request's router hop,
    worker queue wait, and device chunk line up as the causal sequence
    they were.

    Per-file ``t`` is a monotonic offset since THAT log opened —
    meaningless across processes — so merged slices align on the
    ``wall`` field every record carries (zeroed at the earliest wall
    time over all files). Cross-process request joins need no flow
    plumbing: the router forwards ``X-Request-Id``, both sides stamp
    it on their spans, and the id rides every slice's ``args`` — in
    Perfetto, selecting a request's router slice and searching the id
    lights up its worker-side tree.
    """
    per_file = [(str(p), events.read_events(str(p))) for p in paths]
    walls = [float(rec["wall"])
             for _, records in per_file for rec in records
             if "wall" in rec and "t" in rec and "event" in rec]
    ts0_wall = min(walls) if walls else None
    trace_events: list[dict] = []
    meta: list[dict] = []
    run_ids: set[str] = set()
    sources: dict[str, str] = {}
    taken: set[str] = set()
    for pid, (path, records) in enumerate(per_file, start=1):
        label = _process_label(path, taken)
        sources[label] = path
        lanes = _Lanes(pid=pid, ts0_wall=ts0_wall, process_name=label)
        trace_events.extend(
            _render_records(records, lanes, run_id, run_ids))
        meta.extend(lanes.meta)
    trace_events.sort(key=lambda e: e.get("ts", 0.0))
    return {
        "traceEvents": meta + trace_events,
        "displayTimeUnit": "ms",
        "otherData": {
            "sources": sources,
            "run_ids": sorted(run_ids),
            "exporter": "ntxent-trace --merge",
        },
    }


def validate_chrome_trace(trace: dict) -> int:
    """Assert ``trace`` is a structurally legal Chrome-trace object
    (the schema Perfetto's JSON importer requires); returns the number
    of non-metadata events. Raises ``ValueError`` on the first
    violation — tests and the smoke scripts share this one rule."""
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError("top level must be an object with 'traceEvents'")
    evs = trace["traceEvents"]
    if not isinstance(evs, list):
        raise ValueError("'traceEvents' must be a list")
    n = 0
    for i, ev in enumerate(evs):
        if not isinstance(ev, dict):
            raise ValueError(f"traceEvents[{i}] is not an object")
        ph = ev.get("ph")
        if not isinstance(ph, str) or not ph:
            raise ValueError(f"traceEvents[{i}] has no phase 'ph'")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"traceEvents[{i}] ({ph}) has no 'name'")
        if ph == "M":
            if not isinstance(ev.get("args"), dict):
                raise ValueError(f"metadata traceEvents[{i}] needs args")
            continue
        n += 1
        if not isinstance(ev.get("ts"), (int, float)):
            raise ValueError(f"traceEvents[{i}] ({ph}) has no numeric 'ts'")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            raise ValueError(f"traceEvents[{i}] ({ph}) needs int pid/tid")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                raise ValueError(
                    f"complete traceEvents[{i}] needs 'dur' >= 0")
        elif ph == "i":
            if ev.get("s") not in ("g", "p", "t"):
                raise ValueError(
                    f"instant traceEvents[{i}] needs scope s in g/p/t")
        else:
            raise ValueError(
                f"traceEvents[{i}]: exporter never emits phase {ph!r}")
    return n


def main(argv=None) -> int:
    """``ntxent-trace``: JSONL event log -> Perfetto-loadable trace.json."""
    p = argparse.ArgumentParser(
        prog="ntxent-trace",
        description="Convert a run's typed JSONL event log (ntxent-train "
                    "--log-jsonl / ntxent-serve --log-jsonl) into a "
                    "Chrome-trace file; open it at https://ui.perfetto.dev "
                    "or chrome://tracing. Several files (or --merge) "
                    "stitch into ONE trace with a process lane per file "
                    "— router + worker logs join on the forwarded "
                    "X-Request-Id.")
    p.add_argument("jsonl", nargs="+",
                   help="path(s) to JSONL event logs; more than one "
                        "implies --merge")
    p.add_argument("--merge", action="store_true",
                   help="force cross-process stitching (process lanes "
                        "+ shared wall-clock timebase) even for one "
                        "file")
    p.add_argument("-o", "--output", default="trace.json",
                   help="output trace file (default: trace.json)")
    p.add_argument("--run-id", default=None,
                   help="keep only records from this run_id (a shared "
                        "log file carries one id per process)")
    args = p.parse_args(argv)
    merge = args.merge or len(args.jsonl) > 1
    try:
        if merge:
            trace = export_merged_chrome_trace(args.jsonl,
                                               run_id=args.run_id)
        else:
            trace = export_chrome_trace(args.jsonl[0],
                                        run_id=args.run_id)
    except OSError as e:
        print(f"ntxent-trace: cannot read {' '.join(args.jsonl)}: {e}",
              file=sys.stderr)
        return 1
    n = validate_chrome_trace(trace)
    if n == 0:
        print(f"ntxent-trace: {' '.join(args.jsonl)} contained no "
              "exportable events"
              + (f" for run_id {args.run_id}" if args.run_id else ""),
              file=sys.stderr)
        return 1
    with open(args.output, "w") as f:
        json.dump(trace, f)
    spans = sum(1 for e in trace["traceEvents"] if e.get("cat") == "span")
    steps = sum(1 for e in trace["traceEvents"] if e.get("cat") == "step")
    lanes = len({e["pid"] for e in trace["traceEvents"]
                 if e.get("ph") != "M"})
    extra = f", {lanes} process lanes" if merge else ""
    print(f"ntxent-trace: wrote {args.output} ({n} events: {spans} spans, "
          f"{steps} steps{extra}; load in https://ui.perfetto.dev)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
