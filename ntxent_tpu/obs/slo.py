"""SLO engine: declarative objectives, burn-rate windows, typed alerts.

Metrics say what IS; nothing in the stack said what is ACCEPTABLE. This
module closes the loop (ISSUE 10): objectives are declared once
(availability, tail latency, embedding drift), evaluated every
federation tick against the merged fleet registry (obs/aggregate.py),
and a breach becomes a typed ``alert`` event on the JSONL stream, a
flight-recorder dump (the postmortem is captured AT the breach, not
reconstructed after), and an entry on the router's ``/alerts``
endpoint.

Objective kinds:

* ``availability`` — ratio of a bad-outcome counter to a total
  counter, judged as a BURN RATE over two windows (the
  multi-window rule SRE practice converged on): with an error budget
  of ``1 - target``, the alert fires only when the windowed error rate
  exceeds ``burn_factor x budget`` in BOTH the fast window (catches
  the onset quickly) and the slow window (confirms it is sustained,
  not a blip). Counter series are cumulative, so windowed rates come
  from a ring of (t, value) snapshots the engine keeps per objective.
* ``quantile`` — a histogram's exact-window percentile against a
  bound (serving p99 latency, shadow drift p99). Fires after
  ``breach_ticks`` consecutive breaching evaluations (one slow scrape
  must not page), resolves after ``clear_ticks`` clean ones.

Alert lifecycle: ``firing`` -> (condition clears) -> ``resolved``;
both transitions emit an ``alert`` event; only the firing transition
trips the flight recorder. The ``AlertStore`` is the bounded
process-local ledger ``/alerts`` serves — active alerts plus a recent
history ring.

Stdlib only (the obs-package rule): the engine runs in the router
process, which never imports JAX.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field

from . import events
from .registry import MetricsRegistry, quantile

logger = logging.getLogger(__name__)

__all__ = ["Objective", "AlertStore", "SLOEngine"]


@dataclass
class Objective:
    """One declarative service-level objective."""

    name: str
    kind: str                      # "availability" | "quantile"
    target: float                  # availability: good fraction (e.g.
    #                                0.99); quantile: the bound itself
    # availability inputs: cumulative counter names in the federated
    # registry. All label-sets of the name are summed; ``bad_exclude``
    # drops label-sets whose label value matches (e.g. saturation
    # rejections are not availability failures — the client was told
    # to retry, not failed).
    total_metric: str | None = None
    bad_metric: str | None = None
    bad_exclude: dict = field(default_factory=dict)
    fast_window_s: float = 60.0
    slow_window_s: float = 300.0
    burn_factor: float = 2.0
    # quantile inputs: histogram name (+ optional label filter) and q.
    metric: str | None = None
    labels: dict = field(default_factory=dict)
    q: float = 0.99
    breach_ticks: int = 2
    clear_ticks: int = 2
    min_samples: int = 1

    def __post_init__(self):
        if self.kind not in ("availability", "quantile"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if self.kind == "availability":
            if not (self.total_metric and self.bad_metric):
                raise ValueError(f"availability objective {self.name!r} "
                                 "needs total_metric and bad_metric")
            if not 0.0 < self.target < 1.0:
                raise ValueError(f"availability target must be in "
                                 f"(0, 1), got {self.target}")
        elif self.metric is None:
            raise ValueError(f"quantile objective {self.name!r} needs "
                             "a metric name")


class AlertStore:
    """Bounded alert ledger: active alerts + a recent-history ring.

    Thread-safe; written by the SLO engine (aggregator thread) and the
    router's canary-verdict path (request threads), read by
    ``/alerts``.
    """

    def __init__(self, history: int = 128,
                 registry: MetricsRegistry | None = None):
        self._lock = threading.Lock()
        self._active: dict[str, dict] = {}
        self._history: deque[dict] = deque(maxlen=history)
        self._registry = registry
        self._counters: dict[str, object] = {}

    def _count(self, name: str) -> None:
        if self._registry is None:
            return
        counter = self._counters.get(name)
        if counter is None:
            counter = self._counters[name] = self._registry.counter(
                "slo_alerts_total", "alerts fired, by objective",
                labels={"slo": name})
        counter.inc()

    def fire(self, name: str, reason: str, value: float | None = None,
             threshold: float | None = None, **extra) -> dict:
        """Raise (or refresh) an active alert; returns the record."""
        record = {"name": name, "state": "firing", "reason": reason,
                  "value": value, "threshold": threshold,
                  "since": round(time.time(), 3), **extra}
        with self._lock:
            previous = self._active.get(name)
            if previous is not None:
                # Refresh keeps the original onset time: an alert that
                # keeps breaching is ONE incident, not many.
                record["since"] = previous["since"]
                record["refreshed"] = round(time.time(), 3)
            self._active[name] = record
            if previous is None:
                self._history.append(dict(record))
                self._count(name)
        return record

    def resolve(self, name: str, reason: str = "recovered",
                **extra) -> dict | None:
        with self._lock:
            active = self._active.pop(name, None)
            if active is None:
                return None
            record = {**active, "state": "resolved", "reason": reason,
                      "resolved_at": round(time.time(), 3), **extra}
            self._history.append(record)
        return record

    def active(self) -> list[dict]:
        with self._lock:
            return [dict(r) for r in self._active.values()]

    def snapshot(self) -> dict:
        with self._lock:
            return {"firing": sorted(self._active),
                    "active": [dict(r) for r in self._active.values()],
                    "history": [dict(r) for r in self._history]}


# -- federated-registry readers ------------------------------------------


def _iter_metrics(registry: MetricsRegistry):
    for entry in registry.dump_state()["metrics"]:
        yield entry


def counter_total(registry: MetricsRegistry, name: str,
                  exclude: dict | None = None) -> float:
    """Sum every label-set of a counter in ``registry``; label-sets
    matching ``exclude`` (key -> value) are dropped."""
    total = 0.0
    exclude = exclude or {}
    for entry in _iter_metrics(registry):
        if entry["name"] != name or entry["kind"] != "counter":
            continue
        labels = entry.get("labels") or {}
        if any(labels.get(k) == v for k, v in exclude.items()):
            continue
        total += float(entry.get("value", 0.0))
    return total


def histogram_quantile(registry: MetricsRegistry, name: str, q: float,
                       labels: dict | None = None,
                       ) -> tuple[float | None, int]:
    """(q-quantile, pooled sample count) of a histogram across every
    label-set matching ``labels`` (subset match), via the one
    exact-window rule. (None, 0) when no samples exist."""
    pooled: list[float] = []
    want = labels or {}
    for entry in _iter_metrics(registry):
        if entry["name"] != name or entry["kind"] != "summary":
            continue
        have = entry.get("labels") or {}
        if any(have.get(k) != v for k, v in want.items()):
            continue
        pooled.extend(float(v) for v in entry.get("window") or [])
    if not pooled:
        return None, 0
    pooled.sort()
    return quantile(pooled, q), len(pooled)


class SLOEngine:
    """Evaluate objectives against successive merged registries.

    Wire ``engine.evaluate`` onto ``FleetAggregator.on_merge``; every
    federation tick then judges every objective. Breach side effects:
    a typed ``alert`` event (events hub), an ``AlertStore.fire``, and
    ONE flight-recorder dump per incident (the dump captures the event
    tail AT the breach; re-dumping per tick would bury it).
    """

    def __init__(self, objectives: list[Objective],
                 store: AlertStore | None = None,
                 registry: MetricsRegistry | None = None,
                 clock=time.monotonic):
        self.objectives = list(objectives)
        names = [o.name for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate objective names: {names}")
        self.store = store if store is not None \
            else AlertStore(registry=registry)
        self.clock = clock
        self.evaluations = 0
        # Per-objective evaluation state.
        self._rings: dict[str, deque] = {
            o.name: deque() for o in self.objectives}
        self._breach_streak: dict[str, int] = {}
        self._clear_streak: dict[str, int] = {}

    # -- evaluation --------------------------------------------------------
    def evaluate(self, registry: MetricsRegistry) -> list[dict]:
        """Judge every objective against one merged registry; returns
        the alert records that fired or resolved this tick."""
        self.evaluations += 1
        now = self.clock()
        transitions: list[dict] = []
        for obj in self.objectives:
            if obj.kind == "availability":
                breach, value, detail = self._eval_availability(
                    obj, registry, now)
            else:
                breach, value, detail = self._eval_quantile(
                    obj, registry)
            transitions.extend(
                self._transition(obj, breach, value, detail))
        return transitions

    def _eval_availability(self, obj: Objective,
                           registry: MetricsRegistry,
                           now: float):
        total = counter_total(registry, obj.total_metric)
        bad = counter_total(registry, obj.bad_metric,
                            exclude=obj.bad_exclude)
        ring = self._rings[obj.name]
        ring.append((now, total, bad))
        while ring and now - ring[0][0] > obj.slow_window_s:
            ring.popleft()
        budget = 1.0 - obj.target

        def burn(window_s: float) -> float | None:
            """Windowed error rate / budget; None without enough
            history or traffic (no traffic is not an outage)."""
            cutoff = now - window_s
            base = None
            for t, tot, b in ring:
                if t <= cutoff:
                    base = (t, tot, b)
                else:
                    break
            if base is None:
                base = ring[0]
                if now - base[0] < window_s * 0.5:
                    return None  # too little history to judge
            d_total = total - base[1]
            d_bad = bad - base[2]
            if d_total <= 0:
                return None
            return (d_bad / d_total) / budget

        fast = burn(obj.fast_window_s)
        slow = burn(obj.slow_window_s)
        breach = (fast is not None and slow is not None
                  and fast >= obj.burn_factor
                  and slow >= obj.burn_factor)
        detail = {"fast_burn": round(fast, 4) if fast is not None
                  else None,
                  "slow_burn": round(slow, 4) if slow is not None
                  else None,
                  "budget": round(budget, 6)}
        value = fast if fast is not None else 0.0
        return breach, value, detail

    def _eval_quantile(self, obj: Objective,
                       registry: MetricsRegistry):
        value, n = histogram_quantile(registry, obj.metric, obj.q,
                                      labels=obj.labels)
        detail = {"q": obj.q, "samples": n}
        if value is None or n < obj.min_samples:
            return False, value, detail
        return value > obj.target, value, detail

    def _transition(self, obj: Objective, breach: bool,
                    value, detail: dict) -> list[dict]:
        out: list[dict] = []
        name = obj.name
        if breach:
            self._clear_streak[name] = 0
            streak = self._breach_streak.get(name, 0) + 1
            self._breach_streak[name] = streak
            already = any(a["name"] == name
                          for a in self.store.active())
            if streak >= obj.breach_ticks and not already:
                record = self.store.fire(
                    name, reason=f"{obj.kind} objective breached",
                    value=round(float(value), 6)
                    if value is not None else None,
                    threshold=obj.target, kind=obj.kind, **detail)
                events.emit("alert", slo=name, state="firing",
                            kind=obj.kind, value=record["value"],
                            threshold=obj.target, **detail)
                events.dump_flight(reason=f"slo_breach:{name}")
                logger.warning("SLO BREACH %s: value=%s threshold=%s "
                               "%s", name, record["value"], obj.target,
                               detail)
                out.append(record)
        else:
            self._breach_streak[name] = 0
            streak = self._clear_streak.get(name, 0) + 1
            self._clear_streak[name] = streak
            if streak >= obj.clear_ticks:
                record = self.store.resolve(name)
                if record is not None:
                    events.emit("alert", slo=name, state="resolved",
                                kind=obj.kind)
                    logger.info("SLO recovered: %s", name)
                    out.append(record)
        return out
