"""Unified telemetry: one registry, one event stream, shared exporters.

The observability layer the north star's "production-scale" claim
requires (ISSUE 3). Until this package, only serving had metrics (an
isolated JSON dict) while training observability ended at log lines —
a stalled or slowly-degrading run could not be diagnosed after the
fact. The pieces:

* ``registry.MetricsRegistry`` — process-wide counters / gauges /
  exact-window histograms (``default_registry()``); training,
  resilience, and serving all publish here;
* ``events.EventLog`` — typed JSONL records (``step``, ``retry``,
  ``divergence``, ``restart``, ``checkpoint``, ``compile``, ``trace``)
  with monotonic timestamps and run/attempt ids; ``install``/``emit``
  is the process-wide hub deep instrumentation sites use;
* ``timeline.StepTimeline`` — the per-step training breakdown
  (data-wait vs device vs checkpoint time, steps/sec, MFU) feeding both
  of the above;
* ``profiler.ProfilerTrigger`` — on-demand ``jax.profiler`` capture
  (slow-step rolling-median trigger, trigger file, SIGUSR2);
* ``exporters.MetricsServer`` — Prometheus text / JSON over stdlib
  HTTP (``ntxent-train --metrics-port``); the serving server's
  ``/metrics`` negotiates the same two formats over the same registry;
* ``trace`` — span tracing over the same event stream (ISSUE 7):
  ``span``/``emit_span`` producers, the ``ntxent-trace`` exporter to
  Perfetto/Chrome ``trace.json`` (``--merge`` stitches router + worker
  logs into one trace with a process lane per file), and the flight
  recorder (``dump_flight``) that writes the event tail on stalls and
  signals;
* ``aggregate.FleetAggregator`` — metric federation (ISSUE 10): scrape
  every worker's + the router's ``/metrics?format=state`` raw view
  each tick and merge into ONE registry (counters summed, gauges
  instance-labeled, histogram windows pooled so fleet percentiles use
  the same exact-window quantile rule) — the router's
  ``/metrics/fleet``;
* ``history.MetricHistory`` — the retained time-series plane
  (ISSUE 18): per-series ring buffers with staged raw → 10 s → 1 m
  downsampling fed by every federation tick (``HistoryRecorder``),
  durable via stage-fsync-rename spill, served as the router's
  ``/metrics/history``; ``AnomalyDetector`` (rolling median + MAD)
  raises typed ``anomaly`` alerts over it and ``Forecaster``
  (Holt-Winters smoothing) gives the autoscaler its predictive
  ``--predict-horizon`` lead-time signal;
* ``slo.SLOEngine`` — declarative objectives (availability burn-rate
  over fast/slow windows, latency/drift quantile bounds) evaluated on
  every federation tick; breaches emit typed ``alert`` events, trip
  the flight recorder, and land in the ``AlertStore`` the router's
  ``/alerts`` serves.

Everything here is stdlib except the profiler (lazy jax import), so
the package is importable — and scrapeable — from processes that never
initialize a backend (bench.py's parent).
"""

from .aggregate import FleetAggregator, merge_states
from .events import (
    EVENT_TYPES,
    EventLog,
    dump_flight,
    emit,
    get_event_log,
    install,
    read_events,
    set_attempt,
)
from .exporters import PROMETHEUS_CONTENT_TYPE, MetricsServer, choose_format
from .history import (
    DEFAULT_SERIES,
    AnomalyDetector,
    Forecaster,
    HistoryRecorder,
    MetricHistory,
    SeriesSpec,
    gauge_reduce,
    ingest_timeline,
)
from .profiler import ProfilerTrigger
from .registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    default_registry,
    prometheus_name,
    quantile,
)
from .slo import AlertStore, Objective, SLOEngine
from .timeline import StepTimeline
from .trace import (
    current_span_id,
    emit_span,
    export_chrome_trace,
    export_merged_chrome_trace,
    new_request_id,
    span,
    validate_chrome_trace,
)

__all__ = [
    "AlertStore",
    "AnomalyDetector",
    "DEFAULT_SERIES",
    "EVENT_TYPES",
    "EventLog",
    "FleetAggregator",
    "Forecaster",
    "HistoryRecorder",
    "MetricHistory",
    "Objective",
    "SLOEngine",
    "SeriesSpec",
    "gauge_reduce",
    "ingest_timeline",
    "merge_states",
    "dump_flight",
    "emit",
    "get_event_log",
    "install",
    "read_events",
    "set_attempt",
    "PROMETHEUS_CONTENT_TYPE",
    "MetricsServer",
    "choose_format",
    "ProfilerTrigger",
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "default_registry",
    "prometheus_name",
    "quantile",
    "StepTimeline",
    "current_span_id",
    "emit_span",
    "export_chrome_trace",
    "export_merged_chrome_trace",
    "new_request_id",
    "span",
    "validate_chrome_trace",
]
