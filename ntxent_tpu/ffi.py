"""NT-Xent as an XLA FFI custom call backed by the native C++ core.

The reference's native surface was a CUDA/C++ op handed to Python through
pybind11 (/root/reference/src/binding_new.cpp:4-21) — the compiler never saw
it. Here the native core (native/src/ntxent_cpu.cpp) is registered into the
XLA runtime itself as typed FFI custom calls (native/src/ntxent_ffi.cpp), so
the C++ implementation composes with ``jit``, ``grad`` and the rest of the
program: XLA schedules it, owns its buffers, and differentiates through it
via the ``jax.custom_vjp`` wired below (forward saves the O(N) logsumexp
residual; backward is the exact dense native gradient — the contract the
reference's backward violated, SURVEY.md §2.3-D8/D9).

CPU-platform handlers; the TPU hot path remains ops/ntxent_pallas.py. Tests
(tests/test_ffi.py) assert the FFI op, the Pallas kernel, and the jnp oracle
agree on loss and gradients.
"""

from __future__ import annotations

import ctypes
import functools

import jax
import jax.numpy as jnp
import numpy as np

from .native import build_native, find_ffi_lib

__all__ = ["register", "ffi_available", "ntxent_loss_ffi"]

_REGISTERED = False

FORWARD_TARGET = "ntxent_forward_ffi"
BACKWARD_TARGET = "ntxent_backward_ffi"


def ffi_available() -> bool:
    """True when the FFI library is (or can be) built and jax.ffi exists."""
    try:
        import jax.ffi  # noqa: F401
    except ImportError:  # pragma: no cover
        return False
    from shutil import which

    return find_ffi_lib() is not None or which("cmake") is not None


def register(build_if_missing: bool = True) -> None:
    """Build (if needed) and register the FFI handlers with XLA. Idempotent."""
    global _REGISTERED
    if _REGISTERED:
        return
    lib_path = find_ffi_lib()
    if lib_path is None:
        if not build_if_missing:
            raise FileNotFoundError(
                "XLA FFI library not built; run ntxent_tpu.native.build_native()")
        build_native(force=True)
        lib_path = find_ffi_lib()
        if lib_path is None:
            raise RuntimeError(
                "native build completed but produced no libntxent_xla_ffi — "
                "jaxlib FFI headers missing at configure time?")
    lib = ctypes.cdll.LoadLibrary(str(lib_path))
    jax.ffi.register_ffi_target(
        FORWARD_TARGET, jax.ffi.pycapsule(lib.NtxentForwardFfi),
        platform="cpu")
    jax.ffi.register_ffi_target(
        BACKWARD_TARGET, jax.ffi.pycapsule(lib.NtxentBackwardFfi),
        platform="cpu")
    _REGISTERED = True


def _forward_call(z: jax.Array, temperature: float):
    two_n = z.shape[0]
    call = jax.ffi.ffi_call(
        FORWARD_TARGET,
        (jax.ShapeDtypeStruct((), jnp.float32),
         jax.ShapeDtypeStruct((two_n,), jnp.float32)),
    )
    return call(z.astype(jnp.float32), temperature=np.float32(temperature))


def _backward_call(z, lse, g, temperature: float):
    call = jax.ffi.ffi_call(
        BACKWARD_TARGET,
        jax.ShapeDtypeStruct(z.shape, jnp.float32),
    )
    return call(z.astype(jnp.float32), lse, jnp.asarray(g, jnp.float32),
                temperature=np.float32(temperature))


@functools.partial(jax.custom_vjp, nondiff_argnums=(1,))
def _ntxent_ffi(z, temperature):
    return _forward_call(z, temperature)[0]


def _ntxent_ffi_fwd(z, temperature):
    loss, lse = _forward_call(z, temperature)
    return loss, (z, lse)


def _ntxent_ffi_bwd(temperature, res, g):
    z, lse = res
    grad = _backward_call(z, lse, g, temperature)
    return (grad.astype(z.dtype),)


_ntxent_ffi.defvjp(_ntxent_ffi_fwd, _ntxent_ffi_bwd)


def ntxent_loss_ffi(z: jax.Array, temperature: float = 0.07) -> jax.Array:
    """Canonical NT-Xent mean loss via the native XLA FFI custom call.

    Same semantics as ``ops.oracle.ntxent_loss`` / ``ntxent_loss_fused``;
    runs the threaded C++ core inside the XLA CPU runtime. Differentiable
    (exact dense gradient). ``temperature`` must be a static Python float.
    """
    if z.ndim != 2 or z.shape[0] % 2 != 0:
        raise ValueError(f"z must be (2N, D) with even 2N, got {z.shape}")
    register()
    return _ntxent_ffi(z, float(temperature))
