"""Reference-compatible Python API surface.

Mirrors the reference's pybind11 bindings
(/root/reference/src/binding_new.cpp:4-21): ``forward(z, temperature,
use_mixed_precision=False)``, ``backward(z, softmax_output, grad_output,
temperature, use_mixed_precision=False)`` and ``check_tensor_core_support()``
— dispatching to the JAX/Pallas path instead of CUDA.

Differences from the reference, all deliberate (SURVEY.md §2.3):

* Semantics are **canonical** NT-Xent by default (z is (2N, D) stacked views,
  positives at offset N, diagonal masked). Pass ``compat="reference"`` to get
  the reference's as-written behavior (z is (B, D), duplicated, diagonal
  treated as positive — D10) for comparison.
* ``forward`` can return the softmax residual the reference's backward
  demanded but its forward discarded (D9) via ``return_softmax=True``.
* ``backward`` computes the **exact dense gradient** and honors
  ``grad_output``; the reference kept only a wrong diagonal term and ignored
  grad_output entirely (D8). It accepts the softmax residual for signature
  parity but can recompute from z alone.
* ``use_mixed_precision`` actually does something: it runs the similarity
  matmul in bfloat16 with fp32 softmax accumulation (the reference accepted
  and ignored the flag — D11).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .ops import oracle
from .ops.ntxent_pallas import ntxent_loss_fused
from .utils.capability import check_tensor_core_support

__all__ = ["forward", "backward", "check_tensor_core_support", "ntxent"]


def _is_torch(x) -> bool:
    """True for torch.Tensor without importing torch unless it's loaded."""
    import sys

    torch = sys.modules.get("torch")
    return torch is not None and isinstance(x, torch.Tensor)


def _from_torch(x) -> jax.Array:
    # Lazy import is safe: this branch only runs on torch-typed input, by
    # which point torch itself is already loaded (see _is_torch).
    # copy=True: callers of the reference-shaped API own their tensors and
    # may mutate them in place after the call; zero-copy dlpack + async JAX
    # dispatch would make that mutation visible to the pending computation.
    from .torch_compat import to_jax

    return to_jax(x, copy=True)


def _to_torch(x: jax.Array):
    from .torch_compat import to_torch

    return to_torch(x)


def _prep(z, use_mixed_precision: bool):
    """Accept jax/numpy/torch input (the reference's callers hold torch
    tensors, binding_new.cpp:5-9); returns (jax array, was_torch flag)."""
    was_torch = _is_torch(z)
    if was_torch:
        z = _from_torch(z)
    else:
        z = jnp.asarray(z)
    if use_mixed_precision:
        z = z.astype(jnp.bfloat16)
    return z, was_torch


def forward(
    z: jax.Array,
    temperature: float = 0.07,
    use_mixed_precision: bool = False,
    *,
    return_softmax: bool = False,
    compat: str = "canonical",
    fused: bool = True,
):
    """NT-Xent forward. Returns the scalar loss (matching binding_new.cpp:5-9),
    or (loss, softmax) with ``return_softmax=True`` (the intended contract).

    Accepts jax, numpy, or torch input; torch in => torch out, so reference
    callers holding ``torch.Tensor`` embeddings work unchanged.
    """
    z, was_torch = _prep(z, use_mixed_precision)
    out = _forward_jax(z, temperature, return_softmax, compat, fused)
    if was_torch:
        if isinstance(out, tuple):
            return tuple(_to_torch(o) for o in out)
        return _to_torch(out)
    return out


def _forward_jax(z, temperature, return_softmax, compat, fused):
    if compat == "reference":
        loss = oracle.ntxent_loss_compat(z, temperature)
        if return_softmax:
            z_cat = jnp.concatenate([z, z], axis=0)
            logits = oracle.similarity_matrix(z_cat, temperature)
            return loss, jax.nn.softmax(logits, axis=-1)
        return loss
    if compat != "canonical":
        raise ValueError(f"unknown compat mode: {compat!r}")
    if return_softmax:
        return oracle.ntxent_loss_and_softmax(z, temperature)
    if fused:
        return ntxent_loss_fused(z, float(temperature))
    return oracle.ntxent_loss(z, temperature)


def backward(
    z: jax.Array,
    softmax_output: jax.Array | None = None,
    grad_output: jax.Array | float = 1.0,
    temperature: float = 0.07,
    use_mixed_precision: bool = False,
):
    """NT-Xent backward: exact gradients (fixing D8).

    Signature parity with binding_new.cpp:11-17. Returns (grad_z,
    grad_logits) like the reference's {grad_z, grad_logits} pair
    (ntxent_kernel.cu:238). ``softmax_output`` is accepted for signature
    parity and ignored — gradients are recomputed exactly from ``z``.
    Accepts jax, numpy, or torch input; torch in => torch out.
    """
    z, was_torch = _prep(z, use_mixed_precision)
    del softmax_output  # recomputed exactly; kept for signature parity
    if _is_torch(grad_output):
        grad_output = _from_torch(grad_output)
    g = jnp.asarray(grad_output, jnp.float32)
    zf = z.astype(jnp.float32)

    logits, _ = oracle._masked_logits(zf, temperature)
    p = jax.nn.softmax(logits, axis=-1)
    two_n = z.shape[0]
    rows = jnp.arange(two_n)
    pos = (rows + two_n // 2) % two_n
    e = jnp.zeros_like(p).at[rows, pos].set(1.0)
    grad_logits = (p - e) / two_n * g
    # d loss/d z = (1/T) (G + G^T) z with G = grad_logits: each z_k receives
    # a row term (its own loss row) and a column term (every other row's
    # softmax over it). G's diagonal is 0 (masked), so the mask constant
    # contributes nothing.
    grad_z = (grad_logits + grad_logits.T) @ zf / temperature
    grad_z = grad_z.astype(z.dtype)
    if was_torch:
        return _to_torch(grad_z), _to_torch(grad_logits)
    return grad_z, grad_logits


class _NtxentModule:
    """Object-style access mirroring the pybind11 module: ``ntxent.forward``."""

    forward = staticmethod(forward)
    backward = staticmethod(backward)
    check_tensor_core_support = staticmethod(check_tensor_core_support)


ntxent = _NtxentModule()
