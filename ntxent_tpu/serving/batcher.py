"""Dynamic micro-batching scheduler: many callers, one device.

The throughput of a TPU/XLA forward is almost flat in batch size until
the MXU saturates, so the worst way to serve concurrent 1-image requests
is one device call each. ``MicroBatcher`` coalesces: requests land in a
bounded queue, a single worker drains it into one concatenated batch
(closed by ``max_batch`` rows or ``max_delay_s`` after the first row,
whichever comes first), the engine runs it, and results split back
per-request. The DLRM serving literature calls this the dominant
inference lever (PAPERS.md arxiv 2512.05831); it is also what gives the
smoke test its "batch-fill ratio > 1" acceptance signal.

Failure semantics reuse the resilience vocabulary (PR 1):

* the **bounded queue is the backpressure valve** — a full queue rejects
  immediately with ``QueueFullError`` carrying a ``retry_after_s`` hint
  derived from the retry policy's own backoff schedule
  (``resilience.RetryPolicy.delay_for``), so clients back off the way
  the framework's own retries do instead of piling latency onto a
  saturated server;
* **per-request deadlines**: an expired request is completed with
  ``DeadlineExceededError`` at dispatch time and NEVER reaches the
  device — batching a result nobody is waiting for wastes the exact
  capacity the queue is protecting;
* **transient device faults** retry PER CHUNK inside
  ``InferenceEngine`` (its ``retry_policy`` — same filters/backoff as
  loader fetches and checkpoint writes; chunk-level placement so a
  retry never re-runs already-completed chunks of an oversized batch
  and never double-counts dispatch metrics); a persistent fault fails
  the whole batch loudly. The batcher's own ``retry_policy`` is used
  only for its backoff schedule — the ``retry_after_s`` hint on
  queue-full rejections;
* each worker loop iteration **beats a StallWatchdog** when one is
  wired (serving.server arms it per attempt) — beats continue while
  idle, so accumulated silence means exactly one thing: a wedged device
  call, which escalates through the PR 1 stall path (stack dumps +
  supervisor restart).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..obs import trace as _trace
from ..resilience.retry import RetryPolicy
from .engine import InferenceEngine

logger = logging.getLogger(__name__)

__all__ = ["BatcherClosed", "DeadlineExceededError", "MicroBatcher",
           "QueueFullError"]


class QueueFullError(RuntimeError):
    """Backpressure: the request queue is at capacity.

    ``retry_after_s`` is the server's suggested client backoff (surfaced
    as the HTTP 429 ``Retry-After`` header by serving.server).
    """

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(f"request queue full ({depth} waiting); "
                         f"retry in {retry_after_s:.2f}s")
        self.retry_after_s = retry_after_s


class DeadlineExceededError(TimeoutError):
    """The request's deadline expired before a device call picked it up."""


class BatcherClosed(RuntimeError):
    """submit() after close() (server draining/restarting)."""


@dataclass
class _Pending:
    """One queued request and its completion rendezvous."""

    x: np.ndarray
    enqueued: float                       # monotonic
    deadline: float | None                # monotonic, None = no deadline
    request_id: str | None = None         # span linkage (obs/trace.py)
    done: threading.Event = field(default_factory=threading.Event)
    result: np.ndarray | None = None
    error: BaseException | None = None

    def finish(self, result=None, error=None) -> None:
        self.result = result
        self.error = error
        self.done.set()


class MicroBatcher:
    """Bounded-queue request coalescer in front of an InferenceEngine.

    ``submit`` blocks the calling thread until its slice of a batch
    returns (the natural shape for one-thread-per-request HTTP servers);
    ``submit_async`` returns the ``_Pending`` for callers managing their
    own waits. One worker thread owns all engine calls.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        max_batch: int | None = None,
        max_delay_s: float = 0.005,
        queue_size: int = 64,
        retry_policy: RetryPolicy | None = None,
        watchdog=None,
        poll_s: float = 0.05,
    ):
        if queue_size < 1:
            raise ValueError(f"queue_size must be >= 1, got {queue_size}")
        if max_delay_s < 0:
            raise ValueError(f"max_delay_s must be >= 0, got {max_delay_s}")
        self.engine = engine
        self.metrics = engine.metrics
        self.max_batch = int(max_batch or engine.max_bucket)
        self.max_delay_s = float(max_delay_s)
        self.queue_size = int(queue_size)
        self.retry_policy = retry_policy
        self.watchdog = watchdog
        self.poll_s = float(poll_s)
        self.metrics.queue_capacity = self.queue_size
        self._queue: deque[_Pending] = deque()
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-micro-batcher")
        self._thread.start()

    # -- client side -----------------------------------------------------
    def submit_async(self, x: np.ndarray,
                     timeout_s: float | None = None,
                     request_id: str | None = None) -> _Pending:
        x = np.asarray(x)
        if x.shape[1:] != self.engine.example_shape or x.shape[0] < 1:
            raise ValueError(
                f"request must be (n,) + {self.engine.example_shape} with "
                f"n >= 1, got {x.shape}")
        now = time.monotonic()
        pending = _Pending(
            x=x, enqueued=now,
            deadline=now + timeout_s if timeout_s is not None else None,
            request_id=request_id)
        with self._lock:
            # Closed check INSIDE the lock: the worker's exit and close()'s
            # drain both observe closed-ness under this same lock, so an
            # append that won the race is guaranteed to be either served
            # or drained — never stranded.
            if self._closed.is_set():
                raise BatcherClosed("batcher is closed")
            if len(self._queue) >= self.queue_size:
                self.metrics.request_rejected("queue_full")
                raise QueueFullError(len(self._queue),
                                     self._retry_after_s())
            self._queue.append(pending)
            self.metrics.set_queue_depth(len(self._queue))
            self._not_empty.notify()
        self.metrics.request_accepted()
        return pending

    def submit(self, x: np.ndarray,
               timeout_s: float | None = None,
               request_id: str | None = None) -> np.ndarray:
        """Embed ``x`` (one request, shape ``(n,) + example_shape``).

        Raises ``QueueFullError`` (backpressure), ``DeadlineExceededError``
        (``timeout_s`` elapsed), or the device call's own error.
        ``request_id`` (when the caller minted one at ingest) links the
        queue-wait span the worker emits at dispatch to the request.
        """
        pending = self.submit_async(x, timeout_s=timeout_s,
                                    request_id=request_id)
        start = pending.enqueued
        # Grace on top of the deadline: the worker expires the request;
        # the extra poll interval only covers rendezvous scheduling.
        wait = None if timeout_s is None else timeout_s + 4 * self.poll_s
        if not pending.done.wait(wait):
            # Worker wedged past the grace (a stuck device call): surface
            # a timeout here; the watchdog owns diagnosing the wedge.
            # Mark dead so the worker expires it at dispatch (which is
            # also where the rejected_deadline counter is bumped, once).
            pending.deadline = time.monotonic()
            self.metrics.request_done((time.monotonic() - start) * 1e3,
                                      ok=False)
            raise DeadlineExceededError(
                f"no result within {timeout_s:.2f}s (+grace)")
        total_ms = (time.monotonic() - start) * 1e3
        if pending.error is not None:
            self.metrics.request_done(total_ms, ok=False)
            raise pending.error
        self.metrics.request_done(total_ms, ok=True)
        return pending.result

    def _retry_after_s(self) -> float:
        if self.retry_policy is not None:
            return self.retry_policy.delay_for(1)
        return max(self.max_delay_s * 4, 0.05)

    # -- worker side -----------------------------------------------------
    def _take_batch(self) -> list[_Pending]:
        """Block for a first request, then coalesce until the batch is
        full or ``max_delay_s`` has passed since the first arrival."""
        with self._not_empty:
            while not self._queue:
                if self._closed.is_set():
                    return []
                self._not_empty.wait(self.poll_s)
                if self.watchdog is not None:
                    self.watchdog.beat()  # idle is progress, not a stall
            batch = [self._queue.popleft()]
        rows = batch[0].x.shape[0]
        flush_at = time.monotonic() + self.max_delay_s
        while rows < self.max_batch:
            with self._not_empty:
                if not self._queue:
                    remaining = flush_at - time.monotonic()
                    if remaining <= 0:
                        break
                    self._not_empty.wait(min(remaining, self.poll_s))
                    if not self._queue:
                        if time.monotonic() >= flush_at:
                            break
                        continue
                nxt = self._queue[0]
                if rows + nxt.x.shape[0] > self.max_batch:
                    break  # leave it for the next batch, keep FIFO order
                batch.append(self._queue.popleft())
            rows += nxt.x.shape[0]
        with self._lock:
            self.metrics.set_queue_depth(len(self._queue))
        return batch

    def _run(self) -> None:
        while True:
            batch = self._take_batch()
            if not batch:
                if self._closed.is_set():
                    self._drain("batcher closed")
                    return
                continue
            try:
                self._serve_batch(batch)
            except Exception:  # noqa: BLE001 — last-resort shield: the
                # worker thread must outlive ANY per-batch failure
                # (_serve_batch already fails the batch's requests; this
                # catches bugs in the bookkeeping itself — a dead worker
                # with /healthz still green is the one unacceptable state).
                logger.exception("serving: batch bookkeeping failed")
                for p in batch:
                    if not p.done.is_set():
                        p.finish(error=RuntimeError("internal batcher "
                                                    "error (see log)"))
            if self.watchdog is not None:
                self.watchdog.beat()  # a completed cycle is real progress

    def _serve_batch(self, batch: list[_Pending]) -> None:
        now = time.monotonic()
        live: list[_Pending] = []
        expired: list[_Pending] = []
        for p in batch:
            if p.deadline is not None and now >= p.deadline:
                # Expired in the queue: complete it WITHOUT device
                # work (the edge case tests/test_serving.py pins).
                self.metrics.request_rejected("deadline")
                p.finish(error=DeadlineExceededError(
                    "deadline expired while queued "
                    f"({(now - p.enqueued) * 1e3:.0f}ms waiting)"))
                expired.append(p)
            else:
                self.metrics.queue_wait((now - p.enqueued) * 1e3)
                live.append(p)
        if live:
            try:
                # Concatenate INSIDE the shield: a MemoryError on a
                # large coalesced batch must fail these requests, not
                # the worker.
                x = (live[0].x if len(live) == 1
                     else np.concatenate([p.x for p in live]))
                batch_span = _trace.span(
                    "serve.batch", requests=len(live),
                    rows=int(x.shape[0]),
                    request_ids=[p.request_id for p in live
                                 if p.request_id is not None])
                with batch_span:
                    out = self.engine.embed(x, n_requests=len(live))
            except Exception as e:  # noqa: BLE001 — fail the batch, not
                # the worker: the loop must outlive any one bad batch.
                logger.exception("serving: device call failed for a "
                                 "batch of %d request(s)", len(live))
                for p in live:
                    p.finish(error=e)
            else:
                off = 0
                for p in live:
                    n = p.x.shape[0]
                    p.finish(result=out[off:off + n])
                    off += n
        # Queue-wait spans are emitted LAST, after every requester has
        # been woken: each emit is a line-buffered file write, and a
        # handful of synchronous writes between queue drain and dispatch
        # measurably clusters arrivals against the bounded queue under
        # burst load (serving_smoke's concurrency phase catches exactly
        # that). dur_ms still reaches back to the true wait, and the
        # record's end-time skew (~one batch) is visible-but-harmless in
        # the exported trace. Same reasoning keeps the batch span's emit
        # (its __exit__ above) adjacent to the device call rather than
        # before the finish loop: one emit, not one per request.
        # Deadline-expired requests get the span too, tagged error=
        # "deadline" — the slow requests are exactly the ones whose
        # queue_wait the trace exists to explain.
        for p in live:
            if p.request_id is not None:
                _trace.emit_span("serve.queue_wait",
                                 (now - p.enqueued) * 1e3,
                                 request_id=p.request_id)
        for p in expired:
            if p.request_id is not None:
                _trace.emit_span("serve.queue_wait",
                                 (now - p.enqueued) * 1e3,
                                 request_id=p.request_id,
                                 error="deadline")

    def _drain(self, reason: str) -> None:
        with self._lock:
            waiting = list(self._queue)
            self._queue.clear()
            self.metrics.set_queue_depth(0)
        for p in waiting:
            p.finish(error=BatcherClosed(reason))

    # -- lifecycle -------------------------------------------------------
    def close(self, timeout_s: float = 5.0) -> None:
        """Stop the worker; waiting requests fail with BatcherClosed."""
        self._closed.set()
        with self._not_empty:
            self._not_empty.notify_all()
        self._thread.join(timeout_s)
        self._drain("batcher closed")

    @property
    def closed(self) -> bool:
        return self._closed.is_set()
