"""Traffic-adaptive bucket-ladder math: histogram in, bucket edges out.

The serving engine pads every request up to a fixed ladder rung, and
``ServingMetrics`` prices the cost as ``serving_padding_waste`` — on
mixed traffic that is pure wasted device time (ROADMAP item 1).
"Ragged Paged Attention" (PAPERS.md arxiv 2604.15464) gets its TPU wins
by gridding over occupied rows instead of padded shapes; short of a
ragged kernel, the same measure-then-optimize loop PR 7's comms
accounting established applies here: MEASURE the live request-size
distribution, OPTIMIZE the ladder against it, re-AOT off the hot path,
swap atomically (engine.py owns that state machine — this module is the
pure, unit-testable half).

Two pieces:

* ``SizeHistogram`` — an online, exponentially decayed histogram of
  device-chunk row counts. Decay is per OBSERVATION (each new chunk
  multiplies every existing weight by ``decay``), so a traffic shift
  ages out at request rate, not wall-clock rate — exactly the rate at
  which the padding bill accrues.
* ``optimize_ladder`` — dynamic programming over the histogram: pick at
  most ``max_buckets`` rungs that minimize expected padded rows. The
  classic structure applies: an optimal rung sits AT an observed size
  (lowering a rung to its group's max row count strictly reduces
  padding), so the DP partitions the sorted observed sizes into
  contiguous groups and charges each group ``weight x (group_max -
  size)``. The configured maximum bucket is always kept as the top rung
  — it is the chunking cap for oversized requests and the shape the
  batcher/row-cap limits were provisioned against, so it must never
  move.

Everything here is stdlib + plain dicts: no jax, no engine state — the
DP is exact and deterministic, which is what lets the bench A/B and the
regression gate pin its output.
"""

from __future__ import annotations

import threading
from collections.abc import Mapping, Sequence

__all__ = ["SizeHistogram", "expected_padded_rows", "optimize_ladder"]

# Rescale the internal boost factor before it can overflow float range;
# entries whose decayed weight has fallen below NEGLIGIBLE (relative to
# one fresh observation) are dropped so the dict stays bounded by the
# distinct sizes of RECENT traffic.
_RESCALE_AT = 1e30
_NEGLIGIBLE = 1e-9


class SizeHistogram:
    """Exponentially decayed histogram of request/chunk row counts.

    ``observe(rows)`` gives the new sample weight 1 and implicitly
    multiplies every older sample by ``decay`` (implemented as a
    growing boost on new samples + lazy normalization, so one observe
    is O(1), not O(distinct sizes)). ``weights()`` returns the decayed
    view; ``observations`` counts raw observes forever (the
    min-requests cold-start gate reads it). Thread-safe: the engine's
    request threads observe while the re-AOT worker reads.
    """

    def __init__(self, decay: float = 0.999):
        if not 0.0 < decay <= 1.0:
            raise ValueError(f"decay must be in (0, 1], got {decay}")
        self.decay = float(decay)
        self._weights: dict[int, float] = {}
        self._boost = 1.0
        self._observations = 0
        self._lock = threading.Lock()

    def observe(self, rows: int, weight: float = 1.0) -> None:
        rows = int(rows)
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        with self._lock:
            self._observations += 1
            self._boost /= self.decay
            self._weights[rows] = (self._weights.get(rows, 0.0)
                                   + float(weight) * self._boost)
            if self._boost > _RESCALE_AT:
                self._rescale_locked()

    def _rescale_locked(self) -> None:
        boost = self._boost
        self._weights = {s: w / boost for s, w in self._weights.items()
                         if w / boost > _NEGLIGIBLE}
        self._boost = 1.0

    @property
    def observations(self) -> int:
        """Cumulative (undecayed) observe count."""
        with self._lock:
            return self._observations

    def weights(self) -> dict[int, float]:
        """Decayed weight per size (a fresh observation weighs 1.0);
        negligible tails are dropped."""
        with self._lock:
            boost = self._boost
            return {s: w / boost for s, w in self._weights.items()
                    if w / boost > _NEGLIGIBLE}

    def total_weight(self) -> float:
        return sum(self.weights().values())


def expected_padded_rows(weights: Mapping[int, float],
                         ladder: Sequence[int]) -> float:
    """Expected padded rows per (weighted) chunk under ``ladder``.

    ``weights`` maps chunk row count -> weight (a ``SizeHistogram``
    view). Sizes above the top rung are clamped to it — the engine
    chunks oversized requests through the max bucket, so only the
    clamped remainder ever pads. The objective ``optimize_ladder``
    minimizes, shared so tests/hysteresis price ladders identically.
    """
    rungs = sorted(set(int(b) for b in ladder))
    if not rungs:
        raise ValueError("ladder must have at least one rung")
    top = rungs[-1]
    cost = 0.0
    for size, weight in weights.items():
        size = min(int(size), top)
        rung = next(b for b in rungs if b >= size)
        cost += float(weight) * (rung - size)
    return cost


def optimize_ladder(weights: Mapping[int, float], max_buckets: int,
                    max_bucket: int, prior: Sequence[int],
                    ) -> tuple[int, ...]:
    """Bucket edges minimizing expected padded rows, DP-exact.

    * ``weights``: decayed size histogram (chunk rows -> weight);
    * ``max_buckets``: ladder-size budget (total rungs, top included);
    * ``max_bucket``: the immovable top rung (chunking cap);
    * ``prior``: the cold-start ladder — returned verbatim when the
      histogram is empty, so an idle or freshly booted engine keeps the
      configured buckets.

    Returns a sorted tuple of unique rungs ending in ``max_bucket``,
    ``len <= max_buckets``. Single-size traffic collapses to that size
    plus the top rung. Deterministic for a given histogram.
    """
    max_bucket = int(max_bucket)
    prior_ladder = tuple(sorted(set(int(b) for b in prior)))
    agg: dict[int, float] = {}
    for size, weight in weights.items():
        weight = float(weight)
        if weight <= 0.0:
            continue
        size = min(int(size), max_bucket)
        if size < 1:
            continue
        agg[size] = agg.get(size, 0.0) + weight
    if not agg:
        return prior_ladder  # cold start: keep the configured prior
    if max_buckets < 2:
        return (max_bucket,)

    sizes = sorted(agg)
    n = len(sizes)
    # The top rung is forced at max_bucket; when it is not itself an
    # observed size it occupies one budget slot without covering a
    # group.
    budget = max_buckets if sizes[-1] == max_bucket else max_buckets - 1
    budget = min(budget, n)

    # Prefix sums for O(1) group cost: cost(i..j) with the rung at
    # sizes[j] is sizes[j]*sum(w) - sum(w*s) over the group.
    w = [agg[s] for s in sizes]
    pw = [0.0] * (n + 1)
    pws = [0.0] * (n + 1)
    for i, s in enumerate(sizes):
        pw[i + 1] = pw[i] + w[i]
        pws[i + 1] = pws[i] + w[i] * s

    def group_cost(i: int, j: int) -> float:
        """Padding cost of sizes[i..j] (inclusive) padded to sizes[j]."""
        return sizes[j] * (pw[j + 1] - pw[i]) - (pws[j + 1] - pws[i])

    inf = float("inf")
    # dp[j][b]: min cost covering the first j sizes with exactly b
    # groups; more groups never cost more, so dp[n][budget] is optimal.
    dp = [[inf] * (budget + 1) for _ in range(n + 1)]
    dp[0][0] = 0.0
    back = [[0] * (budget + 1) for _ in range(n + 1)]
    for j in range(1, n + 1):
        for b in range(1, min(budget, j) + 1):
            best, arg = inf, j - 1
            for i in range(b - 1, j):
                prev = dp[i][b - 1]
                if prev == inf:
                    continue
                cost = prev + group_cost(i, j - 1)
                if cost < best:
                    best, arg = cost, i
            dp[j][b] = best
            back[j][b] = arg
    b = min(budget, n)
    rungs: list[int] = []
    j = n
    while j > 0:
        rungs.append(sizes[j - 1])  # each group's rung is its max size
        j = back[j][b]
        b -= 1
    ladder = tuple(sorted(set(rungs) | {max_bucket}))
    assert len(ladder) <= max_buckets, (ladder, max_buckets)
    return ladder
