"""Serving observability: one thread-safe registry, JSON out.

The training side already reports steps/s and MFU (utils/profiling.py,
trainer.train_loop); serving needs a different vocabulary — queue depth,
batch-fill ratio, padding waste, tail latency — because an embedding
service lives or dies by its p99 and by how well the micro-batcher
amortizes device dispatches (DLRM inference studies put batching and
memory-traffic decisions first; PAPERS.md arxiv 2512.05831). Everything
here is stdlib: counters and bounded latency windows behind one lock,
exported as a plain dict so ``/metrics`` can ``json.dumps`` it and
``scripts/serving_smoke.sh`` can assert on it.

Percentiles are EXACT over a bounded sliding window (default 2048
samples per series), not bucket-midpoint estimates: a smoke run emits a
few hundred requests total, where histogram-bucket error would swamp the
p50/p95 gap the numbers exist to show. The window bounds memory on
long-lived servers; cumulative count/sum never reset, so rates stay
computable from deltas.
"""

from __future__ import annotations

import threading
import time
from collections import deque

__all__ = ["LatencyWindow", "ServingMetrics"]


class LatencyWindow:
    """Cumulative count/sum plus a bounded window for exact percentiles."""

    def __init__(self, window: int = 2048):
        self.count = 0
        self.total_ms = 0.0
        self._window: deque[float] = deque(maxlen=window)

    def record(self, ms: float) -> None:
        self.count += 1
        self.total_ms += ms
        self._window.append(ms)

    def snapshot(self) -> dict:
        if not self._window:
            return {"count": self.count}
        ordered = sorted(self._window)
        n = len(ordered)

        def pct(q: float) -> float:
            return ordered[min(n - 1, int(q * n))]

        return {
            "count": self.count,
            "mean_ms": round(self.total_ms / self.count, 4),
            "p50_ms": round(pct(0.50), 4),
            "p95_ms": round(pct(0.95), 4),
            "p99_ms": round(pct(0.99), 4),
            "max_ms": round(ordered[-1], 4),
            "window": n,
        }


class ServingMetrics:
    """The serving stack's shared scoreboard.

    Engine, batcher, and server all write here (each holds a reference to
    the same instance); ``/metrics`` reads ``to_dict()``. One lock guards
    everything — every operation is a few counter bumps, so contention is
    noise next to a device call.
    """

    def __init__(self, latency_window: int = 2048):
        self._lock = threading.Lock()
        self.started_at = time.time()
        # Request lifecycle.
        self.requests = 0              # accepted into the queue
        self.responses = 0             # completed (ok)
        self.errors = 0                # failed after acceptance
        self.rejected_queue_full = 0   # backpressure rejections
        self.rejected_deadline = 0     # expired before reaching the device
        # Coalescing (batcher level: one dispatch = one engine.embed) vs
        # device dispatch (engine level: one call = one padded bucket; an
        # oversized dispatch chunks into several). batch_fill_ratio is
        # requests/DISPATCH — the scheduler's coalescing claim — so
        # engine-side chunking can't dilute it below 1.
        self.dispatches = 0            # engine.embed invocations
        self.requests_coalesced = 0    # requests riding those dispatches
        self.device_calls = 0          # bucketed executable calls (chunks)
        self.rows_real = 0             # rows of actual payload sent
        self.rows_padded = 0           # zero rows added to reach a bucket
        # Compile-cache behavior (flat compiles after warmup is the
        # serving_smoke.sh acceptance signal).
        self.compiles = 0
        self.compile_cache_hits = 0
        # Queue gauge (set by the batcher; capacity fixed at wiring time).
        self.queue_depth = 0
        self.queue_capacity = 0
        # Per-bucket dispatch counters: bucket -> [calls, rows_real,
        # rows_padded].
        self._buckets: dict[int, list[int]] = {}
        # Latency series (ms).
        self.latency = {
            "total": LatencyWindow(latency_window),       # submit -> result
            "queue_wait": LatencyWindow(latency_window),  # submit -> dispatch
            "device": LatencyWindow(latency_window),      # one engine.embed
        }

    # -- writers ---------------------------------------------------------
    def request_accepted(self) -> None:
        with self._lock:
            self.requests += 1

    def request_done(self, total_ms: float, ok: bool = True) -> None:
        with self._lock:
            if ok:
                self.responses += 1
            else:
                self.errors += 1
            self.latency["total"].record(total_ms)

    def request_rejected(self, reason: str) -> None:
        with self._lock:
            if reason == "queue_full":
                self.rejected_queue_full += 1
            else:
                self.rejected_deadline += 1

    def dispatch(self, n_requests: int) -> None:
        with self._lock:
            self.dispatches += 1
            self.requests_coalesced += n_requests

    def device_call(self, bucket: int, rows_real: int, rows_padded: int,
                    device_ms: float) -> None:
        with self._lock:
            self.device_calls += 1
            self.rows_real += rows_real
            self.rows_padded += rows_padded
            b = self._buckets.setdefault(int(bucket), [0, 0, 0])
            b[0] += 1
            b[1] += rows_real
            b[2] += rows_padded
            self.latency["device"].record(device_ms)

    def queue_wait(self, ms: float) -> None:
        with self._lock:
            self.latency["queue_wait"].record(ms)

    def compiled(self) -> None:
        with self._lock:
            self.compiles += 1

    def compile_cache_hit(self) -> None:
        with self._lock:
            self.compile_cache_hits += 1

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = int(depth)

    # -- reader ----------------------------------------------------------
    def to_dict(self) -> dict:
        with self._lock:
            padded_total = self.rows_real + self.rows_padded
            return {
                "uptime_s": round(time.time() - self.started_at, 3),
                "requests": self.requests,
                "responses": self.responses,
                "errors": self.errors,
                "rejected_queue_full": self.rejected_queue_full,
                "rejected_deadline": self.rejected_deadline,
                "dispatches": self.dispatches,
                "device_calls": self.device_calls,
                "batch_fill_ratio": round(
                    self.requests_coalesced / self.dispatches, 4)
                if self.dispatches else None,
                "padding_waste": round(self.rows_padded / padded_total, 4)
                if padded_total else None,
                "queue_depth": self.queue_depth,
                "queue_capacity": self.queue_capacity,
                "compile": {
                    "compiles": self.compiles,
                    "cache_hits": self.compile_cache_hits,
                },
                "buckets": {
                    str(b): {"calls": v[0], "rows_real": v[1],
                             "rows_padded": v[2]}
                    for b, v in sorted(self._buckets.items())
                },
                "latency_ms": {name: win.snapshot()
                               for name, win in self.latency.items()},
            }
