"""Serving observability, now published through obs.MetricsRegistry.

The serving vocabulary is unchanged — queue depth, batch-fill ratio,
padding waste, exact-window tail latency (DLRM inference studies put
batching and memory-traffic decisions first; PAPERS.md arxiv
2512.05831) — but the store is no longer a private dict: every series
lives in a ``MetricsRegistry`` (ISSUE 3), so serving and training share
one exporter path (JSON and Prometheus text are two views of the same
objects, and ``/metrics?format=prometheus`` needs no serving-specific
renderer).

This also fixes the old scrape cost: ``to_dict()`` used to rebuild the
whole export under ONE lock that every writer also contended for; now
each metric guards only itself and a scrape reads them one at a time —
no double-locking, no stop-the-world snapshot. The p50/p95/p99 rule
previously private to ``LatencyWindow`` is the registry Histogram's
single-source ``quantile`` (obs/registry.py), shared with the training
timeline.

``LatencyWindow`` remains as the ms-flavored Histogram the serving wire
format always exposed (count / mean_ms / p50_ms / p95_ms / p99_ms /
max_ms / window); percentiles are EXACT over a bounded window, and
cumulative count/sum never reset, exactly as before.
"""

from __future__ import annotations

import os
import threading
import time

from ..obs.registry import Histogram, MetricsRegistry

__all__ = ["LatencyWindow", "ServingMetrics", "read_rss_bytes"]


def read_rss_bytes() -> int | None:
    """This process's resident set size from ``/proc/self/statm``
    (resident pages x page size). Returns None where procfs (or the
    sysconf key) is unavailable — a graceful no-op off Linux, per the
    ISSUE 18 vertical-signals contract."""
    try:
        with open("/proc/self/statm") as f:
            fields = f.read().split()
        return int(fields[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return None


class LatencyWindow(Histogram):
    """Millisecond-unit Histogram with the serving snapshot shape."""

    def __init__(self, window: int = 2048, name: str = "latency_ms",
                 labels: dict | None = None):
        super().__init__(name, labels=labels, window=window)

    @property
    def total_ms(self) -> float:
        return self.total

    def snapshot(self) -> dict:
        return self.snapshot_ms()


class ServingMetrics:
    """The serving stack's shared scoreboard, registry-backed.

    Engine, batcher, and server all write here (each holds a reference
    to the same instance); ``/metrics`` reads ``to_dict()`` (JSON) or
    renders ``self.registry`` (Prometheus). Writer methods are a few
    per-metric counter bumps — contention is noise next to a device
    call.

    ``registry=None`` creates a private registry: several stacks can
    coexist in one process (tests) without cross-counting. Pass
    ``obs.default_registry()`` to join the process-wide export.
    """

    def __init__(self, latency_window: int = 2048,
                 registry: MetricsRegistry | None = None):
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.started_at = time.time()
        r = self.registry
        self._requests = r.counter(
            "serving_requests_total", "requests accepted into the queue")
        self._responses = r.counter(
            "serving_responses_total", "requests completed ok")
        self._errors = r.counter(
            "serving_errors_total", "requests failed after acceptance")
        self._rejected_queue_full = r.counter(
            "serving_rejected_queue_full_total",
            "backpressure rejections (429)")
        self._rejected_deadline = r.counter(
            "serving_rejected_deadline_total",
            "requests expired before reaching the device (504)")
        # Coalescing (batcher level: one dispatch = one engine.embed) vs
        # device dispatch (engine level: one call = one padded bucket;
        # an oversized dispatch chunks into several). batch_fill_ratio
        # is requests/DISPATCH — the scheduler's coalescing claim — so
        # engine-side chunking can't dilute it below 1.
        self._dispatches = r.counter(
            "serving_dispatches_total", "engine.embed invocations")
        self._requests_coalesced = r.counter(
            "serving_requests_coalesced_total",
            "requests riding those dispatches")
        self._device_calls = r.counter(
            "serving_device_calls_total",
            "bucketed executable calls (chunks)")
        self._rows_real = r.counter(
            "serving_rows_real_total", "rows of actual payload sent")
        self._rows_padded = r.counter(
            "serving_rows_padded_total",
            "zero rows added to reach a bucket")
        self._compiles = r.counter(
            "serving_compiles_total", "bucket executable compiles")
        self._compile_cache_hits = r.counter(
            "serving_compile_cache_hits_total",
            "bucket executable cache hits")
        self._queue_depth = r.gauge(
            "serving_queue_depth", "requests waiting in the queue")
        self._queue_capacity = r.gauge(
            "serving_queue_capacity", "bounded queue capacity")
        # Derived gauges kept current at write time so the Prometheus
        # rendering carries them too (the smoke test asserts
        # batch_fill_ratio appears in BOTH formats).
        self._fill_ratio = r.gauge(
            "serving_batch_fill_ratio",
            "requests per dispatch (coalescing factor)")
        self._padding_waste = r.gauge(
            "serving_padding_waste", "padded-row fraction of device rows")
        self.latency = {
            name: r.histogram("serving_latency_ms",
                              "request latency by stage",
                              labels={"stage": name},
                              window=latency_window)
            for name in ("total", "queue_wait", "device")
        }
        # Zero-downtime rollout (fleet workers): weight swaps by mode
        # ("reused" = same structure, compiled ladder kept; "warmed" =
        # structure changed, new ladder compiled BEFORE the swap) plus
        # the checkpoint step currently served — what the router's
        # canary logic and the fleet smoke read per worker.
        self._swap_lock = threading.Lock()
        self._swaps: dict[str, object] = {}
        self._ckpt_step = r.gauge(
            "serving_checkpoint_step",
            "training step of the checkpoint currently served "
            "(-1 = random init)")
        self._ckpt_step.set(-1)
        self._rollbacks = r.counter(
            "serving_rollbacks_total",
            "weight rollbacks after a canary breach")
        # bucket -> (calls, rows_real, rows_padded, waste-gauge) labeled
        # series; created on first use (the ladder is not known here).
        # The per-bucket waste gauge is the padding bill ITEMIZED: the
        # aggregate serving_padding_waste says mixed traffic pads, the
        # breakdown says which rung to split (ISSUE 9).
        self._bucket_lock = threading.Lock()
        self._buckets: dict[int, tuple] = {}
        # Request-size histogram: device-chunk row counts as labeled
        # cumulative counters (cardinality bounded by the max bucket).
        # This is the OBSERVABLE view; the decayed optimizer histogram
        # lives in the engine (serving/ladder.py).
        self._size_lock = threading.Lock()
        self._sizes: dict[int, object] = {}
        # Adaptive bucket ladder (ISSUE 9): generation 0 is the
        # configured prior; every atomic re-AOT swap bumps it. Ladder
        # membership renders as serving_ladder_bucket{bucket=...} 1|0
        # gauges so a scraper sees rungs come and go.
        self._ladder_lock = threading.Lock()
        self._ladder_buckets: list[int] = []
        self._ladder_rungs: dict[int, object] = {}
        self._ladder_gen = r.gauge(
            "serving_ladder_generation",
            "adaptive bucket-ladder generation (0 = configured prior)")
        self._ladder_swaps = r.counter(
            "serving_ladder_swaps_total",
            "atomic ladder swaps published by the re-AOT worker")
        self._ladder_compiles = r.counter(
            "serving_ladder_compiles_total",
            "background bucket compiles for ladder re-AOT "
            "(never on a request's hot path)")
        self._ladder_failures = r.counter(
            "serving_ladder_refresh_failures_total",
            "ladder re-AOT attempts that failed (old ladder kept)")
        # Per-cause compile counters (ISSUE 14), created lazily like
        # the per-mode swap counters below.
        self._compile_cause_lock = threading.Lock()
        self._compile_causes: dict[str, object] = {}
        # Worker vertical signals (ISSUE 18): per-process memory and
        # compile-cache pressure, refreshed at scrape time (/metrics)
        # rather than on a writer path — they are properties of the
        # process, not of any request.
        self._worker_rss = r.gauge(
            "serving_worker_rss_bytes",
            "resident set size of this worker process "
            "(0 where procfs is unavailable)")
        self._compile_cache_entries = r.gauge(
            "serving_compile_cache_entries",
            "entries in the engine's bucket-executable cache")
        # Cross-process correlation (ISSUE 7): run identity, stamped by
        # set_run_id. None until a run id is known (tests, bare engines).
        self.run_id: str | None = None

    def update_vertical(self,
                        compile_cache_entries: int | None = None) -> None:
        """Refresh the per-process vertical gauges (scrape-time call
        site: serving/server.py's /metrics handler). RSS read failure
        leaves the gauge at its last value — absent procfs simply never
        moves it off 0."""
        rss = read_rss_bytes()
        if rss is not None:
            self._worker_rss.set(rss)
        if compile_cache_entries is not None:
            self._compile_cache_entries.set(int(compile_cache_entries))

    def set_run_id(self, run_id: str | None) -> None:
        """Label this serving process's metrics with a run id.

        Training has stamped run_id on every JSONL record since PR 3;
        serving scrapes were anonymous. The id lands as the standard
        info-metric pattern (``serving_run_info{run_id="..."} 1`` — a
        constant-label series a scraper joins on) plus a ``run_id`` key
        in the JSON wire shape, so a serving scrape correlates with the
        training run whose checkpoints it serves.
        """
        if not run_id:
            return
        self.run_id = str(run_id)
        self.registry.gauge(
            "serving_run_info",
            "serving process identity (join key for cross-process "
            "correlation)", labels={"run_id": self.run_id}).set(1)

    # -- compatibility readers (engine/bench read these directly) --------
    @property
    def requests(self) -> int:
        return int(self._requests.value)

    @property
    def responses(self) -> int:
        return int(self._responses.value)

    @property
    def errors(self) -> int:
        return int(self._errors.value)

    @property
    def rejected_queue_full(self) -> int:
        return int(self._rejected_queue_full.value)

    @property
    def rejected_deadline(self) -> int:
        return int(self._rejected_deadline.value)

    @property
    def dispatches(self) -> int:
        return int(self._dispatches.value)

    @property
    def requests_coalesced(self) -> int:
        return int(self._requests_coalesced.value)

    @property
    def device_calls(self) -> int:
        return int(self._device_calls.value)

    @property
    def rows_real(self) -> int:
        return int(self._rows_real.value)

    @property
    def rows_padded(self) -> int:
        return int(self._rows_padded.value)

    @property
    def compiles(self) -> int:
        return int(self._compiles.value)

    @property
    def compile_cache_hits(self) -> int:
        return int(self._compile_cache_hits.value)

    @property
    def queue_depth(self) -> int:
        return int(self._queue_depth.value)

    @property
    def queue_capacity(self) -> int:
        return int(self._queue_capacity.value)

    @queue_capacity.setter
    def queue_capacity(self, value: int) -> None:
        # The batcher assigns this as a plain attribute at wiring time.
        self._queue_capacity.set(int(value))

    # -- writers ---------------------------------------------------------
    def request_accepted(self) -> None:
        self._requests.inc()

    def request_done(self, total_ms: float, ok: bool = True) -> None:
        (self._responses if ok else self._errors).inc()
        self.latency["total"].observe(total_ms)

    def request_rejected(self, reason: str) -> None:
        if reason == "queue_full":
            self._rejected_queue_full.inc()
        else:
            self._rejected_deadline.inc()

    def dispatch(self, n_requests: int) -> None:
        self._dispatches.inc()
        self._requests_coalesced.inc(n_requests)
        self._fill_ratio.set(
            self._requests_coalesced.value / self._dispatches.value)

    def _bucket_counters(self, bucket: int) -> tuple:
        with self._bucket_lock:
            counters = self._buckets.get(bucket)
            if counters is None:
                labels = {"bucket": str(int(bucket))}
                counters = (
                    self.registry.counter(
                        "serving_bucket_calls_total",
                        "device calls per ladder bucket", labels=labels),
                    self.registry.counter(
                        "serving_bucket_rows_real_total",
                        "real rows per ladder bucket", labels=labels),
                    self.registry.counter(
                        "serving_bucket_rows_padded_total",
                        "padded rows per ladder bucket", labels=labels),
                    self.registry.gauge(
                        "serving_bucket_padding_waste",
                        "padded-row fraction of this bucket's device "
                        "rows", labels=labels),
                )
                self._buckets[bucket] = counters
            return counters

    def device_call(self, bucket: int, rows_real: int, rows_padded: int,
                    device_ms: float) -> None:
        self._device_calls.inc()
        self._rows_real.inc(rows_real)
        self._rows_padded.inc(rows_padded)
        calls, real, padded, waste = self._bucket_counters(int(bucket))
        calls.inc()
        real.inc(rows_real)
        padded.inc(rows_padded)
        bucket_total = real.value + padded.value
        if bucket_total:
            waste.set(padded.value / bucket_total)
        self.latency["device"].observe(device_ms)
        total = self._rows_real.value + self._rows_padded.value
        if total:
            self._padding_waste.set(self._rows_padded.value / total)

    def observe_request_size(self, rows: int) -> None:
        """One device-chunk row count into the request-size histogram
        (labeled cumulative counters — the Prometheus/JSON-visible view
        of the distribution the adaptive ladder optimizes against).

        The ``rows`` label is the POWER-OF-TWO CEILING of the real
        count, not the count itself (ISSUE 10 satellite): raw counts
        mint one series per distinct size, so an adversarial sweep of
        1..max_request_rows would bloat every scrape for the lifetime
        of the process. Pow2 bucketing caps cardinality at
        log2(max-rows) series while keeping the shape the ladder story
        needs; the optimizer's own decayed histogram (serving/
        ladder.py) still sees exact sizes — only the export buckets.
        """
        bucket = 1 << max(0, int(rows) - 1).bit_length()
        with self._size_lock:
            counter = self._sizes.get(bucket)
            if counter is None:
                counter = self._sizes[bucket] = self.registry.counter(
                    "serving_request_size_total",
                    "device chunks by real row count "
                    "(pow2-ceiling buckets)",
                    labels={"rows": str(bucket)})
        counter.inc()

    # -- adaptive ladder (ISSUE 9) ---------------------------------------
    def set_ladder(self, buckets, generation: int) -> None:
        """Publish the live ladder: membership gauges (removed rungs go
        to 0, never vanish mid-scrape) + the generation gauge."""
        rungs = sorted(int(b) for b in buckets)
        with self._ladder_lock:
            self._ladder_buckets = rungs
            for b in rungs:
                if b not in self._ladder_rungs:
                    self._ladder_rungs[b] = self.registry.gauge(
                        "serving_ladder_bucket",
                        "1 = rung currently in the live ladder",
                        labels={"bucket": str(b)})
            for b, gauge in self._ladder_rungs.items():
                gauge.set(1 if b in rungs else 0)
        self._ladder_gen.set(int(generation))

    def ladder_swap(self, buckets, generation: int) -> None:
        self._ladder_swaps.inc()
        self.set_ladder(buckets, generation)

    def ladder_compiled(self, cause: str | None = None) -> None:
        self._ladder_compiles.inc()
        if cause:
            self.compile_cause(cause)

    def ladder_refresh_failed(self) -> None:
        self._ladder_failures.inc()

    @property
    def ladder_generation(self) -> int:
        return int(self._ladder_gen.value)

    @property
    def ladder_swaps(self) -> int:
        return int(self._ladder_swaps.value)

    @property
    def ladder_compiles(self) -> int:
        return int(self._ladder_compiles.value)

    def queue_wait(self, ms: float) -> None:
        self.latency["queue_wait"].observe(ms)

    def compiled(self, cause: str | None = None) -> None:
        self._compiles.inc()
        if cause:
            self.compile_cause(cause)

    def compile_cause(self, cause: str) -> None:
        """Itemize one compile by WHY it happened (ISSUE 14: the
        recompile-cause differ's vocabulary — first_compile/new_shape/
        dtype/weights_reload/structure/recompile, a closed set, so the
        `reason` label's cardinality is bounded by construction). The
        bare `serving_compiles_total` / `serving_ladder_compiles_total`
        stay the request-visible vs background split; this series is
        the causal breakdown across both."""
        with self._compile_cause_lock:
            counter = self._compile_causes.get(cause)
            if counter is None:
                counter = self._compile_causes[cause] = \
                    self.registry.counter(
                        "serving_compiles_by_cause_total",
                        "executable compiles by recompile-differ cause",
                        labels={"reason": str(cause)})
        counter.inc()

    def compile_cache_hit(self) -> None:
        self._compile_cache_hits.inc()

    def set_queue_depth(self, depth: int) -> None:
        self._queue_depth.set(int(depth))

    def model_swap(self, mode: str) -> None:
        with self._swap_lock:
            counter = self._swaps.get(mode)
            if counter is None:
                counter = self._swaps[mode] = self.registry.counter(
                    "serving_model_swaps_total",
                    "live weight swaps by mode", labels={"mode": mode})
        counter.inc()

    def set_checkpoint_step(self, step: int) -> None:
        self._ckpt_step.set(int(step))

    def rollback(self) -> None:
        self._rollbacks.inc()

    @property
    def checkpoint_step(self) -> int:
        return int(self._ckpt_step.value)

    @property
    def model_swaps(self) -> int:
        with self._swap_lock:
            return int(sum(c.value for c in self._swaps.values()))

    # -- readers ---------------------------------------------------------
    def to_dict(self) -> dict:
        """The JSON wire shape (unchanged keys), assembled metric by
        metric — no single scrape-wide lock."""
        rows_real, rows_padded = self.rows_real, self.rows_padded
        dispatches = self.dispatches
        padded_total = rows_real + rows_padded
        with self._bucket_lock:
            bucket_items = sorted(self._buckets.items())
        with self._size_lock:
            size_items = sorted(self._sizes.items())
        with self._ladder_lock:
            ladder_buckets = list(self._ladder_buckets)
        return {
            "uptime_s": round(time.time() - self.started_at, 3),
            "run_id": self.run_id,
            "requests": self.requests,
            "responses": self.responses,
            "errors": self.errors,
            "rejected_queue_full": self.rejected_queue_full,
            "rejected_deadline": self.rejected_deadline,
            "dispatches": dispatches,
            "device_calls": self.device_calls,
            "batch_fill_ratio": round(
                self.requests_coalesced / dispatches, 4)
            if dispatches else None,
            "padding_waste": round(rows_padded / padded_total, 4)
            if padded_total else None,
            "queue_depth": self.queue_depth,
            "queue_capacity": self.queue_capacity,
            "compile": {
                "compiles": self.compiles,
                "cache_hits": self.compile_cache_hits,
            },
            "checkpoint_step": self.checkpoint_step,
            "model_swaps": self.model_swaps,
            "ladder": {
                "buckets": ladder_buckets,
                "generation": self.ladder_generation,
                "swaps": self.ladder_swaps,
                "compiles": self.ladder_compiles,
                "refresh_failures": int(self._ladder_failures.value),
            },
            "request_sizes": {str(rows): int(c.value)
                              for rows, c in size_items},
            "buckets": {
                str(b): {"calls": int(calls.value),
                         "rows_real": int(real.value),
                         "rows_padded": int(padded.value),
                         "padding_waste": round(
                             padded.value / (real.value + padded.value),
                             4)
                         if (real.value + padded.value) else None}
                for b, (calls, real, padded, _waste) in bucket_items
            },
            "latency_ms": {name: win.snapshot_ms()
                           for name, win in self.latency.items()},
        }

    def render_prometheus(self) -> str:
        """Exposition-format text for everything in this stack's
        registry (the serving /metrics content-negotiation target)."""
        return self.registry.render_prometheus()
