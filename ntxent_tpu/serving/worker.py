"""Worker-side zero-downtime rollout: watch checkpoints, swap warm.

A fleet worker is an ``EmbeddingServer`` plus this module's
``CheckpointWatcher``: a daemon thread that polls the crash-safe
checkpoint directory (training/checkpoint.py) with the SAME validity
rules training restores use — manifest-verified, newest-VALID step, a
torn or corrupt step is invisible — and hot-swaps the engine's weights
when a new step lands:

* **warm, then swap**: ``engine.swap_variables`` reuses the compiled
  ladder when the pytree structure is unchanged (the overwhelmingly
  common case — executables take weights as arguments) and pre-compiles
  the full ladder BEFORE publishing when it changed. Requests never see
  a cold bucket, which is what keeps per-worker compile counts flat
  across a rollout (the fleet smoke's acceptance signal);
* **staggered adoption** (``delay_s``): the fleet hands each worker a
  different delay, so a new checkpoint reaches one worker first — that
  worker IS the canary cohort the router routes a configured traffic
  fraction to;
* **rollback** (``rollback()``, wired to the worker's ``POST
  /rollback``): revert to the previously served weights and blocklist
  the bad step so the watcher never re-adopts it. The router calls this
  on every worker at the bad step when the canary error rate breaches.

The watcher never writes to the checkpoint directory (no GC, no saves)
— it is a pure reader beside the training job that owns the dir.
"""

from __future__ import annotations

import logging
import threading
import time
from collections.abc import Callable

from ..obs import events as obs_events

logger = logging.getLogger(__name__)

__all__ = ["CheckpointWatcher"]


def default_variables_fn(state) -> dict:
    """TrainState -> the variables dict the serving forward applies
    (the same shape cli.serve_main builds at startup)."""
    return {"params": state.params, "batch_stats": state.batch_stats}


class CheckpointWatcher:
    """Poll a checkpoint dir; warm-swap the engine on a new valid step.

    ``template`` is the TrainState template restores deserialize into
    (cli builds it from the same model flags as the engine).
    ``initial_step`` is the step already being served (None = random
    init — the first valid step on disk is adopted as an upgrade).
    """

    def __init__(self, ckpt_dir, template, engine,
                 poll_s: float = 2.0, delay_s: float = 0.0,
                 initial_step: int | None = None,
                 variables_fn: Callable = default_variables_fn,
                 on_swap: Callable[[int, str], None] | None = None):
        from ..training.checkpoint import CheckpointManager

        # max_to_keep=None: retention/GC belong to the training process
        # that owns the directory; a reader must never collect its steps.
        self.manager = CheckpointManager(ckpt_dir, max_to_keep=None)
        self.template = template
        self.engine = engine
        self.poll_s = float(poll_s)
        self.delay_s = float(delay_s)
        self.variables_fn = variables_fn
        self.on_swap = on_swap
        self.current_step: int | None = initial_step
        self.blocked_steps: set[int] = set()
        self.swaps = 0
        self.rollbacks = 0
        self._prev: tuple[int | None, object] | None = None
        self._first_seen: dict[int, float] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        if initial_step is not None:
            engine.metrics.set_checkpoint_step(initial_step)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "CheckpointWatcher":
        if self._thread is not None:
            raise RuntimeError("watcher already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-ckpt-watcher")
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None
        self.manager.close()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — a bad poll must not kill
                # the watcher: the worker keeps serving current weights.
                logger.exception("checkpoint watcher: poll failed")

    # -- adoption ---------------------------------------------------------
    def _candidate_step(self) -> int | None:
        """Newest manifest-VALID step that is not blocklisted and not
        what we already serve (newest-valid semantics from PR 5: a torn
        or corrupt step can never be adopted)."""
        for step in sorted(self.manager.all_steps(), reverse=True):
            if step in self.blocked_steps:
                continue
            if step == self.current_step:
                return None  # already serving the newest acceptable step
            if self.manager.verify(step):
                return step
            logger.warning("checkpoint watcher: step %d fails "
                           "verification — skipping", step)
        return None

    def poll_once(self) -> bool:
        """One poll cycle; returns True when a swap happened."""
        with self._lock:
            step = self._candidate_step()
            if step is None:
                return False
            if self.delay_s > 0:
                first = self._first_seen.setdefault(step, time.monotonic())
                if time.monotonic() - first < self.delay_s:
                    return False  # staggered: not this worker's turn yet
            return self._adopt(step)

    def _adopt(self, step: int) -> bool:
        try:
            state = self.manager.restore(self.template, step=step)
        except Exception as e:  # noqa: BLE001 — a CRC-clean step that
            # fails to deserialize (foreign format) must not wedge the
            # watcher in a retry loop: block it and keep serving.
            logger.exception("checkpoint watcher: restore of step %d "
                             "failed — blocklisting it", step)
            self.blocked_steps.add(step)
            obs_events.emit("rollout", action="restore_failed", step=step,
                            error=f"{type(e).__name__}: {e}")
            return False
        variables = self.variables_fn(state)
        prev = (self.current_step, self.engine.variables)
        mode = self.engine.swap_variables(variables)
        self._prev = prev
        self.current_step = step
        self.swaps += 1
        self._first_seen.pop(step, None)
        self.engine.metrics.set_checkpoint_step(step)
        obs_events.emit("rollout", action="swap", step=step, mode=mode,
                        previous_step=prev[0])
        logger.info("checkpoint watcher: now serving step %d (%s, "
                    "previous %s)", step, mode, prev[0])
        if self.on_swap is not None:
            self.on_swap(step, mode)
        return True

    # -- rollback ---------------------------------------------------------
    def rollback(self, step: int | None = None) -> bool:
        """Revert to the previously served weights; blocklist the bad
        step. ``step=None`` blocks whatever is currently served. Returns
        True when weights actually changed (False: the named step is not
        the one being served — still blocklisted so it is never
        adopted)."""
        with self._lock:
            bad = step if step is not None else self.current_step
            if bad is not None:
                self.blocked_steps.add(bad)
                self._first_seen.pop(bad, None)
            if bad is None or bad != self.current_step:
                return False
            if self._prev is None:
                logger.warning("checkpoint watcher: rollback of step %s "
                               "requested but no previous weights held",
                               bad)
                return False
            prev_step, prev_vars = self._prev
            self.engine.swap_variables(prev_vars)
            self.current_step = prev_step
            self._prev = None
            self.rollbacks += 1
            self.engine.metrics.set_checkpoint_step(
                prev_step if prev_step is not None else -1)
            self.engine.metrics.rollback()
            obs_events.emit("rollout", action="rollback", step=bad,
                            restored_step=prev_step)
            logger.warning("checkpoint watcher: rolled back step %d -> "
                           "%s (step blocklisted)", bad, prev_step)
            return True
