"""Request-size limits shared by the worker AND the router tier.

This module exists to stay import-light: ``router.py`` (the JAX-free
fleet front door) needs the same body cap ``server.py`` (the worker
half, which imports the engine and therefore JAX) enforces, and must
not drag the whole worker stack in to read one constant.
"""

# Request-size caps: the bounded queue protects device time, but a body
# has to be parsed BEFORE it can be queued — without caps a multi-GB
# JSON body (or one merely-huge valid request hogging the single worker
# through thousands of chunked device calls) exhausts memory or
# head-of-line-blocks everything without a single 429. Oversized bodies
# get 413 + Connection: close without being read.
MAX_BODY_BYTES = 32 << 20

__all__ = ["MAX_BODY_BYTES"]
