"""Content-keyed embedding cache: the load the workers never see.

The DLRM embedding-bag inference analysis (PAPERS.md arxiv 2512.05831)
puts a number on what production traffic looks like: most lookups
repeat, so a content-keyed cache in front of the device absorbs a large
fraction of the load before it costs any accelerator time. For this
fleet the same observation is ALSO a robustness property — warm keys
keep serving through a worker crash, because a hit never leaves the
router process.

``EmbeddingCache`` caches per ROW, not per request: the key is a
content hash of one example's bytes (+ shape/dtype so a reshaped array
can never alias), so a mixed request whose rows partially repeat still
hits on the repeated ones and forwards only the misses. Bounds are
explicit and double-layered:

* **LRU capacity** (``capacity_rows``): a hit refreshes recency; an
  insert past capacity evicts the coldest entries;
* **TTL** (``ttl_s``): an entry older than the TTL is a MISS (and is
  evicted) even when capacity has room — a rolled-out model must not
  serve pre-rollout embeddings forever. ``clear()`` is the rollout
  hook: the router flushes on a trusted-version change so a new
  checkpoint's embeddings never mix with the old one's.

Counters ride the shared ``MetricsRegistry`` per request-size bucket
(the same ladder vocabulary the engine uses): hit/miss row counts,
evictions by reason, and a current-size gauge. A lookup that fully
hits is a visible trace slice — the router emits ``fleet.cache`` with
the request id — so a cached answer explains itself in the exported
trace instead of looking like a mysteriously fast worker.
"""

from __future__ import annotations

import hashlib
import threading
import time
from collections import OrderedDict
from collections.abc import Sequence

import numpy as np

from ..obs.registry import MetricsRegistry

__all__ = ["EmbeddingCache"]


def row_key(row: np.ndarray) -> bytes:
    """Content hash of one example: bytes + shape + dtype (two arrays
    that agree here are the same input to a deterministic forward)."""
    h = hashlib.sha1(row.tobytes())
    h.update(f"{row.shape}:{row.dtype}".encode())
    return h.digest()


class EmbeddingCache:
    """TTL + LRU bounded map from row content hash to embedding row.

    Thread-safe: the router's handler threads look up and insert
    concurrently. ``buckets`` is only a labeling vocabulary (which
    ladder rung a request's row count falls in); it does not change
    behavior.
    """

    def __init__(self, capacity_rows: int = 4096, ttl_s: float = 300.0,
                 buckets: Sequence[int] = (1, 4, 16, 64, 128),
                 registry: MetricsRegistry | None = None,
                 clock=time.monotonic, hot_rows: int = 64):
        if capacity_rows < 1:
            raise ValueError(
                f"capacity_rows must be >= 1, got {capacity_rows}")
        if ttl_s <= 0:
            raise ValueError(f"ttl_s must be > 0, got {ttl_s}")
        self.capacity_rows = int(capacity_rows)
        self.ttl_s = float(ttl_s)
        self.hot_rows = int(hot_rows)
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self.clock = clock
        self._lock = threading.Lock()
        self._entries: OrderedDict[bytes, tuple[np.ndarray, float]] = \
            OrderedDict()
        # Bumped by clear(): a reader that captured the generation
        # before lookup() can tell whether a flush (model change)
        # landed while its misses were in flight — merged entries from
        # two generations would mix embeddings of two models.
        self._generation = 0
        # Hot-row side store (ROADMAP item 4 follow-up): the INPUT rows
        # whose keys actually hit, bounded to the hot_rows most recent
        # distinct ones. Inputs are model-independent, so clear() — a
        # MODEL change — keeps them: they are exactly what a promote
        # replays through the new model instead of booting cold
        # (``hot_keys``). A row is copied in only on its FIRST hit.
        self._hot: OrderedDict[bytes, np.ndarray] = OrderedDict()
        r = self.registry
        self._size = r.gauge("fleet_cache_rows",
                             "embedding rows currently cached")
        self._capacity = r.gauge("fleet_cache_capacity_rows",
                                 "embedding cache row capacity")
        self._capacity.set(self.capacity_rows)
        self._hits_total = r.counter("fleet_cache_hits_total",
                                     "cached rows served")
        self._misses_total = r.counter("fleet_cache_misses_total",
                                       "rows that had to be dispatched")
        self._label_lock = threading.Lock()
        self._by_bucket: dict[tuple[str, str], object] = {}
        self._evictions: dict[str, object] = {}

    # -- labeling ---------------------------------------------------------
    def _bucket_label(self, rows: int) -> str:
        for b in self.buckets:
            if rows <= b:
                return str(b)
        return f">{self.buckets[-1]}"

    def _bucket_counter(self, kind: str, rows: int):
        label = self._bucket_label(rows)
        with self._label_lock:
            counter = self._by_bucket.get((kind, label))
            if counter is None:
                counter = self._by_bucket[(kind, label)] = \
                    self.registry.counter(
                        f"fleet_cache_{kind}_total",
                        f"cached-row {kind} by request-size bucket",
                        labels={"bucket": label})
        return counter

    def _eviction_counter(self, reason: str):
        with self._label_lock:
            counter = self._evictions.get(reason)
            if counter is None:
                counter = self._evictions[reason] = self.registry.counter(
                    "fleet_cache_evictions_total",
                    "entries dropped from the embedding cache",
                    labels={"reason": reason})
        return counter

    # -- core -------------------------------------------------------------
    def lookup(self, rows: np.ndarray) -> tuple[dict[int, np.ndarray],
                                                list[int]]:
        """Split a request into cached and to-dispatch rows.

        Returns ``(hits, miss_indices)``: ``hits`` maps row index ->
        cached embedding; ``miss_indices`` lists the rows (in request
        order) that must be forwarded. An expired entry counts as a
        miss and is evicted (reason ``ttl``) — the subsequent insert of
        the fresh result re-populates it.
        """
        now = self.clock()
        hits: dict[int, np.ndarray] = {}
        misses: list[int] = []
        # Hash outside the lock: SHA-1 over row bytes is the expensive
        # part (hundreds of KB per row at real image sizes) and needs
        # no shared state — holding the lock for it would serialize
        # every handler thread on one request's hashing.
        keys = [row_key(rows[i]) for i in range(rows.shape[0])]
        fresh_hot: list[int] = []
        with self._lock:
            for i, key in enumerate(keys):
                entry = self._entries.get(key)
                if entry is None:
                    misses.append(i)
                    continue
                value, expires_at = entry
                if now >= expires_at:
                    del self._entries[key]
                    self._eviction_counter("ttl").inc()
                    misses.append(i)
                    continue
                self._entries.move_to_end(key)
                hits[i] = value
                if key in self._hot:
                    self._hot.move_to_end(key)
                else:
                    fresh_hot.append(i)
            self._size.set(len(self._entries))
        if fresh_hot and self.hot_rows > 0:
            # Copy outside the lock (same rule as hashing), insert
            # under it; first-hit keys only, so steady repeat traffic
            # costs a move_to_end, not a memcpy.
            copies = [(keys[i], np.array(rows[i])) for i in fresh_hot]
            with self._lock:
                for key, row in copies:
                    self._hot[key] = row
                    self._hot.move_to_end(key)
                while len(self._hot) > self.hot_rows:
                    self._hot.popitem(last=False)
        n = int(rows.shape[0])
        if hits:
            self._hits_total.inc(len(hits))
            self._bucket_counter("hits", n).inc(len(hits))
        if misses:
            self._misses_total.inc(len(misses))
            self._bucket_counter("misses", n).inc(len(misses))
        return hits, misses

    def insert(self, rows: np.ndarray, embeddings: np.ndarray) -> None:
        """Cache ``embeddings[i]`` under ``rows[i]``'s content hash."""
        if rows.shape[0] != embeddings.shape[0]:
            raise ValueError(f"rows/embeddings mismatch: {rows.shape[0]} "
                             f"vs {embeddings.shape[0]}")
        expires_at = self.clock() + self.ttl_s
        # Hash + copy outside the lock (see lookup). The per-row copy
        # matters twice over: embeddings[i] is a VIEW into the worker's
        # whole response batch — caching the view would pin every row's
        # base array for the lifetime of one entry.
        keys = [row_key(rows[i]) for i in range(rows.shape[0])]
        values = [np.array(embeddings[i], dtype=np.float32)
                  for i in range(rows.shape[0])]
        with self._lock:
            for key, value in zip(keys, values):
                if key in self._entries:
                    self._entries.move_to_end(key)
                self._entries[key] = (value, expires_at)
            while len(self._entries) > self.capacity_rows:
                self._entries.popitem(last=False)
                self._eviction_counter("lru").inc()
            self._size.set(len(self._entries))

    def clear(self, reason: str = "flush") -> int:
        """Drop everything (the rollout hook); returns entries dropped."""
        with self._lock:
            n = len(self._entries)
            self._entries.clear()
            self._generation += 1
            self._size.set(0)
        if n:
            self._eviction_counter(reason).inc(n)
        return n

    def hot_keys(self, n: int) -> list[np.ndarray]:
        """The hottest cached INPUT rows, most-recently-hit first.

        Returns up to ``n`` row arrays (private copies) from the
        bounded hot store — the replay set for cache warming on a
        canary promote: the router re-forwards them through the newly
        trusted model right after the flush, so the hottest traffic
        never sees a cold cache. Survives ``clear()`` by design
        (inputs carry no model state).
        """
        if n < 1:
            return []
        with self._lock:
            rows = list(self._hot.values())[-int(n):]
        return list(reversed(rows))

    @property
    def generation(self) -> int:
        """Flush epoch: changes exactly when clear() runs. Capture it
        before lookup(); a change by merge time means the hits belong
        to a model the router no longer serves."""
        with self._lock:
            return self._generation

    # -- readers ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    @property
    def hits(self) -> int:
        return int(self._hits_total.value)

    @property
    def misses(self) -> int:
        return int(self._misses_total.value)

    def hit_rate(self) -> float | None:
        total = self.hits + self.misses
        return self.hits / total if total else None

    def snapshot(self) -> dict:
        """The JSON wire shape the router's /metrics embeds."""
        with self._label_lock:
            evictions = {reason: int(c.value)
                         for reason, c in sorted(self._evictions.items())}
        with self._lock:
            hot = len(self._hot)
        return {
            "rows": len(self),
            "capacity_rows": self.capacity_rows,
            "ttl_s": self.ttl_s,
            "hot_rows": hot,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": round(self.hit_rate(), 4)
            if self.hit_rate() is not None else None,
            "evictions": evictions,
        }
