"""Shadow routing: mirror trusted traffic to the canary, diff embeddings.

The canary machinery (ISSUE 8) judges a new checkpoint by ERROR RATE —
a model that answers 200 with subtly wrong embeddings promotes cleanly.
This module closes that hole (ISSUE 10 / ROADMAP item 4's last open
follow-up): while a canary is undecided, a configured fraction of
TRUSTED-cohort requests is mirrored to a canary-step worker OFF the
client's critical path, the two embedding sets are diffed per row
(cosine distance), and the drift distribution feeds the same verdict
the error rate does — promote now requires drift-p99 under
``--shadow-max-drift`` on top of the error-rate bar, and a drift
breach rolls the fleet back exactly like an error breach.

Why mirroring (vs just routing more canary traffic): the mirrored
request has a KNOWN-GOOD answer to compare against — the trusted
response the client already received. Live canary traffic can only be
judged pass/fail; mirrored traffic is judged numerically. And because
the mirror rides a background queue, the client pays nothing: a slow
or crashing canary shows up in drift/error accounting, never in
client latency.

Mechanics:

* the router calls ``offer()`` after every successful trusted-cohort
  response (body + request id + the embeddings it just returned);
* ``offer`` applies the fraction (every Nth eligible request) and a
  bounded queue — overflow drops the OLDEST offer and counts it
  (telemetry backpressure must shed telemetry, never requests);
* one daemon worker drains the queue: pick a ready canary-step worker,
  POST the identical body (``X-Shadow-Of`` names the mirrored request
  so worker logs can tell mirrors from client traffic), diff, publish
  ``fleet_shadow_drift`` + a ``fleet.shadow`` span carrying the
  per-request drift, and report BOTH signals into the pool's verdict
  (drift samples via ``observe_drift``, outcome via ``observe``);
* verdict side effects (promote/rollback) are handed back to the
  router's ``on_decision`` — the same path a live canary outcome takes.

JAX-free (router-process rule); numpy only for the row math.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import deque

import numpy as np

from ..obs import trace as _trace

logger = logging.getLogger(__name__)

__all__ = ["cosine_drift", "ShadowMirror"]


def cosine_drift(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Per-row cosine distance ``1 - cos(a_i, b_i)`` of two equally
    shaped embedding batches, in [0, 2]. Zero-norm rows (a degenerate
    model output) diff at the maximum distance rather than NaN — a
    collapsed canary must look MAXIMALLY drifted, not unmeasurable."""
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    if a.shape != b.shape:
        raise ValueError(f"shape mismatch: {a.shape} vs {b.shape}")
    na = np.linalg.norm(a, axis=-1)
    nb = np.linalg.norm(b, axis=-1)
    denom = na * nb
    cos = np.zeros(a.shape[0], np.float32)
    ok = denom > 0
    cos[ok] = np.einsum("ij,ij->i", a[ok], b[ok]) / denom[ok]
    cos[~ok] = -1.0
    return np.clip(1.0 - cos, 0.0, 2.0)


class ShadowMirror:
    """Mirror a fraction of trusted traffic to the undecided canary.

    ``pool`` is the router's ``WorkerPool`` (canary state + drift
    accounting live there — the verdict must be one state machine, not
    two); ``on_decision`` receives any promote/rollback verdict a
    mirrored outcome triggers (the router passes its
    ``_handle_decision``).
    """

    def __init__(self, pool, fraction: float = 0.1,
                 forward_timeout_s: float = 30.0,
                 queue_max: int = 64,
                 on_decision=None):
        if not 0.0 < fraction <= 1.0:
            raise ValueError(f"shadow fraction must be in (0, 1], got "
                             f"{fraction}")
        self.pool = pool
        self.fraction = float(fraction)
        self.forward_timeout_s = float(forward_timeout_s)
        self.queue_max = int(queue_max)
        self.on_decision = on_decision
        r = pool.registry
        self.drift = r.histogram(
            "fleet_shadow_drift",
            "per-row cosine distance between trusted and canary "
            "embeddings for mirrored requests")
        self._mirrored = r.counter(
            "fleet_shadow_mirrored_total",
            "requests mirrored to a canary-step worker")
        self._errors = r.counter(
            "fleet_shadow_errors_total",
            "mirrored requests the canary failed to answer")
        self._dropped = r.counter(
            "fleet_shadow_dropped_total",
            "mirror offers shed (queue full / no canary worker ready)")
        self._drift_p99 = r.gauge(
            "fleet_shadow_drift_p99",
            "rolling drift p99 over the histogram window")
        self._rr = 0
        self._lock = threading.Lock()
        self._queue: deque[tuple] = deque()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- producer side (request threads) -----------------------------------
    def offer(self, body: bytes, rid: str, served_step: int | None,
              embeddings) -> bool:
        """Called by the router after a successful forward. Enqueues a
        mirror when (a) a canary is undecided, (b) THIS response came
        from the trusted cohort (a canary-served response has nothing
        trusted to diff against), and (c) the fraction counter elects
        it. Returns True when enqueued. Never blocks."""
        step = self.pool.canary_step()
        if step is None:
            return False
        trusted = self.pool.trusted_step
        if trusted is None or served_step != trusted:
            return False
        if embeddings is None:
            return False
        with self._lock:
            self._rr += 1
            period = max(1, round(1.0 / self.fraction))
            if self._rr % period != 0:
                return False
            if len(self._queue) >= self.queue_max:
                self._queue.popleft()
                self._dropped.inc()
            self._queue.append((body, rid, step, embeddings))
        self._wake.set()
        return True

    # -- consumer side (the mirror thread) ---------------------------------
    def _mirror_one(self, body: bytes, rid: str, step: int,
                    primary) -> None:
        entry = self.pool.pick_step(step)
        if entry is None:
            self._dropped.inc()
            return
        t0 = time.monotonic()
        drift_max = drift_mean = None
        ok = False
        status = 0
        try:
            req = urllib.request.Request(
                entry.url + "/embed", data=body, method="POST",
                headers={"Content-Type": "application/json",
                         "X-Request-Id": _trace.new_request_id(),
                         "X-Shadow-Of": rid})
            with urllib.request.urlopen(
                    req, timeout=self.forward_timeout_s) as resp:
                status = resp.status
                payload = json.loads(resp.read())
            shadow = np.asarray(payload["embeddings"], np.float32)
            primary = np.asarray(primary, np.float32)
            if shadow.shape != primary.shape:
                raise ValueError(f"row mismatch: {shadow.shape} vs "
                                 f"{primary.shape}")
            drifts = cosine_drift(primary, shadow)
            for d in drifts:
                self.drift.observe(float(d))
            pcts = self.drift.percentiles()
            if pcts:
                self._drift_p99.set(pcts.get(0.99, 0.0))
            drift_max = float(drifts.max())
            drift_mean = float(drifts.mean())
            ok = True
        except urllib.error.HTTPError as e:
            e.read()
            status = e.code
            if e.code in (429, 504):
                # Saturation/deadline on the MIRROR is not model
                # quality — drop this sample, feed nothing.
                self._dropped.inc()
                return
            self._errors.inc()
        except (urllib.error.URLError, OSError, ValueError, KeyError,
                TypeError) as e:
            status = -1
            logger.debug("shadow mirror of %s failed: %r", rid, e)
            self._errors.inc()
        finally:
            self.pool.done(entry.worker_id)
        self._mirrored.inc()
        decision = None
        if ok:
            decision = self.pool.observe_drift(
                step, [float(d) for d in drifts])
            if decision is None:
                decision = self.pool.observe(entry.worker_id, step,
                                             ok=True)
        else:
            # A canary that cannot answer its mirror is error-rate
            # evidence, same as a failed live forward.
            self.pool.report_failure(entry.worker_id,
                                     f"shadow http {status}")
            decision = self.pool.observe(entry.worker_id, step,
                                         ok=False)
        _trace.emit_span("fleet.shadow",
                         (time.monotonic() - t0) * 1e3,
                         request_id=rid, worker=entry.worker_id,
                         step=step, status=status, ok=ok,
                         drift=drift_max, drift_mean=drift_mean)
        if decision is not None and self.on_decision is not None:
            self.on_decision(decision)

    def _run(self) -> None:
        while True:
            self._wake.wait(0.2)
            self._wake.clear()
            while True:
                with self._lock:
                    if not self._queue:
                        break
                    body, rid, step, primary = self._queue.popleft()
                try:
                    self._mirror_one(body, rid, step, primary)
                except Exception:  # noqa: BLE001 — the mirror must
                    # never die to one bad sample.
                    logger.exception("shadow mirror failed")
            if self._stop.is_set():
                return

    # -- readers / lifecycle -----------------------------------------------
    def snapshot(self) -> dict:
        with self._lock:
            depth = len(self._queue)
        return {"fraction": self.fraction,
                "mirrored": int(self._mirrored.value),
                "errors": int(self._errors.value),
                "dropped": int(self._dropped.value),
                "queue_depth": depth,
                "drift": self.drift.snapshot()}

    def start(self) -> "ShadowMirror":
        if self._thread is not None:
            raise RuntimeError("shadow mirror already started")
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-shadow-mirror")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
