"""Fault-tolerant router tier: many workers, one front door.

One ``EmbeddingServer`` is a single point of failure, a single queue,
and a restart-equals-outage deployment model. ``FleetRouter`` is the
stdlib-HTTP tier that fixes all three (ISSUE 8 / ROADMAP item 4): it
spreads ``/embed`` load over N worker replicas, retries failed
forwards on the surviving workers, sheds load with the existing 429 +
Retry-After semantics when every worker is saturated, serves repeated
rows from the ``EmbeddingCache`` without any worker seeing them, and
canaries new-checkpoint workers at a configurable traffic fraction
with automatic rollback on an error-rate breach.

Failure semantics per forwarded request (the per-request retry budget
that turns a worker SIGKILL into zero client-visible 5xx):

* connection errors and worker 5xx count against the worker
  (``WorkerPool.report_failure`` — the fleet supervisor ejects after
  consecutive failures) and the request retries on a DIFFERENT worker,
  up to ``retries`` extra attempts;
* a worker 429 is saturation, not failure: the router tries another
  worker, and only when every attempted worker is saturated does the
  client see a 429 carrying the largest Retry-After observed;
* worker 4xx (bad request, 413, 504) is the CLIENT's problem and
  passes through verbatim on the first occurrence — retrying a 400 on
  another replica would just fail twice;
* budget exhausted on 5xx: the client receives the WORKER's status
  code and error body (never a synthetic router error that hides the
  cause); with no ready workers at all the answer is an immediate 503,
  never a hang.

Canary state machine (one rollout at a time, owned by the pool lock):

  ``trusted`` — all ready workers serve the trusted step: plain
  least-in-flight routing.
  ``canarying`` — some ready worker reports a step newer than the
  trusted one (the staggered watcher put it there): the router routes
  ``canary_fraction`` of requests to the new-step cohort and counts
  outcomes. 429s are neutral (saturation says nothing about the
  model).
  promote — at ``canary_min_requests`` outcomes with error rate <=
  ``canary_max_error_rate`` the new step becomes trusted (and the
  cache flushes: embeddings from the old model must not outlive it).
  rollback — on breach the step is marked bad, every worker serving
  it gets ``POST /rollback`` (worker.py reverts and blocklists), and
  routing is old-cohort-only again — "canary rollback restores
  old-checkpoint routing".

Request identity: the router mints ``X-Request-Id`` at its edge and
forwards it, so one id threads cache -> route -> worker queue ->
device chunk in the exported trace; a cache hit emits a ``fleet.cache``
slice under the same id — a cached answer explains itself instead of
looking like a mysteriously fast worker.

Retrieval surface (ISSUE 15, ``attach_index``): ``POST /search``
embeds the query rows through the fleet and answers top-k ids+scores
from the checkpoint-step-versioned ANN index (``ntxent_tpu/retrieval``)
— the version MATCHING the step that embedded the query, so a rollout
window's laggard-served queries search the space they were embedded
in. ``POST /embed?store=true`` and ``POST /index/insert`` feed the
index, trust-gated exactly like cache inserts (a canary model's
vectors must not survive its own rollback). The rollout state machine
drives index versions: promote cuts searches to the new step's index
and rebuilds it by background re-embedding, a fleet-wide rollback
(every ready worker reverting below the trusted step) demotes the
trusted step AND restores the prior index version, and a drift-reason
canary breach marks the live index stale, forcing a rebuild.

Admission control (ISSUE 16, ``TenantAdmission``): per-tenant token
buckets keyed on the ``X-Tenant`` request header (bare requests share
the default tenant) meter ``/embed`` AND ``/search`` by row count, so
saturation degrades per tenant (the over-quota tenant 429s, everyone
else keeps their rate) instead of FIFO. Exhaustion answers 429 +
``Retry-After`` — the same shed contract the worker queue uses — and
the ``tenant`` label is cardinality-bounded router-side: at most
``max_tenants`` tracked values, the rest melt into ``other``.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

from ..obs import events as _events
from ..obs import trace as _trace
from ..obs.exporters import PROMETHEUS_CONTENT_TYPE, choose_format
from ..obs.registry import MetricsRegistry
from ..obs.slo import AlertStore
from .cache import EmbeddingCache
from .limits import MAX_BODY_BYTES

logger = logging.getLogger(__name__)

__all__ = ["WorkerEntry", "WorkerPool", "FleetRouter", "TokenBucket",
           "TenantAdmission"]


def _step_header(headers) -> int | None:
    """Parse the worker's ``X-Checkpoint-Step`` response label. The
    worker stamps it at reply time, so it names the model that ACTUALLY
    served — the pool's health-probe view lags a hot swap by up to a
    poll interval, and cache/canary accounting must not mislabel that
    window's responses."""
    raw = headers.get("X-Checkpoint-Step") if headers is not None else None
    if raw is None:
        return None
    try:
        return int(raw)
    except (TypeError, ValueError):
        return None


class TokenBucket:
    """One tenant's admission budget: ``rate`` tokens/s refill toward a
    ``burst`` cap (monotonic clock; float tokens so fractional rates
    work). ``try_take`` is the whole API — atomic under the owner's
    lock (``TenantAdmission`` serializes callers; a bare bucket in
    tests is single-threaded)."""

    def __init__(self, rate: float, burst: float | None = None):
        if rate <= 0.0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        # Default burst = 1 second of rate (and never below one token,
        # or a sub-1/s quota could not admit ANY request).
        self.burst = max(1.0, float(burst if burst is not None else rate))
        self.tokens = self.burst
        self._stamp = time.monotonic()

    def try_take(self, cost: float = 1.0,
                 now: float | None = None) -> tuple[bool, float]:
        """Spend ``cost`` tokens if available. Returns ``(admitted,
        retry_after_s)`` — the wait is 0.0 on admit, else the refill
        time until ``cost`` tokens would exist. (A cost past the burst
        cap can never be admitted by waiting; the uncapped hint is
        still monotone and nonzero, which beats advertising an instant
        retry that will 429 forever.)"""
        now = time.monotonic() if now is None else now
        elapsed = max(0.0, now - self._stamp)
        self.tokens = min(self.burst, self.tokens + elapsed * self.rate)
        self._stamp = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True, 0.0
        return False, (cost - self.tokens) / self.rate


class TenantAdmission:
    """Per-tenant token-bucket quotas over the router's request paths.

    ``quotas`` pins named tenants to explicit ``(rate, burst)``; any
    other tenant gets the default quota, lazily. Cardinality is
    bounded HERE, not at the scrape: clients pick their own
    ``X-Tenant`` values, so past ``max_tenants`` distinct names every
    new tenant shares one ``"other"`` bucket and label value — an
    adversarial header can neither explode the registry nor mint
    itself a fresh budget per request.
    """

    OTHER = "other"

    def __init__(self, default_rate: float = 100.0,
                 default_burst: float | None = None,
                 quotas: dict[str, tuple[float, float | None]]
                 | None = None,
                 registry: MetricsRegistry | None = None,
                 max_tenants: int = 32,
                 default_tenant: str = "default"):
        self.default_rate = float(default_rate)
        self.default_burst = default_burst
        self.default_tenant = str(default_tenant)
        self.max_tenants = int(max_tenants)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._lock = threading.Lock()
        self._buckets: dict[str, TokenBucket] = {}
        self._counters: dict[tuple[str, str], object] = {}
        self._pinned = set()
        for name, (rate, burst) in sorted((quotas or {}).items()):
            self._buckets[str(name)] = TokenBucket(rate, burst)
            self._pinned.add(str(name))

    def _normalize(self, tenant: str | None) -> str:
        tenant = (tenant or "").strip()
        if not tenant:
            return self.default_tenant
        # Exposition-legal label value, bounded length: the header is
        # attacker-controlled wire input.
        tenant = "".join(c if c.isalnum() or c in "-_.:" else "_"
                         for c in tenant[:64])
        return tenant or self.default_tenant

    def _bucket_locked(self, tenant: str) -> tuple[str, TokenBucket]:
        bucket = self._buckets.get(tenant)
        if bucket is not None:
            return tenant, bucket
        if len(self._buckets) >= self.max_tenants:
            bucket = self._buckets.get(self.OTHER)
            if bucket is None:
                bucket = self._buckets[self.OTHER] = TokenBucket(
                    self.default_rate, self.default_burst)
            return self.OTHER, bucket
        bucket = self._buckets[tenant] = TokenBucket(
            self.default_rate, self.default_burst)
        return tenant, bucket

    def _count_locked(self, outcome: str, tenant: str) -> None:
        counter = self._counters.get((outcome, tenant))
        if counter is None:
            name = f"tenant_{outcome}_total"
            counter = self._counters[(outcome, tenant)] = \
                self.registry.counter(
                    name, f"requests {outcome} by the per-tenant "
                          "admission buckets",
                    labels={"tenant": tenant})
        counter.inc()

    def admit(self, tenant: str | None,
              cost: float = 1.0,
              now: float | None = None) -> tuple[bool, float]:
        """Meter one request of ``cost`` rows for ``tenant``. Returns
        ``(admitted, retry_after_s)`` and counts the outcome under the
        (bounded) tenant label."""
        name = self._normalize(tenant)
        with self._lock:
            name, bucket = self._bucket_locked(name)
            ok, retry_after = bucket.try_take(cost, now=now)
            self._count_locked("admitted" if ok else "rejected", name)
        return ok, retry_after

    def snapshot(self) -> dict:
        """Tenant -> remaining tokens (observability surface; the
        authoritative counters live in the registry)."""
        with self._lock:
            return {name: round(b.tokens, 3)
                    for name, b in sorted(self._buckets.items())}


class WorkerEntry:
    """One worker replica as the router sees it (mutated under the
    pool's lock; plain attributes — this is a record, not an actor)."""

    def __init__(self, worker_id: str, url: str):
        self.worker_id = worker_id
        self.url = url.rstrip("/")
        self.alive = False
        self.ready = False
        # Draining (ISSUE 16): still alive and probing healthy, but the
        # autoscaler has marked it for retirement — selection skips it,
        # its in-flight requests complete, and the controller SIGTERMs
        # only once inflight hits zero (or the drain deadline passes).
        self.draining = False
        self.checkpoint_step: int | None = None
        self.inflight = 0
        self.consecutive_failures = 0
        # What produced the latest failure ("probe" | "forward"): a
        # healthy /readyz probe is evidence against a PROBE-failure
        # streak only — it says nothing about /embed, so it must not
        # wipe router-reported forward failures before the fleet's
        # eject check ever sees them.
        self.last_failure_kind: str | None = None
        self.last_error: str | None = None

    def snapshot(self) -> dict:
        return {"url": self.url, "alive": self.alive, "ready": self.ready,
                "draining": self.draining,
                "checkpoint_step": self.checkpoint_step,
                "inflight": self.inflight,
                "consecutive_failures": self.consecutive_failures,
                "last_error": self.last_error}


class WorkerPool:
    """Thread-safe worker table + selection + canary state machine.

    The fleet supervisor (fleet.py) writes membership and health; the
    router reads selections and reports per-request outcomes. Both the
    router's forward failures and the supervisor's health-probe
    failures land in ``consecutive_failures`` — one ejection signal,
    two observers. Resets are evidence-matched: a successful forward
    clears the counter outright, while a passing /readyz probe clears
    it only when the streak is probe-originated (a listening worker
    that 500s every /embed must still reach the eject threshold).
    """

    def __init__(self, canary_fraction: float = 0.25,
                 canary_min_requests: int = 20,
                 canary_max_error_rate: float = 0.1,
                 shadow_max_drift: float | None = None,
                 shadow_min_samples: int = 8,
                 registry: MetricsRegistry | None = None):
        if not 0.0 < canary_fraction <= 1.0:
            raise ValueError(f"canary_fraction must be in (0, 1], got "
                             f"{canary_fraction}")
        self.canary_fraction = float(canary_fraction)
        self.canary_min_requests = int(canary_min_requests)
        self.canary_max_error_rate = float(canary_max_error_rate)
        # Shadow drift gate (ISSUE 10): when set, a canary may only
        # promote once its mirrored-traffic drift p99 is at or under
        # this bound (see serving/shadow.py); a breach rolls back even
        # with a clean error rate.
        self.shadow_max_drift = (float(shadow_max_drift)
                                 if shadow_max_drift is not None
                                 else None)
        self.shadow_min_samples = int(shadow_min_samples)
        self.registry = registry if registry is not None \
            else MetricsRegistry()
        self._lock = threading.Lock()
        self._workers: dict[str, WorkerEntry] = {}
        self.trusted_step: int | None = None
        # Fired (outside the lock) when the FIRST checkpoint step is
        # adopted as trusted via set_health — there was no canary to
        # decide, so this is the router's only signal to flush
        # random-init-weight embeddings out of its cache.
        self.on_trusted_adopt = None
        # Fired (outside the lock, as (new_step, old_step)) when the
        # trusted step DEMOTES: every ready worker reports a step older
        # than the trusted one — the fleet was force-rolled-back
        # beneath the router (operator /rollback broadcast, checkpoint
        # dir rewound). Without demotion the router would gate cache/
        # index inserts against a step nobody serves forever; with it
        # the cache flushes and the retrieval tier restores the prior
        # index version (ISSUE 15).
        self.on_trusted_rollback = None
        self.bad_steps: set[int] = set()
        self._canary_step: int | None = None
        self._canary_ok = 0
        self._canary_err = 0
        self._canary_drift: list[float] = []
        # What the last promote/rollback verdict was based on — the
        # router's alert path reads this right after observe()/
        # observe_drift() returns a decision (the decision tuple
        # itself stays (action, step): existing consumers unpack it).
        self.last_verdict: dict = {}
        self._rr = 0  # request counter driving the canary fraction
        r = self.registry
        self._ready_gauge = r.gauge("fleet_workers_ready",
                                    "workers passing /readyz")
        self._alive_gauge = r.gauge("fleet_workers_alive",
                                    "workers with a live process")
        self._trusted_gauge = r.gauge(
            "fleet_trusted_step",
            "checkpoint step the router currently trusts "
            "(-1 = none yet)")
        self._trusted_gauge.set(-1)
        self._canary_requests = r.counter(
            "fleet_canary_requests_total",
            "requests routed to a canary-step worker")
        self._canary_errors = r.counter(
            "fleet_canary_errors_total",
            "canary-routed requests that failed (5xx/unreachable)")
        self._promotions = r.counter(
            "fleet_promotions_total",
            "canary steps promoted to trusted")
        self._rollbacks = r.counter(
            "fleet_rollbacks_total",
            "canary steps rolled back on error-rate breach")
        self._shadow_breaches = r.counter(
            "fleet_shadow_breaches_total",
            "canary rollbacks forced by the drift bar "
            "(error rate alone would have promoted)")
        self._demotions = r.counter(
            "fleet_trusted_demotions_total",
            "trusted-step demotions (every ready worker reverted "
            "below the trusted step — a fleet-wide rollback)")

    # -- membership / health (the fleet supervisor's surface) -------------
    def upsert(self, worker_id: str, url: str) -> WorkerEntry:
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None or entry.url != url.rstrip("/"):
                prior = entry
                entry = WorkerEntry(worker_id, url)
                if prior is not None:
                    # A restarted incarnation (new port) inherits the
                    # dead one's last-reported step until its first
                    # probe overwrites it: the entry keeps pinning the
                    # trusted step through the restart window, so a
                    # lone crash can never read as a fleet-wide
                    # rollback (_maybe_demote_locked). Routing is
                    # unaffected — the entry starts not-ready.
                    entry.checkpoint_step = prior.checkpoint_step
                self._workers[worker_id] = entry
            self._update_gauges()
            return entry

    def remove(self, worker_id: str) -> None:
        with self._lock:
            self._workers.pop(worker_id, None)
            self._update_gauges()

    def set_health(self, worker_id: str, alive: bool, ready: bool,
                   checkpoint_step: int | None = None) -> None:
        adopted: int | None = None
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None:
                return
            entry.alive = alive
            entry.ready = ready and alive
            if checkpoint_step is not None:
                entry.checkpoint_step = int(checkpoint_step)
            if ready and alive \
                    and entry.last_failure_kind != "forward":
                # A passing probe closes a probe-failure streak. It is
                # NOT evidence that /embed works — a worker 500ing
                # every forward while answering /readyz 200 must still
                # accumulate toward ejection (only a successful forward
                # resets that streak).
                entry.consecutive_failures = 0
                entry.last_failure_kind = None
            if self.trusted_step is None \
                    and entry.checkpoint_step is not None:
                # First observed version becomes the trusted baseline —
                # there is nothing to canary against before it.
                self.trusted_step = adopted = entry.checkpoint_step
                self._trusted_gauge.set(self.trusted_step)
            demoted = self._maybe_demote_locked()
            self._update_gauges()
        if demoted is not None and self.on_trusted_rollback is not None:
            # Outside the lock for the same reason as the adopt hook:
            # the router flushes its cache and rolls the retrieval
            # index back to the restored step's version.
            new_step, old_step = demoted
            try:
                self.on_trusted_rollback(new_step, old_step)
            except Exception:  # noqa: BLE001 — a hook failure must not
                # poison health reporting.
                logger.exception("on_trusted_rollback hook failed")
        if adopted is not None and self.on_trusted_adopt is not None:
            # Outside the lock: the hook flushes the router's cache
            # (which takes its own lock) — any embeddings cached while
            # workers served random init must not outlive the first
            # real model.
            try:
                self.on_trusted_adopt(adopted)
            except Exception:  # noqa: BLE001 — a hook failure must not
                # poison health reporting.
                logger.exception("on_trusted_adopt hook failed")

    def _maybe_demote_locked(self) -> tuple[int, int] | None:
        """Detect a fleet-wide rollback (lock held): every KNOWN
        worker step is strictly older than the trusted one (with at
        least one worker ready), and no canary verdict is pending (an
        armed canary IS a worker at a newer step, so the two states
        cannot overlap). Demotes trusted to the newest step actually
        served and returns ``(new_step, old_step)``; None when nothing
        changed.

        Judging every entry's LAST-REPORTED step — not just live
        workers' — is what makes both failure windows safe: a
        warming/draining trusted-step worker still reports its step
        and pins trusted (the stagger window), and so does the ENTRY
        of a crashed trusted-step worker mid-restart (its step
        survives the death; a lone crash during a rollout must not
        read as an operator rollback). A genuine fleet-wide rollback
        updates every entry's reported step as the reverted workers
        answer /readyz. The cost is the conservative direction: a
        trusted-step worker that dies FOREVER (restart budget
        exhausted) pins trusted until its entry is removed — searches
        still answer (version-matched) and inserts stay gated, which
        beats spuriously flushing the cache and rolling the index
        back on a crash."""
        if self.trusted_step is None or self._canary_step is not None:
            return None
        known_steps = [w.checkpoint_step for w in self._workers.values()
                       if w.checkpoint_step is not None]
        ready_steps = [w.checkpoint_step for w in self._workers.values()
                       if w.ready and w.checkpoint_step is not None]
        if not ready_steps or not known_steps \
                or any(s >= self.trusted_step for s in known_steps):
            return None
        old = self.trusted_step
        self.trusted_step = max(ready_steps)
        self._trusted_gauge.set(self.trusted_step)
        self._demotions.inc()
        logger.warning("fleet rolled back beneath the router: trusted "
                       "step %d -> %d (every live worker reverted)",
                       old, self.trusted_step)
        return (self.trusted_step, old)

    def report_failure(self, worker_id: str, error: str = "",
                       kind: str = "forward") -> int:
        """A failed forward or health probe (``kind``: "forward" |
        "probe"); returns the consecutive count (the fleet ejects past
        its threshold)."""
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None:
                return 0
            entry.consecutive_failures += 1
            entry.last_failure_kind = kind
            entry.last_error = error or entry.last_error
            return entry.consecutive_failures

    def report_success(self, worker_id: str) -> None:
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is not None:
                entry.consecutive_failures = 0
                entry.last_failure_kind = None
                entry.last_error = None

    def clear_failures(self, worker_id: str) -> None:
        """Reset the consecutive-failure count but KEEP last_error (the
        post-mortem). The fleet calls this when it schedules a restart:
        the failures belonged to the dead incarnation, and carrying
        them over would insta-eject the replacement while it is still
        booting — before it can even publish its port."""
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is not None:
                entry.consecutive_failures = 0
                entry.last_failure_kind = None

    def _update_gauges(self) -> None:
        self._ready_gauge.set(sum(1 for w in self._workers.values()
                                  if w.ready))
        self._alive_gauge.set(sum(1 for w in self._workers.values()
                                  if w.alive))

    # -- drain-down (the autoscaler's surface, ISSUE 16) -------------------
    def set_draining(self, worker_id: str, draining: bool = True) -> bool:
        """Mark/unmark a worker draining (no new routes; in-flight
        completes). Returns False when the worker is unknown."""
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is None:
                return False
            entry.draining = bool(draining)
            return True

    def inflight_of(self, worker_id: str) -> int:
        """In-flight request count for one worker (0 when unknown) —
        the drain state machine's completion signal."""
        with self._lock:
            entry = self._workers.get(worker_id)
            return entry.inflight if entry is not None else 0

    def routable_count(self) -> int:
        """Ready, non-draining workers — the pool size the autoscaler
        reasons about (a draining victim no longer carries load)."""
        with self._lock:
            return sum(1 for w in self._workers.values()
                       if w.ready and not w.draining)

    # -- selection ---------------------------------------------------------
    def _is_canary(self, entry: WorkerEntry) -> bool:
        return (self.trusted_step is not None
                and entry.checkpoint_step is not None
                and entry.checkpoint_step > self.trusted_step
                and entry.checkpoint_step not in self.bad_steps)

    def pick(self, exclude: set[str] | None = None) -> WorkerEntry | None:
        """Least-in-flight selection with canary fractioning; None when
        no ready worker remains (the router's immediate-503 case).
        Increments the chosen worker's inflight (caller must ``done``).
        """
        exclude = exclude or set()
        with self._lock:
            # A draining worker is invisible to selection AND to canary
            # arming: it keeps probing ready (so the fleet does not
            # eject it mid-drain) while its in-flight requests finish,
            # but it must receive zero NEW routes — that is the whole
            # zero-5xx scale-down contract (serving/autoscale.py).
            all_ready = [w for w in self._workers.values()
                         if w.ready and not w.draining]
            ready = [w for w in all_ready
                     if w.worker_id not in exclude]
            if not ready:
                return None
            # Canary ARMING considers every ready worker: a failover
            # retry that excludes the canary (its 5xx is exactly the
            # evidence being counted) must not reset the breach
            # accounting mid-verdict.
            armed = [w for w in all_ready if self._is_canary(w)]
            if armed:
                # One rollout at a time: canary the NEWEST new step.
                newest = max(w.checkpoint_step for w in armed)
                if self._canary_step != newest:
                    self._canary_step = newest
                    self._canary_ok = self._canary_err = 0
                    self._canary_drift = []
            else:
                self._canary_step = None
            canaries = [w for w in ready
                        if self._is_canary(w)
                        and w.checkpoint_step == self._canary_step]
            bad = [w for w in ready
                   if w.checkpoint_step in self.bad_steps]
            old = [w for w in ready if not self._is_canary(w)
                   and w not in bad]
            if canaries and old:
                self._rr += 1
                period = max(1, round(1.0 / self.canary_fraction))
                cohort = canaries if self._rr % period == 0 else old
            elif canaries:
                cohort = canaries  # nothing older is ready
            else:
                # A bad-step worker beats a 503; ``ready`` itself is the
                # last resort (every selectable worker is a non-newest
                # canary — traffic must still flow).
                cohort = old or bad or ready
            entry = min(cohort, key=lambda w: (w.inflight, w.worker_id))
            entry.inflight += 1
            return entry

    def done(self, worker_id: str) -> None:
        with self._lock:
            entry = self._workers.get(worker_id)
            if entry is not None and entry.inflight > 0:
                entry.inflight -= 1

    def canary_step(self) -> int | None:
        """The undecided canary step, if any (the shadow mirror's
        arming check)."""
        with self._lock:
            return self._canary_step

    def pick_step(self, step: int) -> WorkerEntry | None:
        """Least-in-flight ready worker AT a specific checkpoint step
        (the shadow mirror's canary target selection); None when no
        such worker is ready. Increments inflight (caller must
        ``done``)."""
        with self._lock:
            cohort = [w for w in self._workers.values()
                      if w.ready and not w.draining
                      and w.checkpoint_step == step]
            if not cohort:
                return None
            entry = min(cohort, key=lambda w: (w.inflight, w.worker_id))
            entry.inflight += 1
            return entry

    def allow_cache_insert(self, served_step: int | None) -> bool:
        """Only embeddings from the TRUSTED model may enter the cache:
        no inserts while a canary is undecided (a canary model's
        embeddings must not survive its own rollback), and a response
        from a non-trusted step (a promote/rollback raced the forward)
        must not poison the freshly flushed cache."""
        with self._lock:
            if self._canary_step is not None:
                return False
            if served_step is None or self.trusted_step is None:
                return True
            return served_step == self.trusted_step

    # -- canary accounting -------------------------------------------------
    def _drift_p99_locked(self) -> float | None:
        if not self._canary_drift:
            return None
        from ..obs.registry import quantile

        return quantile(sorted(self._canary_drift), 0.99)

    def _decide_locked(self, promote: bool,
                       verdict: dict) -> tuple[str, int]:
        """Finalize the pending canary (lock held): reset the verdict
        state and apply the decision. ``verdict`` lands in
        ``last_verdict`` for the router's alert path."""
        decided = self._canary_step
        self._canary_step = None
        self._canary_ok = self._canary_err = 0
        self._canary_drift = []
        self.last_verdict = {"step": decided, **verdict}
        if promote:
            self.trusted_step = decided
            self._trusted_gauge.set(decided)
            self._promotions.inc()
            logger.info("canary: promoted step %d (%s)", decided,
                        verdict)
            return ("promote", decided)
        self.bad_steps.add(decided)
        self._rollbacks.inc()
        logger.warning("canary: BREACH on step %d (%s) — rolling back",
                       decided, verdict)
        return ("rollback", decided)

    def observe(self, worker_id: str, step: int | None,
                ok: bool) -> tuple[str, int] | None:
        """Record one forwarded outcome (live canary traffic and
        shadow mirrors alike). Returns ``("promote", step)``,
        ``("rollback", step)``, or None. 429s must NOT be reported here
        (saturation is not model quality).

        With a drift bar configured (``shadow_max_drift``), the
        error-rate bar alone cannot promote: the verdict DEFERS until
        ``shadow_min_samples`` mirrored rows have been diffed (up to a
        cap — a fleet whose mirror produces nothing, e.g. shadow
        disabled or the canary shedding every mirror, must not pin an
        undecided canary forever)."""
        with self._lock:
            if (self._canary_step is None or step is None
                    or step != self._canary_step):
                return None
            self._canary_requests.inc()
            if ok:
                self._canary_ok += 1
            else:
                self._canary_err += 1
                self._canary_errors.inc()
            total = self._canary_ok + self._canary_err
            if total < self.canary_min_requests:
                return None
            rate = self._canary_err / total
            if rate > self.canary_max_error_rate:
                return self._decide_locked(False, {
                    "reason": "error_rate", "error_rate": round(rate, 4),
                    "bar": self.canary_max_error_rate,
                    "requests": total})
            if self.shadow_max_drift is not None:
                n = len(self._canary_drift)
                # Floor of 1: a percentile needs at least one sample —
                # min_samples=0 must mean "judge as soon as anything
                # arrives", never "judge an empty distribution".
                if n < max(1, self.shadow_min_samples):
                    if total < self.canary_min_requests * 4:
                        return None  # defer: wait for mirrored rows
                    logger.warning(
                        "canary: promoting step %d on error rate alone "
                        "— only %d/%d drift samples arrived after %d "
                        "outcomes (is the shadow mirror running?)",
                        self._canary_step, n, self.shadow_min_samples,
                        total)
                    return self._decide_locked(True, {
                        "reason": "error_rate_only",
                        "error_rate": round(rate, 4),
                        "drift_samples": n, "requests": total})
                p99 = self._drift_p99_locked()
                if p99 > self.shadow_max_drift:
                    self._shadow_breaches.inc()
                    return self._decide_locked(False, {
                        "reason": "shadow_drift",
                        "drift_p99": round(p99, 6),
                        "bar": self.shadow_max_drift,
                        "drift_samples": n, "requests": total})
                return self._decide_locked(True, {
                    "reason": "error_rate+drift",
                    "error_rate": round(rate, 4),
                    "drift_p99": round(p99, 6),
                    "drift_samples": n, "requests": total})
            return self._decide_locked(True, {
                "reason": "error_rate", "error_rate": round(rate, 4),
                "requests": total})

    def observe_drift(self, step: int | None,
                      samples: list[float]) -> tuple[str, int] | None:
        """Record mirrored-row drift samples for the undecided canary
        (serving/shadow.py). An already-over-the-bar p99 rolls back
        IMMEDIATELY — a drifted model must not keep taking canary
        traffic while the error-rate count ambles toward its minimum.
        Returns a decision tuple or None."""
        if not samples:
            return None
        with self._lock:
            if (self._canary_step is None or step is None
                    or step != self._canary_step):
                return None
            self._canary_drift.extend(float(s) for s in samples)
            # Bounded: the verdict needs a recent distribution, not
            # an unbounded history.
            if len(self._canary_drift) > 4096:
                self._canary_drift = self._canary_drift[-4096:]
            if self.shadow_max_drift is None:
                return None
            n = len(self._canary_drift)
            if n < max(1, self.shadow_min_samples):
                return None
            p99 = self._drift_p99_locked()
            if p99 > self.shadow_max_drift:
                self._shadow_breaches.inc()
                return self._decide_locked(False, {
                    "reason": "shadow_drift",
                    "drift_p99": round(p99, 6),
                    "bar": self.shadow_max_drift,
                    "drift_samples": n})
            return None

    # -- readers -----------------------------------------------------------
    def workers(self) -> list[WorkerEntry]:
        with self._lock:
            return list(self._workers.values())

    def workers_at_step(self, step: int) -> list[WorkerEntry]:
        with self._lock:
            return [w for w in self._workers.values()
                    if w.checkpoint_step == step]

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "workers": {w.worker_id: w.snapshot()
                            for w in sorted(self._workers.values(),
                                            key=lambda w: w.worker_id)},
                "trusted_step": self.trusted_step,
                "bad_steps": sorted(self.bad_steps),
                "canary_step": self._canary_step,
                "canary_fraction": self.canary_fraction,
                "shadow_max_drift": self.shadow_max_drift,
                "canary_drift_samples": len(self._canary_drift),
                "last_verdict": dict(self.last_verdict),
            }


class FleetRouter:
    """HTTP front door over a ``WorkerPool`` (+ optional cache).

    Same lifecycle idiom as ``EmbeddingServer``: ``start()`` binds and
    returns (the fleet CLI owns the foreground loop); ``close()`` tears
    down. The router holds no model and compiles nothing — it can
    restart in milliseconds, which is exactly why the cache lives here
    and not in the workers.
    """

    def __init__(self, pool: WorkerPool,
                 cache: EmbeddingCache | None = None,
                 example_shape=None,
                 host: str = "127.0.0.1", port: int = 8080,
                 retries: int = 2,
                 forward_timeout_s: float = 30.0,
                 control_timeout_s: float = 5.0,
                 max_body_bytes: int = MAX_BODY_BYTES,
                 registry: MetricsRegistry | None = None,
                 warm_rows: int = 32):
        self.pool = pool
        self.cache = cache
        # First-checkpoint adoption (None -> step) is a model change
        # with no canary verdict to hang the flush on: embeddings from
        # pre-checkpoint (random-init) weights must not survive it.
        # Demotion (a fleet-wide forced rollback) is equally a model
        # change — and additionally restores the prior index version.
        pool.on_trusted_adopt = self._on_trusted_adopt
        pool.on_trusted_rollback = self._on_trusted_rollback
        self.example_shape = (tuple(int(d) for d in example_shape)
                              if example_shape is not None else None)
        self.host, self.port = host, int(port)
        self.retries = int(retries)
        self.forward_timeout_s = float(forward_timeout_s)
        self.control_timeout_s = float(control_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        # Cache warming on promote (ROADMAP item 4 follow-up): how many
        # hot rows to replay through the newly trusted model right
        # after the promote flush (0 disables — the cache then boots
        # cold exactly as before).
        self.warm_rows = int(warm_rows)
        self.registry = registry if registry is not None \
            else pool.registry
        r = self.registry
        self._requests = r.counter("fleet_requests_total",
                                   "requests arriving at the router")
        self._responses = r.counter("fleet_responses_total",
                                    "2xx responses sent by the router")
        self._cache_only = r.counter(
            "fleet_cache_only_responses_total",
            "requests answered entirely from the cache (no worker)")
        self._cache_warmed = r.counter(
            "fleet_cache_warmed_total",
            "hot rows replayed through a newly promoted model")
        self._forwards = r.counter("fleet_forwards_total",
                                   "forward attempts to workers")
        self._retries_ctr = r.counter(
            "fleet_retries_total",
            "forward attempts beyond the first (failover)")
        self._rejects: dict[str, object] = {}
        self._reject_lock = threading.Lock()
        self.latency = {
            stage: r.histogram("fleet_latency_ms",
                               "router latency by stage",
                               labels={"stage": stage})
            for stage in ("total", "forward")
        }
        # Fleet observability plane (ISSUE 10): all optional — a bare
        # router (tests, bench) behaves exactly as before.
        self.run_id: str | None = None
        self.index = None           # retrieval.IndexManager (attach_index)
        self.shards = None          # retrieval.ShardFanout (attach_shards)
        self.shadow = None          # ShadowMirror (attach_shadow)
        self.admission = None       # TenantAdmission (ISSUE 16)
        self.aggregator = None      # obs.FleetAggregator -> /metrics/fleet
        self.history = None         # obs.MetricHistory -> /metrics/history
        self.alerts = AlertStore(registry=self.registry)  # -> /alerts
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        self._shutdown = threading.Event()

    def set_run_id(self, run_id: str | None) -> None:
        """Stamp the router's own run identity (ISSUE 10 satellite):
        the same ``serving_run_info`` info-metric pattern the workers
        publish, so a federated scrape or a merged trace correlates
        the router with its run — workers were labeled, the router
        was anonymous."""
        if not run_id:
            return
        self.run_id = str(run_id)
        self.registry.gauge(
            "serving_run_info",
            "router process identity (join key for cross-process "
            "correlation)", labels={"run_id": self.run_id}).set(1)

    def attach_index(self, manager) -> None:
        """Wire a ``retrieval.IndexManager`` (ISSUE 15): ``POST
        /search`` / ``/index/insert`` / ``/embed?store=true`` go live,
        rollout decisions drive index versions, and the manager's
        background rebuilds re-embed through this router's forward
        path."""
        self.index = manager
        manager.reembed = self._reembed
        if self.pool.trusted_step is not None:
            # Attached after the fleet already adopted: the index must
            # version against the step actually serving.
            manager.activate(self.pool.trusted_step)

    def attach_shards(self, fanout) -> None:
        """Wire a ``retrieval.ShardFanout`` (ISSUE 17): ``POST
        /search`` fans out to the shard plane and merges top-k; a dead
        shard degrades recall (``shards.degraded`` in the payload),
        never availability. Since ISSUE 20 the plane is VERSIONED: the
        rollout state machine drives it exactly like the in-process
        ``IndexManager`` — promote cuts every shard to the promoted
        step's generation, rollback restores the retained one fleet-
        wide, and the fan-out rejects any shard response carrying the
        wrong version, so a rollback can never serve mixed-model
        neighbors across shards. When an ``IndexManager`` is ALSO
        attached it stays the id/docstore authority and the shards
        mirror its inserts."""
        self.shards = fanout
        if self.pool.trusted_step is not None:
            # Attached after the fleet already adopted: the shard
            # plane must version against the step actually serving.
            fanout.activate(self.pool.trusted_step)

    def _on_trusted_adopt(self, step: int) -> None:
        if self.cache is not None:
            self.cache.clear(reason="adopt")
        if self.index is not None:
            self.index.activate(step)
        if self.shards is not None:
            self.shards.activate(step)

    def _on_trusted_rollback(self, new_step: int, old_step: int) -> None:
        """The fleet reverted beneath the router (WorkerPool demotion):
        embeddings of the demoted model must not outlive it, and the
        retrieval tier atomically restores the prior step's retained
        index version — the in-process index AND the shard plane."""
        if self.cache is not None:
            self.cache.clear(reason="rollback")
        if self.index is not None:
            self.index.rollback_to(new_step)
        if self.shards is not None:
            self.shards.rollback_to(new_step)
        _events.emit("rollout", action="trusted_demoted",
                     step=new_step, from_step=old_step)

    def _reembed(self, rows: np.ndarray) -> np.ndarray | None:
        """Embed input rows through the fleet for an index rebuild
        (runs on the manager's rebuild thread). Chunked under the body
        cap exactly like ``_warm_cache``; returns the stacked
        embeddings, or None when any chunk fails — a partial rebuild
        would silently shrink the index, so all-or-nothing."""
        x = np.asarray(rows, np.float32)
        rid = _trace.new_request_id()
        row_bytes = len(json.dumps(x[0].tolist())) + 2
        per = max(1, min(x.shape[0],
                         (self.max_body_bytes // 2) // row_bytes))
        out: list[np.ndarray] = []
        i = 0
        while i < x.shape[0]:
            chunk = x[i:i + per]
            body = json.dumps({"inputs": chunk.tolist()}).encode()
            code, payload, _, _served = self.forward(body, rid)
            if code == 413 and per > 1:
                per = max(1, per // 2)
                continue
            if code != 200 or not isinstance(payload, dict):
                logger.warning("retrieval rebuild: re-embed chunk "
                               "failed (%s)", code)
                return None
            try:
                emb = np.asarray(payload["embeddings"], np.float32)
                if emb.shape[0] != chunk.shape[0]:
                    raise ValueError("row-count mismatch")
            except (KeyError, TypeError, ValueError) as e:
                logger.warning("retrieval rebuild: malformed re-embed "
                               "response (%s)", e)
                return None
            out.append(emb)
            i += chunk.shape[0]
        return np.concatenate(out) if out else None

    def attach_shadow(self, mirror) -> None:
        """Wire a ShadowMirror: the router offers every successful
        trusted forward to it, and its verdicts take effect through
        the same decision path a live canary outcome uses."""
        self.shadow = mirror
        mirror.on_decision = self._handle_decision

    def _reject(self, reason: str) -> None:
        with self._reject_lock:
            counter = self._rejects.get(reason)
            if counter is None:
                counter = self._rejects[reason] = self.registry.counter(
                    "fleet_rejected_total",
                    "non-2xx router outcomes by reason",
                    labels={"reason": reason})
        counter.inc()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._httpd is not None:
            raise RuntimeError("router already started")
        self._httpd = ThreadingHTTPServer((self.host, self.port),
                                          _make_router_handler(self))
        self.port = self._httpd.server_address[1]
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ntxent-fleet-router")
        self._http_thread.start()
        logger.info("fleet router on http://%s:%d", self.host, self.port)
        return self

    def close(self) -> None:
        self._shutdown.set()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._http_thread = None

    # -- forwarding --------------------------------------------------------
    def _post(self, url: str, body: bytes, rid: str,
              timeout_s: float) -> tuple[int, bytes, int | None]:
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": "application/json",
                     "X-Request-Id": rid})
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, resp.read(), _step_header(resp.headers)

    def _broadcast_rollback(self, step: int) -> None:
        """Tell every worker serving the breached step to revert (the
        staggered laggards get the bad step blocklisted before they
        ever adopt it — rollback() blocklists even when not serving)."""
        for entry in self.pool.workers():
            if not entry.alive:
                continue
            try:
                self._post(entry.url + "/rollback",
                           json.dumps({"step": step}).encode(),
                           _trace.new_request_id(),
                           self.control_timeout_s)
                logger.info("rollback of step %d sent to %s", step,
                            entry.worker_id)
            except (urllib.error.URLError, OSError, ValueError):
                logger.warning("rollback of step %d failed to reach %s "
                               "(its watcher will still refuse the step "
                               "once ejected/restarted)", step,
                               entry.worker_id)

    def _handle_decision(self, decision: tuple[str, int] | None) -> None:
        if decision is None:
            return
        action, step = decision
        verdict = dict(self.pool.last_verdict)
        if action == "promote":
            # A promote is the all-clear for any standing rollback
            # alert: the fleet accepted a successor model.
            if self.alerts.resolve("canary_rollback",
                                   reason=f"step {step} promoted"):
                _events.emit("alert", slo="canary_rollback",
                             state="resolved", kind="canary",
                             step=step)
        if action == "rollback":
            # A rollback IS an alert (ISSUE 10): typed event on the
            # JSONL stream, an /alerts entry, and a flight dump so the
            # postmortem tail (canary outcomes, shadow spans, the
            # breach itself) is captured AT the verdict. ONE fixed
            # alert name — the step rides the record's fields; a
            # per-step name would mint unbounded slo_alerts_total
            # label cardinality and an ever-growing firing set (the
            # same cardinality bug this PR fixes for request sizes).
            reason = verdict.get("reason", "canary_breach")
            self.alerts.fire("canary_rollback",
                             reason=reason,
                             value=verdict.get("drift_p99",
                                               verdict.get("error_rate")),
                             threshold=verdict.get("bar"), step=step)
            _events.emit("alert", slo="canary_rollback",
                         state="firing", kind="canary", step=step,
                         **{k: v for k, v in verdict.items()
                            if k != "step"})
            _events.dump_flight(reason=f"canary_rollback:step{step}:"
                                       f"{reason}")
            # Broadcast off the request thread: the verdict fires
            # inside the handler of whichever client request tripped
            # the breach, and serial /rollback POSTs (up to
            # workers x control_timeout_s against a wedged worker)
            # must not stall that client's response. Routing is safe
            # immediately — observe() already blocklisted the step
            # under the pool lock before returning the decision.
            threading.Thread(
                target=self._broadcast_rollback, args=(step,),
                daemon=True, name="fleet-rollback").start()
            if self.cache is not None:
                self.cache.clear(reason="rollback")
            if self.index is not None:
                # Drop any candidate version warmed for the breached
                # step; a DRIFT-reason breach additionally marks the
                # live index stale (the spaces demonstrably moved) and
                # forces a rebuild (ISSUE 15).
                self.index.on_canary_rollback(
                    step, verdict.get("reason", "canary_breach"))
            if self.shards is not None:
                self.shards.on_canary_rollback(
                    step, verdict.get("reason", "canary_breach"))
        elif action == "promote":
            if self.cache is not None:
                # Embeddings from the previous model must not outlive
                # it — but the hot INPUTS are model-independent:
                # capture them before the flush and replay them through
                # the newly trusted model so the hottest traffic never
                # boots cold.
                hot = (self.cache.hot_keys(self.warm_rows)
                       if self.warm_rows > 0 else [])
                self.cache.clear(reason="promote")
                if hot:
                    # Off the deciding request's thread: the verdict
                    # fired inside whichever client handler tripped it,
                    # and a full re-forward of warm_rows rows must not
                    # stall that client's response.
                    threading.Thread(target=self._warm_cache,
                                     args=(hot,), daemon=True,
                                     name="fleet-cache-warm").start()
            if self.index is not None:
                # Cut searches over to the new step's version (created
                # empty, rebuilt in the background by re-embedding the
                # retained inputs through the now-trusted fleet); the
                # prior version stays retained for rollback.
                self.index.promote(step)
            if self.shards is not None:
                # Cut the WHOLE shard plane to the promoted step in one
                # broadcast: every shard opens a fresh generation at
                # ``step`` and retains the prior one, so a later
                # rollback restores the exact pre-promote fleet — no
                # shard can serve the old model's neighbors next to a
                # peer serving the new one.
                self.shards.promote(step)

    def _warm_cache(self, rows: list) -> int:
        """Replay hot input rows through the (now trusted) fleet and
        re-insert their fresh embeddings; returns rows warmed. Best
        effort: any failure just leaves those rows cold, exactly the
        pre-warming behavior.

        The replay is CHUNKED: workers 413 a body over their byte cap
        or a request over ``--max-request-rows``, and warm_rows hot
        rows of a production-sized model serialize to far more JSON
        than one request may carry. Chunks are sized from one row's
        measured JSON footprint against half the router's own body cap
        (the workers' default cap matches), and any 413 halves the
        chunk and retries — which also adapts to a row cap the router
        cannot see."""
        x = np.stack(rows).astype(np.float32)
        rid = _trace.new_request_id()
        t0 = time.monotonic()
        row_bytes = len(json.dumps(x[0].tolist())) + 2
        per = max(1, min(x.shape[0],
                         (self.max_body_bytes // 2) // row_bytes))
        warmed, status = 0, 200
        i = 0
        while i < x.shape[0]:
            chunk = x[i:i + per]
            body = json.dumps({"inputs": chunk.tolist()}).encode()
            code, payload, _, served_step = self.forward(body, rid)
            if code == 413 and per > 1:
                per = max(1, per // 2)  # cap tighter than estimated
                continue  # same rows, smaller chunks
            if code != 200:
                status = code
            elif isinstance(payload, dict):
                try:
                    emb = np.asarray(payload["embeddings"], np.float32)
                    if emb.shape[0] != chunk.shape[0]:
                        raise ValueError(f"{emb.shape[0]} rows for "
                                         f"{chunk.shape[0]} inputs")
                except (KeyError, TypeError, ValueError):
                    emb = None
                # The same trust gate as any insert: a rollback or a
                # fresh canary racing the warm-up must not poison the
                # cache.
                if emb is not None and self.pool.allow_cache_insert(
                        served_step):
                    self.cache.insert(chunk, emb)
                    warmed += int(chunk.shape[0])
            i += chunk.shape[0]
        if warmed:
            self._cache_warmed.inc(warmed)
        _trace.emit_span("fleet.cache_warm",
                         (time.monotonic() - t0) * 1e3, request_id=rid,
                         rows=int(x.shape[0]), warmed=warmed,
                         status=status)
        if warmed:
            logger.info("cache warm after promote: replayed %d/%d hot "
                        "row(s)", warmed, int(x.shape[0]))
        else:
            logger.warning("cache warm after promote: nothing warmed "
                           "(status %s)", status)
        return warmed

    def forward(self, body: bytes, rid: str) -> tuple[int, dict,
                                                      dict | None,
                                                      int | None]:
        """Forward one /embed body with failover; returns ``(status,
        payload, payload_extra_headers, served_checkpoint_step)`` — the
        step of the worker that produced the answer (None on failure),
        which is what gates cache inserts. Never raises for worker-side
        trouble — every failure mode maps to a status."""
        tried: set[str] = set()
        attempts = 0
        last_5xx: tuple[str, int, dict] | None = None
        last_unreachable: str | None = None
        saturated_retry_after = 0.0
        saturated = False
        while attempts <= self.retries:
            entry = self.pool.pick(exclude=tried)
            if entry is None:
                break
            tried.add(entry.worker_id)
            attempts += 1
            self._forwards.inc()
            if attempts > 1:
                self._retries_ctr.inc()
            # Provisional attribution from the routing table; the
            # worker's own X-Checkpoint-Step reply label overrides it
            # (a hot swap between health probe and forward would
            # otherwise mislabel the response's model).
            step = entry.checkpoint_step
            t0 = time.monotonic()
            try:
                with _trace.span("fleet.forward", request_id=rid,
                                 worker=entry.worker_id, attempt=attempts):
                    status, payload, hdr_step = self._post(
                        entry.url + "/embed", body, rid,
                        self.forward_timeout_s)
                if hdr_step is not None:
                    step = hdr_step
            except urllib.error.HTTPError as e:
                hdr_step = _step_header(e.headers)
                if hdr_step is not None:
                    step = hdr_step
                raw = e.read()
                try:
                    detail = json.loads(raw)
                except ValueError:
                    detail = None
                if not isinstance(detail, dict):
                    # Valid-JSON-but-not-an-object bodies (a recycled
                    # port answering "busy" or null) must not crash the
                    # .get() consumers below — forward() never raises
                    # for worker-side trouble.
                    detail = {"error": raw.decode(errors="replace")[:500]}
                if e.code == 429:
                    # Saturation: not a worker failure, not a canary
                    # signal — try a sibling.
                    saturated = True
                    try:
                        retry_after = float(
                            detail.get("retry_after_s", 0.05))
                    except (TypeError, ValueError):
                        # Same recycled-port threat model as the
                        # non-dict guard above: a null/string value
                        # must not raise out of forward().
                        retry_after = 0.05
                    saturated_retry_after = max(saturated_retry_after,
                                                retry_after)
                    continue
                if e.code == 504:
                    # Deadline exceeded: the CLIENT's timeout_ms ran
                    # out (usually queue wait under load). The worker
                    # answered sanely — not a failure to eject on, not
                    # model-quality evidence for the canary (same
                    # neutrality as 429), and retrying would burn
                    # another full deadline past an already-expired
                    # one. Pass through.
                    self.pool.report_success(entry.worker_id)
                    return e.code, detail, None, step
                if e.code >= 500:
                    last_5xx = (entry.worker_id, e.code, detail)
                    self.pool.report_failure(
                        entry.worker_id, f"http {e.code}")
                    self._handle_decision(
                        self.pool.observe(entry.worker_id, step,
                                          ok=False))
                    continue
                # 4xx: the client's problem — pass through verbatim.
                # The worker itself is healthy, so the outcome still
                # counts toward a pending canary verdict — and a
                # verdict decided HERE must take effect like any other.
                self._handle_decision(
                    self.pool.observe(entry.worker_id, step, ok=True))
                self.pool.report_success(entry.worker_id)
                return e.code, detail, None, step
            except (urllib.error.URLError, OSError) as e:
                last_unreachable = entry.worker_id
                self.pool.report_failure(entry.worker_id, repr(e))
                self._handle_decision(
                    self.pool.observe(entry.worker_id, step, ok=False))
                continue
            finally:
                self.pool.done(entry.worker_id)
                self.latency["forward"].observe(
                    (time.monotonic() - t0) * 1e3)
            try:
                result = json.loads(payload)
                if not isinstance(result, dict):
                    raise ValueError("non-object JSON body")
            except ValueError:
                last_5xx = (entry.worker_id, 502,
                            {"error": "unparseable worker response"})
                self.pool.report_failure(entry.worker_id, "bad payload")
                # Garbage out of a canary is exactly the model-quality
                # evidence the verdict counts.
                self._handle_decision(
                    self.pool.observe(entry.worker_id, step, ok=False))
                continue
            self.pool.report_success(entry.worker_id)
            self._handle_decision(
                self.pool.observe(entry.worker_id, step, ok=True))
            if (self.shadow is not None and status == 200
                    and "embeddings" in result):
                # Off the critical path by construction: offer() only
                # enqueues (the mirror thread does the canary POST and
                # the diff). The embeddings ride as the parsed list —
                # the mirror converts once, on its own thread.
                self.shadow.offer(body, rid, step,
                                  result["embeddings"])
            return status, result, None, step
        if last_5xx is not None:
            worker_id, code, detail = last_5xx
            self._reject("worker_error")
            # Budget exhausted: surface the WORKER's status — the
            # router must not translate a diagnosable failure into a
            # generic one.
            return code, {"error": f"worker {worker_id} failed after "
                                   f"{attempts} attempt(s)",
                          "worker_error": detail.get("error"),
                          "worker": worker_id,
                          "attempts": attempts}, None, None
        if saturated:
            self._reject("saturated")
            return 429, {"error": "all workers saturated",
                         "retry_after_s": saturated_retry_after}, \
                {"Retry-After": f"{saturated_retry_after:.3f}"}, None
        if last_unreachable is not None:
            self._reject("unreachable")
            return 503, {"error": f"no worker reachable (last tried "
                                  f"{last_unreachable}, {attempts} "
                                  "attempt(s))"}, None, None
        self._reject("no_workers")
        return 503, {"error": "no ready workers"}, None, None

    # -- metrics -----------------------------------------------------------
    def metrics_dict(self) -> dict:
        out = {
            "run_id": self.run_id,
            "requests": int(self._requests.value),
            "responses": int(self._responses.value),
            "cache_only_responses": int(self._cache_only.value),
            "cache_warmed": int(self._cache_warmed.value),
            "forwards": int(self._forwards.value),
            "retries": int(self._retries_ctr.value),
            "latency_ms": {stage: h.snapshot_ms()
                           for stage, h in self.latency.items()},
            **self.pool.snapshot(),
        }
        if self.cache is not None:
            out["cache"] = self.cache.snapshot()
        if self.index is not None:
            out["index"] = self.index.snapshot()
        if self.shadow is not None:
            out["shadow"] = self.shadow.snapshot()
        if self.admission is not None:
            out["tenants"] = self.admission.snapshot()
        if self.aggregator is not None:
            out["federation"] = self.aggregator.snapshot()
        if self.history is not None:
            out["history"] = self.history.snapshot()
        firing = self.alerts.active()
        if firing:
            out["alerts_firing"] = [a["name"] for a in firing]
        return out


def _csv_cell(value) -> str:
    """One history point field as a CSV cell (empty for absent/None —
    a rollup never has missing stats, but raw/rollup share this path)."""
    if value is None:
        return ""
    if isinstance(value, float):
        return repr(value)
    return str(value)


def _make_router_handler(router: FleetRouter):
    pool = router.pool

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):  # noqa: N802
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            route = urlparse(self.path).path
            if route == "/healthz":
                ready = sum(1 for w in pool.workers() if w.ready)
                self._reply(200 if ready else 503,
                            {"status": "routing" if ready
                             else "no_ready_workers",
                             "workers_ready": ready,
                             "trusted_step": pool.trusted_step})
            elif route == "/metrics":
                fmt = choose_format(self.path,
                                    self.headers.get("Accept"),
                                    default="json")
                if fmt == "prometheus":
                    self._reply_prometheus(
                        router.registry.render_prometheus())
                elif fmt == "state":
                    # The federation scrape view (obs/aggregate.py):
                    # raw registry state, histogram windows included,
                    # so a federating replica router can merge THIS
                    # router like any worker.
                    self._reply(200, router.registry.dump_state())
                else:
                    self._reply(200, router.metrics_dict())
            elif route == "/metrics/fleet":
                # The federated view (ISSUE 10): one merged scrape for
                # the whole fleet — workers + this router. Default is
                # Prometheus text (this endpoint exists FOR scrapers);
                # ?format=json returns the same merged registry's
                # collect() dict.
                if router.aggregator is None:
                    self._reply(503, {"error": "no federation "
                                               "aggregator attached"})
                    return
                merged = router.aggregator.merged(max_age_s=30.0)
                fmt = choose_format(self.path,
                                    self.headers.get("Accept"),
                                    default="prometheus")
                if fmt == "json":
                    self._reply(200, merged.collect())
                elif fmt == "state":
                    self._reply(200, merged.dump_state())
                else:
                    self._reply_prometheus(merged.render_prometheus())
            elif route == "/metrics/history":
                # The retained time-series plane (ISSUE 18): raw ring
                # + 10s/1m rollups per series. ?series=NAME selects
                # one series (else the store snapshot), ?step=raw|10s|1m
                # picks the resolution, ?window=SECONDS trims relative
                # to the newest sample, ?format=csv flattens for
                # spreadsheet triage (JSON otherwise).
                if router.history is None:
                    self._reply(503, {"error": "no metrics history "
                                               "attached"})
                    return
                query = parse_qs(urlparse(self.path).query)
                series = query.get("series", [None])[0]
                step = query.get("step", ["raw"])[0]
                window = query.get("window", [None])[0]
                fmt = query.get("format", ["json"])[0]
                if series is None:
                    self._reply(200, {
                        **router.history.snapshot(),
                        "series_names":
                            router.history.series_names(),
                    })
                    return
                try:
                    window_s = float(window) if window is not None \
                        else None
                    payload = router.history.query(series, step=step,
                                                   window_s=window_s)
                except KeyError:
                    self._reply(404, {"error": f"no series {series!r}",
                                      "series":
                                      router.history.series_names()})
                    return
                except ValueError as e:
                    self._reply(400, {"error": str(e)})
                    return
                if fmt == "csv":
                    self._reply_csv(series, payload["step"],
                                    payload["points"])
                else:
                    self._reply(200, payload)
            elif route == "/alerts":
                # SLO + canary-verdict breaches (obs/slo.py): active
                # alerts and the recent history ring.
                self._reply(200, router.alerts.snapshot())
            elif route == "/index":
                # Retrieval-tier state: versions, active step,
                # staleness, docstore depth (ISSUE 15); with a shard
                # plane attached, its per-shard health rides along.
                if router.index is None and router.shards is None:
                    self._reply(503, {"error": "no retrieval index "
                                               "attached"})
                else:
                    snap = router.index.snapshot() \
                        if router.index is not None else {}
                    if router.shards is not None:
                        snap["shard_plane"] = router.shards.snapshot()
                    self._reply(200, snap)
            else:
                self._reply(404, {"error": f"no route {self.path!r}"})

        def _reply_prometheus(self, text: str) -> None:
            body = text.encode()
            self.send_response(200)
            self.send_header("Content-Type", PROMETHEUS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _reply_csv(self, series: str, step: str,
                       points: list[dict]) -> None:
            # Raw points have (t, value); rollup points carry the full
            # bucket stats. Header comes from the first point's keys so
            # both shapes round-trip.
            cols = list(points[0].keys()) if points \
                else ["t", "value"]
            lines = [",".join(cols)]
            for p in points:
                lines.append(",".join(_csv_cell(p.get(c)) for c in cols))
            body = ("\n".join(lines) + "\n").encode()
            self.send_response(200)
            self.send_header("Content-Type", "text/csv")
            self.send_header("Content-Length", str(len(body)))
            self.send_header("Content-Disposition",
                             f"inline; filename={series}.{step}.csv")
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # noqa: N802
            rid = (self.headers.get("X-Request-Id")
                   or _trace.new_request_id())
            t0 = time.monotonic()
            url = urlparse(self.path)
            route = url.path
            query = parse_qs(url.query)
            status = {"code": None, "rows": None, "k": None}

            def reply(code: int, payload: dict,
                      headers: dict | None = None) -> None:
                status["code"] = code
                merged = {"X-Request-Id": rid}
                if headers:
                    merged.update(headers)
                self._reply(code, payload, merged)
                if code < 300:
                    router._responses.inc()

            try:
                self._do_post(reply, rid, status, route, query)
            finally:
                if status["code"] is not None:
                    dur_ms = (time.monotonic() - t0) * 1e3
                    if route == "/embed":
                        router.latency["total"].observe(dur_ms)
                        _trace.emit_span("fleet.request", dur_ms,
                                         request_id=rid,
                                         status=status["code"],
                                         rows=status["rows"])
                    elif route == "/search":
                        # The search request's end-to-end span (embed
                        # forward + index scan) under the same id the
                        # worker chunks trace under.
                        _trace.emit_span("fleet.search", dur_ms,
                                         request_id=rid,
                                         status=status["code"],
                                         rows=status["rows"],
                                         k=status["k"])
                        if router.index is not None:
                            router.index.metrics.latency[
                                "search_request"].observe(dur_ms)
                    elif route == "/index/insert":
                        _trace.emit_span("fleet.insert", dur_ms,
                                         request_id=rid,
                                         status=status["code"],
                                         rows=status["rows"])

        def _do_post(self, reply, rid, status, route, query) -> None:
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = 0
            if length > router.max_body_bytes:
                self.close_connection = True
                reply(413, {"error": f"body of {length} bytes exceeds "
                                     f"the {router.max_body_bytes}-byte "
                                     "cap"},
                      {"Connection": "close"})
                return
            body = self.rfile.read(length) if length > 0 else b""
            if route == "/search":
                router._requests.inc()
                self._do_search(reply, rid, body, status)
                return
            if route == "/index/insert":
                router._requests.inc()
                self._do_insert(reply, rid, body, status)
                return
            if route != "/embed":
                reply(404, {"error": f"no route {self.path!r}"})
                return
            router._requests.inc()
            store = (query.get("store", ["0"])[0].lower()
                     in ("1", "true", "yes"))
            parsed = self._parse_rows(body)
            # Admission meters by row count when the router can parse
            # the body (cost scales with the work a tenant asks for);
            # an unparseable pass-through body costs one token — the
            # worker owns its 400, but the forward is still work.
            cost = int(parsed[0].shape[0]) if parsed is not None else 1
            if not self._admit(reply, cost):
                return
            if parsed is None or (router.cache is None and not store):
                # Unparseable here (the worker owns the 400) or neither
                # cache nor store needs the rows: pure pass-through.
                code, payload, headers, _ = router.forward(body, rid)
                if isinstance(payload, dict) and "rows" in payload:
                    status["rows"] = payload.get("rows")
                if store and code == 200 and isinstance(payload, dict):
                    # store=true on rows the router could not parse for
                    # keying: the embed succeeded but nothing entered
                    # the index — say so instead of silently dropping.
                    payload["stored"] = 0
                reply(code, payload, headers)
                return
            x, timeout_ms = parsed
            status["rows"] = int(x.shape[0])
            code, payload, headers, served_step, emb = \
                self._embed_full(rid, x, timeout_ms)
            if store and code == 200 and emb is not None \
                    and isinstance(payload, dict):
                ids = self._index_store(x, emb, served_step)
                payload["stored"] = len(ids)
                payload["ids"] = ids
                if router.index is not None:
                    payload["index_step"] = router.index.active_step
            reply(code, payload, headers)

        def _do_search(self, reply, rid, body, status) -> None:
            """POST /search {"inputs": ..., "k": N}: embed through the
            fleet, answer top-k from the step-matched index version —
            or, when a shard plane is attached, fan out and merge
            (degraded beats down: a dead shard drops its lists' rows,
            the response says so, and the status stays 200)."""
            if router.index is None and router.shards is None:
                reply(503, {"error": "no retrieval index attached "
                                     "(start the fleet with "
                                     "--index-dir)"})
                return
            try:
                req = json.loads(body or b"{}")
                if not isinstance(req, dict):
                    # A top-level array/scalar body must be a 400, not
                    # an AttributeError that drops the connection.
                    raise ValueError("body is not a JSON object")
                k = int(req.get("k", 10))
                if not 1 <= k <= 1024:
                    raise ValueError(f"k={k} out of [1, 1024]")
            except (TypeError, ValueError) as e:
                reply(400, {"error": f"unparseable /search body: {e}"})
                return
            status["k"] = k
            # One parse for the whole request: k above, rows here.
            parsed = self._parse_rows_obj(req)
            if parsed is None:
                reply(400, {"error": "inputs not parseable as rows of "
                                     "the fleet's example shape"})
                return
            x, timeout_ms = parsed
            status["rows"] = int(x.shape[0])
            # /search rides the same per-tenant buckets as /embed
            # (ISSUE 16): the retrieval path embeds through the fleet
            # too, so an unmetered /search would be a quota bypass.
            if not self._admit(reply, int(x.shape[0])):
                return
            code, payload, headers, served_step, emb = \
                self._embed_full(rid, x, timeout_ms)
            if code != 200 or emb is None:
                reply(code, payload, headers)
                return
            if router.shards is not None:
                # Shard plane: every shard probes the same global
                # top-nprobe lists and contributes the ones it owns,
                # so the merged answer equals the unsharded scan when
                # all shards report — and shrinks by exactly the dead
                # shards' lists when they don't.
                res = router.shards.search(emb, k=k)
                reply(200, {
                    "ids": res["ids"].tolist(),
                    "scores": [[float(s) if np.isfinite(s) else None
                                for s in row]
                               for row in res["scores"]],
                    "k": k, "rows": int(x.shape[0]),
                    "index_rows": res["rows"],
                    "shards": res["shards"],
                    "index_step": res["version"],
                    "served_step": served_step})
                return
            index_dim = router.index.dim
            if index_dim is not None and emb.shape[-1] != index_dim:
                # Fleet/index width skew (a changed --proj-dim rolled
                # out over a persisted index): a config conflict the
                # client can see, never a ValueError that drops the
                # connection.
                reply(409, {"error": f"embedding width "
                                     f"{emb.shape[-1]} != index dim "
                                     f"{index_dim} (the fleet's model "
                                     "changed width; rebuild or "
                                     "re-create the index)"})
                return
            # prefer_step: query vectors must search the index version
            # of the SPACE they were embedded in — during a rollout
            # window a laggard-served query legitimately belongs to the
            # retained prior version.
            res = router.index.search(emb, k=k, prefer_step=served_step)
            reply(200, {"ids": res["ids"], "scores": res["scores"],
                        "k": k, "rows": int(x.shape[0]),
                        "index_step": res["step"],
                        "index_stale": res["stale"],
                        "index_rows": res["rows"],
                        "served_step": served_step})

        def _do_insert(self, reply, rid, body, status) -> None:
            """POST /index/insert {"inputs": ...}: embed + store. The
            insert is trust-gated (same rule as cache inserts); a gated
            request still answers 200 with stored=0 — rollout windows
            are normal operation, not client errors."""
            if router.index is None and router.shards is None:
                reply(503, {"error": "no retrieval index attached "
                                     "(start the fleet with "
                                     "--index-dir)"})
                return
            parsed = self._parse_rows(body)
            if parsed is None:
                reply(400, {"error": "inputs not parseable as rows of "
                                     "the fleet's example shape"})
                return
            x, timeout_ms = parsed
            status["rows"] = int(x.shape[0])
            code, payload, headers, served_step, emb = \
                self._embed_full(rid, x, timeout_ms)
            if code != 200 or emb is None:
                reply(code, payload, headers)
                return
            ids = self._index_store(x, emb, served_step)
            out = {"stored": len(ids), "ids": ids,
                   "rows": int(x.shape[0]),
                   "index_step": (router.index.active_step
                                  if router.index is not None else None),
                   "served_step": served_step}
            if not ids:
                out["reason"] = "not_trusted"
            reply(200, out)

        def _index_store(self, x, emb, served_step) -> list:
            """Trust-gated index insert; [] when gated, unattached, or
            rejected (wrong step/dim). Never raises — a bad payload
            must degrade to stored:0, not drop the connection. With a
            shard plane attached the rows ALSO fan out to their owner
            shards (the IndexManager, when present, stays the id
            authority; a bare shard plane allocates its own)."""
            if router.index is None and router.shards is None:
                return []
            if not pool.allow_cache_insert(served_step):
                return []
            step = served_step if served_step is not None \
                else pool.trusted_step
            ids: list = []
            try:
                if router.index is not None:
                    ids = router.index.insert(x, emb, step=step)
            except Exception:  # noqa: BLE001 — the embed already
                # succeeded; an index-side failure must not turn a
                # 200 into a dropped connection.
                logger.exception("index insert failed")
                ids = []
            if router.shards is not None:
                try:
                    if router.index is None:
                        ids = router.shards.insert_auto(emb)
                    elif ids:
                        router.shards.insert(
                            np.asarray(ids, np.int64), emb)
                except Exception:  # noqa: BLE001 — same contract as
                    # the local-index failure above.
                    logger.exception("shard insert failed")
            return ids

        def _admit(self, reply, cost: int) -> bool:
            """Per-tenant admission check (no-op without a configured
            ``TenantAdmission``). On exhaustion answers the same 429 +
            Retry-After contract the saturation path uses, so clients
            need one backoff implementation, not two."""
            adm = router.admission
            if adm is None:
                return True
            tenant = self.headers.get("X-Tenant")
            ok, retry_after = adm.admit(tenant, cost=max(1, cost))
            if ok:
                return True
            router._reject("tenant_quota")
            reply(429, {"error": "tenant over admission quota",
                        "tenant": adm._normalize(tenant),
                        "retry_after_s": round(retry_after, 3)},
                  {"Retry-After": str(max(1, int(retry_after + 0.999)))})
            return False

        def _parse_rows(self, body: bytes):
            """Best-effort parse for cache keying; None = pass through
            and let a worker produce the authoritative 400. Caching
            requires ``example_shape`` (without it a batchless single
            example is indistinguishable from a batch of smaller rows,
            and a wrong split would poison the cache)."""
            if router.example_shape is None:
                # Before the parse: a shape-less router passes bodies
                # through untouched and must not pay a full json.loads
                # per request just to discard the result.
                return None
            try:
                req = json.loads(body or b"{}")
            except ValueError:
                return None
            return self._parse_rows_obj(req)

        def _parse_rows_obj(self, req):
            """``_parse_rows`` on an already-parsed body (callers that
            needed other fields must not pay a second json.loads)."""
            if router.example_shape is None or not isinstance(req, dict):
                return None
            try:
                x = np.asarray(req["inputs"], dtype=np.float32)
                if x.shape == router.example_shape:
                    x = x[None]
                if x.shape[1:] != router.example_shape or x.shape[0] < 1:
                    return None
                timeout_ms = req.get("timeout_ms")
                return x, timeout_ms
            except (KeyError, TypeError, ValueError):
                return None

        def _embed_full(self, rid, x, timeout_ms):
            """Embed parsed rows through cache+fleet; returns ``(code,
            payload, headers, served_step, embeddings-or-None)`` — the
            shared engine behind /embed (cached path), /search query
            embedding, and the index insert surfaces. ``served_step``
            is None when every row came from the cache (the embeddings
            are then trusted-model by construction)."""
            cache = router.cache
            if cache is None:
                body = {"inputs": x.tolist()}
                if timeout_ms is not None:
                    body["timeout_ms"] = timeout_ms
                code, payload, headers, served_step = router.forward(
                    json.dumps(body).encode(), rid)
                if code != 200 or not isinstance(payload, dict):
                    return code, payload, headers, served_step, None
                try:
                    emb = np.asarray(payload["embeddings"], np.float32)
                    if emb.shape[0] != x.shape[0]:
                        raise ValueError(f"{emb.shape[0]} rows for "
                                         f"{x.shape[0]} inputs")
                except (KeyError, TypeError, ValueError) as e:
                    router._reject("bad_worker_payload")
                    return 502, {"error": f"malformed worker response: "
                                          f"{e}"}, None, served_step, \
                        None
                return code, payload, headers, served_step, emb
            t0 = time.monotonic()
            generation = cache.generation
            hits, miss_idx = cache.lookup(x)
            _trace.emit_span("fleet.cache",
                             (time.monotonic() - t0) * 1e3,
                             request_id=rid, rows=int(x.shape[0]),
                             hits=len(hits), misses=len(miss_idx))
            if not miss_idx:
                # A full hit is single-model by construction (every row
                # came from the same cache generation) even if a flush
                # lands right now — no mixing possible, serve it.
                out = np.stack([hits[i] for i in range(x.shape[0])])
                router._cache_only.inc()
                return 200, {"embeddings": out.tolist(),
                             "dim": int(out.shape[-1]),
                             "rows": int(out.shape[0]),
                             "cache_hits": int(out.shape[0])}, \
                    None, None, out
            sub = {"inputs": x[miss_idx].tolist()}
            if timeout_ms is not None:
                sub["timeout_ms"] = timeout_ms
            code, payload, headers, served_step = router.forward(
                json.dumps(sub).encode(), rid)
            if code == 200 and hits and (
                    cache.generation != generation
                    or (served_step is not None
                        and pool.trusted_step is not None
                        and served_step != pool.trusted_step)):
                # The cached rows and the fetched rows came from
                # different models — one response must never mix two
                # embedding spaces. Two ways there: a flush
                # (promote/rollback/adopt — a MODEL change) landed
                # while the misses were in flight, or the forward hit a
                # non-trusted worker (a post-promote laggard still on
                # the old step, or a canary) while the cache holds the
                # trusted model. Re-forward the whole request — a
                # single worker reply is internally consistent
                # regardless of later flushes.
                hits = {}
                miss_idx = list(range(x.shape[0]))
                full = {"inputs": x.tolist()}
                if timeout_ms is not None:
                    full["timeout_ms"] = timeout_ms
                code, payload, headers, served_step = router.forward(
                    json.dumps(full).encode(), rid)
            if code != 200:
                return code, payload, headers, served_step, None
            try:
                fetched = np.asarray(payload["embeddings"],
                                     dtype=np.float32)
                if fetched.shape[0] != len(miss_idx):
                    raise ValueError(f"worker returned "
                                     f"{fetched.shape[0]} rows for "
                                     f"{len(miss_idx)} misses")
            except (KeyError, TypeError, ValueError) as e:
                router._reject("bad_worker_payload")
                return 502, {"error": f"malformed worker response: "
                                      f"{e}"}, None, served_step, None
            if pool.allow_cache_insert(served_step):
                cache.insert(x[miss_idx], fetched)
            merged = np.empty((x.shape[0], fetched.shape[-1]),
                              dtype=np.float32)
            for j, i in enumerate(miss_idx):
                merged[i] = fetched[j]
            for i, vec in hits.items():
                merged[i] = vec
            return 200, {"embeddings": merged.tolist(),
                         "dim": int(merged.shape[-1]),
                         "rows": int(merged.shape[0]),
                         "cache_hits": len(hits)}, None, served_step, \
                merged

    return Handler
