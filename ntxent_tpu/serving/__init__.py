"""Embedding inference serving: the north star's request-side half.

Training produces encoders; this package makes them servable under the
static-shape rules of XLA and the failure model of the PR 1 resilience
layer. The stack, bottom-up:

* ``engine.InferenceEngine`` — shape-bucketed, AOT-compiled forward
  (pad to a fixed ladder of batch sizes; compiled-executable cache
  keyed by bucket/dtype/model-hash; ``warmup()`` bounds first-request
  latency);
* ``batcher.MicroBatcher`` — dynamic micro-batching with a bounded
  queue: coalesce concurrent requests into one device call, split
  results per request, reject-with-retry-after on a full queue,
  per-request deadlines that never waste device work;
* ``server.EmbeddingServer`` — stdlib-HTTP ``/embed``, ``/healthz``,
  ``/metrics``, supervised by ``resilience.Supervisor`` +
  ``StallWatchdog`` so a wedged device call escalates through the
  existing stall path;
* ``metrics.ServingMetrics`` — per-bucket counts, queue depth,
  batch-fill ratio, padding waste, p50/p95/p99 latency, as JSON.

Launch with ``ntxent-serve`` (cli.py); load-test with
``scripts/serving_smoke.sh``; benchmark with ``python bench.py
--serving`` (writes BENCH_serving.json).
"""

from .batcher import (
    BatcherClosed,
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
)
from .engine import DEFAULT_BUCKETS, InferenceEngine
from .metrics import ServingMetrics
from .server import EmbeddingServer

__all__ = [
    "BatcherClosed",
    "DEFAULT_BUCKETS",
    "DeadlineExceededError",
    "EmbeddingServer",
    "InferenceEngine",
    "MicroBatcher",
    "QueueFullError",
    "ServingMetrics",
]
