"""Embedding inference serving: the north star's request-side half.

Training produces encoders; this package makes them servable under the
static-shape rules of XLA and the failure model of the PR 1 resilience
layer. The stack, bottom-up:

* ``engine.InferenceEngine`` — shape-bucketed, AOT-compiled forward
  (pad to a ladder of batch sizes; compiled-executable cache keyed by
  bucket/dtype/model-hash; ``warmup()`` bounds first-request latency;
  ``adaptive=True`` learns the ladder online and swaps it atomically
  after background re-AOT);
* ``ladder.SizeHistogram`` / ``ladder.optimize_ladder`` — the pure
  half of the traffic-adaptive ladder: decayed request-size histogram
  + DP bucket-edge optimizer (stdlib-only, JAX-free);
* ``batcher.MicroBatcher`` — dynamic micro-batching with a bounded
  queue: coalesce concurrent requests into one device call, split
  results per request, reject-with-retry-after on a full queue,
  per-request deadlines that never waste device work;
* ``server.EmbeddingServer`` — stdlib-HTTP ``/embed``, ``/healthz``,
  ``/metrics``, supervised by ``resilience.Supervisor`` +
  ``StallWatchdog`` so a wedged device call escalates through the
  existing stall path;
* ``metrics.ServingMetrics`` — per-bucket counts, queue depth,
  batch-fill ratio, padding waste, p50/p95/p99 latency, as JSON.

One process is not a fleet (ISSUE 8 / ROADMAP item 4); the fleet tier
sits in front of N of the above:

* ``cache.EmbeddingCache`` — content-hash keyed per-row cache with TTL
  + LRU bounds: repeated rows never reach a worker (and keep serving
  through a worker crash);
* ``router.FleetRouter`` / ``router.WorkerPool`` — the routing tier:
  least-in-flight spread, per-request retry budget (a worker SIGKILL
  under load yields zero client-visible 5xx), 429 load-shedding when
  all workers saturate, canary fractions + automatic rollback across
  checkpoint rollouts;
* ``shadow.ShadowMirror`` — shadow routing (ISSUE 10): mirror a
  fraction of trusted traffic to the undecided canary off the client's
  critical path, diff the embedding sets per row (cosine drift), and
  gate promotion on drift-p99 in addition to error rate;
* ``worker.CheckpointWatcher`` — worker-side zero-downtime rollout:
  watch the crash-safe checkpoint dir, warm the ladder, swap
  atomically, roll back on router command;
* ``fleet.ServingFleet`` — spawn/supervise the worker subprocesses
  (health-checked, ejected on consecutive failures, restarted with
  backoff; ``killworker@K``/``slowworker@K`` chaos);
* ``autoscale.AutoscaleController`` — closed-loop pool sizing over
  the federated signals (ISSUE 16): hysteresis/cooldown scale-up
  through the supervision path, zero-5xx drain-down, and
  ``router.TenantAdmission`` per-tenant token-bucket quotas.

Launch with ``ntxent-serve`` (one worker) or ``ntxent-fleet`` (router
+ N workers); load-test with ``scripts/serving_smoke.sh`` /
``scripts/fleet_smoke.sh``; benchmark with ``python bench.py
--serving`` / ``--fleet`` (BENCH_serving.json / BENCH_fleet.json).

Exports resolve lazily (PEP 562): the router tier (cache/router/fleet)
is JAX-free, and the ``ntxent-fleet`` router process importing it must
not pay the JAX import that ``engine``/``server``/``worker`` (the
worker-process half) would drag in eagerly.
"""

import importlib

# name -> defining submodule; resolved on first attribute access.
_EXPORTS = {
    "AutoscaleController": "autoscale",
    "flash_crowd": "autoscale",
    "parse_tenant_quotas": "autoscale",
    "BatcherClosed": "batcher",
    "DeadlineExceededError": "batcher",
    "MicroBatcher": "batcher",
    "QueueFullError": "batcher",
    "EmbeddingCache": "cache",
    "DEFAULT_BUCKETS": "engine",
    "InferenceEngine": "engine",
    "SizeHistogram": "ladder",
    "expected_padded_rows": "ladder",
    "optimize_ladder": "ladder",
    "ServingFleet": "fleet",
    "ServingMetrics": "metrics",
    "FleetRouter": "router",
    "WorkerPool": "router",
    "TokenBucket": "router",
    "TenantAdmission": "router",
    "ShadowMirror": "shadow",
    "cosine_drift": "shadow",
    "EmbeddingServer": "server",
    "CheckpointWatcher": "worker",
}


def __getattr__(name):
    module = _EXPORTS.get(name)
    if module is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    value = getattr(importlib.import_module(f".{module}", __name__), name)
    globals()[name] = value  # cache: later access skips __getattr__
    return value


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))


__all__ = [
    "AutoscaleController",
    "BatcherClosed",
    "CheckpointWatcher",
    "DEFAULT_BUCKETS",
    "DeadlineExceededError",
    "EmbeddingCache",
    "EmbeddingServer",
    "FleetRouter",
    "InferenceEngine",
    "MicroBatcher",
    "QueueFullError",
    "ServingFleet",
    "ServingMetrics",
    "ShadowMirror",
    "SizeHistogram",
    "TenantAdmission",
    "TokenBucket",
    "WorkerPool",
    "cosine_drift",
    "expected_padded_rows",
    "flash_crowd",
    "optimize_ladder",
    "parse_tenant_quotas",
]
