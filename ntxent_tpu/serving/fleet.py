"""Worker-replica supervision: spawn, health-check, eject, restart.

``ServingFleet`` owns the worker PROCESSES the way ``crashsim`` owns
training lineages: each worker is a subprocess (an ``ntxent-serve``
with ``--port-file`` + ``--watch-ckpt``), its stdout goes to a
per-worker log, its bound port is published through a port file, and a
single monitor thread runs the supervision loop:

* **liveness**: a dead process (SIGKILL, OOM, crash) is detected by
  ``poll()`` and restarted after ``RetryPolicy`` backoff — the same
  restart-with-backoff vocabulary the training Supervisor uses, with
  the per-worker restart count as the backoff ordinal;
* **health**: each tick probes ``/readyz`` (readiness distinct from
  liveness — a warming worker is alive but takes no traffic) and feeds
  ``WorkerPool.set_health``, so the router's routing table is never
  more than one poll behind reality. The router's own forward failures
  land in the same ``consecutive_failures`` counter;
* **ejection**: ``eject_after`` consecutive failures (probe or
  forward) SIGKILLs the worker and schedules a restart — a wedged-but-
  listening worker is indistinguishable from a slow one except by this
  counter, which is why slowworker chaos drives exactly this path;
* **fleet chaos**: ``FaultPlan``'s ``killworker@K`` / ``slowworker@K``
  fire on the K-th supervision tick — counted from the first tick
  where every worker is ready, so a plan hits a SERVING fleet at a
  deterministic point rather than a booting one — via
  ``FaultInjector.on_fleet_tick``: SIGKILL (no cleanup, the crash the
  retry budget must hide) and SIGSTOP-for-a-while (the gray failure
  health checks must catch).

The fleet mutates the pool; the router only reads it. Everything here
is JAX-free — supervision must never pay backend-init latency.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import subprocess
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

from ..obs import events as obs_events
from ..obs.registry import MetricsRegistry
from ..resilience.retry import RetryPolicy
from .router import WorkerPool

logger = logging.getLogger(__name__)

__all__ = ["ManagedWorker", "ServingFleet"]


class ManagedWorker:
    """One supervised worker subprocess (mutated by the monitor only)."""

    def __init__(self, worker_id: str, cmd: list[str], port_file: Path,
                 log_path: Path):
        self.worker_id = worker_id
        self.cmd = cmd
        self.port_file = port_file
        self.log_path = log_path
        self.proc: subprocess.Popen | None = None
        self.port: int | None = None
        self.restarts = 0
        self.restart_at: float | None = None
        self.slow_until: float | None = None

    @property
    def url(self) -> str | None:
        return f"http://127.0.0.1:{self.port}" if self.port else None

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None


class ServingFleet:
    """Spawn and supervise N workers; keep a ``WorkerPool`` truthful.

    ``make_cmd(worker_id, port_file) -> list[str]`` builds the worker's
    argv (the CLI passes serve flags through; tests pass any process
    that writes its port to ``port_file`` and answers ``/readyz``).
    """

    def __init__(self, make_cmd, n_workers: int, workdir,
                 pool: WorkerPool | None = None,
                 poll_s: float = 0.5,
                 eject_after: int = 3,
                 health_timeout_s: float = 2.0,
                 max_restarts: int = 8,
                 backoff: RetryPolicy | None = None,
                 injector=None,
                 slowworker_s: float = 3.0,
                 env: dict | None = None,
                 registry: MetricsRegistry | None = None,
                 attach: bool = False,
                 chaos_channel: str = "fleet"):
        if n_workers < 1:
            raise ValueError(f"n_workers must be >= 1, got {n_workers}")
        self.make_cmd = make_cmd
        self.workdir = Path(workdir)
        self.workdir.mkdir(parents=True, exist_ok=True)
        self.pool = pool if pool is not None else WorkerPool()
        self.poll_s = float(poll_s)
        self.eject_after = int(eject_after)
        self.health_timeout_s = float(health_timeout_s)
        self.max_restarts = int(max_restarts)
        self.backoff = backoff or RetryPolicy(
            max_attempts=max_restarts + 1, base_delay_s=0.5,
            multiplier=2.0, max_delay_s=15.0, jitter=0.1)
        self.injector = injector
        self.chaos_channel = str(chaos_channel)
        self.slowworker_s = float(slowworker_s)
        self.env = env
        self.registry = registry if registry is not None \
            else self.pool.registry
        r = self.registry
        self._spawns = r.counter("fleet_worker_spawns_total",
                                 "worker processes launched")
        self._worker_restarts = r.counter(
            "fleet_worker_restarts_total",
            "workers relaunched after death or ejection")
        self._ejections = r.counter(
            "fleet_worker_ejections_total",
            "workers killed after consecutive health failures")
        self._chaos_armed = False
        self._chaos_kills = 0
        self._chaos_slows = 0
        # Attach mode (router replication, ROADMAP item 4 follow-up):
        # a REPLICA router observes the same worker pool a primary
        # fleet owns. It discovers workers from the primary's port
        # files and probes /readyz, but never spawns, kills, ejects, or
        # restarts — process supervision stays with the one fleet that
        # created the processes. Worker membership is fixed at attach
        # time (the primary's w*.port files present then).
        self.attach = bool(attach)
        if self.attach:
            found = sorted(self.workdir.glob("w*.port"))
            self.workers = [
                ManagedWorker(pf.stem, cmd=None, port_file=pf,
                              log_path=self.workdir
                              / f"{pf.stem}.attached.log")
                for pf in found
            ] or [
                ManagedWorker(f"w{i}", cmd=None,
                              port_file=self.workdir / f"w{i}.port",
                              log_path=self.workdir
                              / f"w{i}.attached.log")
                for i in range(int(n_workers))
            ]
        else:
            self.workers = [
                ManagedWorker(f"w{i}",
                              cmd=None,  # built at spawn (fresh file)
                              port_file=self.workdir / f"w{i}.port",
                              log_path=self.workdir / f"w{i}.log")
                for i in range(int(n_workers))
            ]
        # Dynamic membership (ISSUE 16): the autoscaler adds/retires
        # workers from the aggregator thread while the monitor thread
        # iterates — membership mutations and iteration both go
        # through this lock (iteration via workers_snapshot()).
        self._workers_lock = threading.Lock()
        self._next_ordinal = int(n_workers)
        # Wired by the CLI when --autoscale is on: the controller the
        # drainworker@T chaos action targets, and the flash-crowd hook
        # spike@T fires (serving/autoscale.py / scripts/loadgen.py).
        self.autoscaler = None
        self.on_spike = None
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def workers_snapshot(self) -> list[ManagedWorker]:
        """Stable view of the (now mutable) membership list."""
        with self._workers_lock:
            return list(self.workers)

    # -- process control ---------------------------------------------------
    def _spawn(self, worker: ManagedWorker) -> None:
        worker.port_file.unlink(missing_ok=True)
        worker.port = None
        worker.restart_at = None
        worker.slow_until = None
        worker.cmd = self.make_cmd(worker.worker_id, worker.port_file)
        try:
            log = open(worker.log_path, "ab")
            try:
                worker.proc = subprocess.Popen(
                    worker.cmd, stdout=log, stderr=subprocess.STDOUT,
                    env=self.env)
            finally:
                log.close()  # the child holds its own fd now
        except OSError as e:
            # A failed launch (fork/exec ENOMEM, transient FS trouble)
            # must reschedule, not strand the worker: restart_at was
            # cleared above and proc is None, so without this no later
            # tick would ever look at the worker again — silently lost
            # capacity.
            worker.proc = None
            logger.error("fleet: spawn of %s failed: %r",
                         worker.worker_id, e)
            self._schedule_restart(worker, f"spawn failed: {e}")
            return
        self._spawns.inc()
        obs_events.emit("fleet", action="spawn",
                        worker=worker.worker_id, pid=worker.proc.pid,
                        restarts=worker.restarts)
        logger.info("fleet: spawned %s (pid %d)", worker.worker_id,
                    worker.proc.pid)

    def _kill(self, worker: ManagedWorker) -> None:
        if worker.proc is not None and worker.proc.poll() is None:
            try:
                worker.proc.kill()
                worker.proc.wait(timeout=5.0)
            except (OSError, subprocess.TimeoutExpired):
                pass

    def _schedule_restart(self, worker: ManagedWorker,
                          reason: str) -> None:
        # The failure count belonged to the incarnation that just died;
        # the replacement must boot with a clean slate or the eject
        # check fires again before its port file even appears.
        self.pool.clear_failures(worker.worker_id)
        worker.restarts += 1
        if worker.restarts > self.max_restarts:
            logger.error("fleet: %s exceeded %d restarts (%s) — leaving "
                         "it down", worker.worker_id, self.max_restarts,
                         reason)
            worker.restart_at = None
            return
        delay = self.backoff.delay_for(min(worker.restarts,
                                           self.backoff.max_attempts))
        worker.restart_at = time.monotonic() + delay
        self._worker_restarts.inc()
        obs_events.emit("fleet", action="restart_scheduled",
                        worker=worker.worker_id, reason=reason,
                        restart=worker.restarts,
                        max_restarts=self.max_restarts,
                        delay_s=round(delay, 3))
        logger.warning("fleet: %s down (%s) — restart %d/%d in %.2fs",
                       worker.worker_id, reason, worker.restarts,
                       self.max_restarts, delay)

    # -- dynamic membership (the autoscaler's surface, ISSUE 16) -----------
    def add_worker(self) -> ManagedWorker | None:
        """Spawn one NEW worker through the normal supervision path
        (fresh ordinal, port file, /readyz probing, restart budget).
        The caller gates pool-size bounds; this only creates. Returns
        None in attach mode — a replica router must never spawn
        processes the primary owns."""
        if self.attach:
            logger.warning("fleet: add_worker ignored in attach mode")
            return None
        with self._workers_lock:
            worker_id = f"w{self._next_ordinal}"
            self._next_ordinal += 1
            worker = ManagedWorker(
                worker_id, cmd=None,
                port_file=self.workdir / f"{worker_id}.port",
                log_path=self.workdir / f"{worker_id}.log")
            self.workers.append(worker)
        self._spawn(worker)
        return worker

    def retire_worker(self, worker_id: str,
                      grace_s: float = 5.0) -> bool:
        """Permanently remove one worker: membership first (so the
        monitor never reads its death as a crash and restarts it), then
        the pool entry (no more routes), then SIGTERM with a background
        SIGKILL fallback after ``grace_s``. The CALLER owns the zero-
        5xx part — this must only run once the victim is drained (no
        in-flight requests), which is the autoscale controller's drain
        state machine's job."""
        with self._workers_lock:
            worker = next((w for w in self.workers
                           if w.worker_id == worker_id), None)
            if worker is None:
                return False
            self.workers.remove(worker)
        self.pool.remove(worker_id)
        obs_events.emit("fleet", action="retire", worker=worker_id,
                        pid=worker.pid)
        logger.info("fleet: retiring %s (pid %s)", worker_id, worker.pid)
        proc = worker.proc
        if proc is not None and proc.poll() is None:
            try:
                proc.terminate()
            except OSError:
                pass

            def _reap() -> None:
                try:
                    proc.wait(timeout=grace_s)
                except subprocess.TimeoutExpired:
                    try:
                        proc.kill()
                        proc.wait(timeout=5.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass

            # The reap must not stall the calling thread (the federation
            # tick the controller rides): TERM now, KILL later if the
            # worker ignores it.
            threading.Thread(target=_reap, daemon=True,
                             name=f"ntxent-fleet-reap-{worker_id}"
                             ).start()
        worker.port_file.unlink(missing_ok=True)
        return True

    # -- health ------------------------------------------------------------
    def _probe(self, worker: ManagedWorker) -> None:
        """One /readyz probe; updates the pool and the failure count."""
        if worker.port is None:
            try:
                text = worker.port_file.read_text().strip()
                worker.port = int(text) if text else None
            except (OSError, ValueError):
                worker.port = None
            if worker.port is None:
                return  # still booting: not a failure, not ready
            self.pool.upsert(worker.worker_id, worker.url)
        try:
            req = urllib.request.Request(worker.url + "/readyz")
            with urllib.request.urlopen(
                    req, timeout=self.health_timeout_s) as resp:
                body = json.loads(resp.read())
            self.pool.set_health(worker.worker_id, alive=True, ready=True,
                                 checkpoint_step=body.get(
                                     "checkpoint_step"))
        except urllib.error.HTTPError as e:
            # 503 = alive but warming/draining: healthy process, no
            # traffic. Anything else odd counts as a failure.
            try:
                body = json.loads(e.read())
            except Exception:  # noqa: BLE001
                body = {}
            if e.code == 503:
                self.pool.set_health(worker.worker_id, alive=True,
                                     ready=False,
                                     checkpoint_step=body.get(
                                         "checkpoint_step"))
            else:
                self.pool.set_health(worker.worker_id, alive=True,
                                     ready=False)
                self.pool.report_failure(worker.worker_id,
                                         f"readyz http {e.code}",
                                         kind="probe")
        except (urllib.error.URLError, OSError, ValueError) as e:
            self.pool.set_health(worker.worker_id, alive=worker.alive(),
                                 ready=False)
            self.pool.report_failure(worker.worker_id, repr(e),
                                     kind="probe")
            if self.attach:
                # The primary may have restarted this worker on a NEW
                # port: forget the cached one so the next tick re-reads
                # the port file the primary republished.
                worker.port = None

    # -- chaos -------------------------------------------------------------
    def _apply_chaos(self) -> None:
        if self.injector is None:
            return
        if not self._chaos_armed:
            # Chaos ordinals count from the first tick where EVERY
            # worker is ready: a plan like killworker@20 must hit a
            # serving fleet at a deterministic point, not a booting one
            # at whatever tick JAX init happened to finish on.
            if sum(1 for w in self.pool.workers()
                   if w.ready) < len(self.workers_snapshot()):
                return
            self._chaos_armed = True
        if self.chaos_channel == "shard":
            # A shard fleet pulls its OWN ordinal stream: killshard@3
            # means "3 ticks after the shard plane armed", independent
            # of how many embed-fleet ticks the same injector served.
            actions = self.injector.on_shard_tick()
        else:
            actions = self.injector.on_fleet_tick()
        for action in actions:
            if action.startswith("spike"):
                # Flash crowd (ISSUE 16): no process to signal — the
                # CLI wires on_spike to a loadgen burst against the
                # router so the AUTOSCALER is what gets exercised.
                hook = self.on_spike
                if hook is None:
                    logger.warning("fleet chaos: %s due but no spike "
                                   "hook wired (--autoscale off?)",
                                   action)
                    continue
                logger.warning("fleet chaos: firing flash-crowd hook "
                               "(%s)", action)
                try:
                    hook(action)
                except Exception:  # noqa: BLE001 — chaos must not take
                    # down supervision.
                    logger.exception("fleet chaos: spike hook failed")
                continue
            if action.startswith("drainworker"):
                ctl = self.autoscaler
                if ctl is None:
                    logger.warning("fleet chaos: %s due but no "
                                   "autoscaler attached", action)
                    continue
                logger.warning("fleet chaos: forcing a drain-down (%s)",
                               action)
                ctl.force_drain(reason="chaos")
                continue
            live = [w for w in self.workers_snapshot() if w.alive()]
            if not live:
                logger.warning("fleet chaos: %s due but no live worker",
                               action)
                continue
            if action.startswith(("killworker", "killshard")):
                target = live[self._chaos_kills % len(live)]
                self._chaos_kills += 1
                logger.warning("fleet chaos: SIGKILL %s (pid %s)",
                               target.worker_id, target.pid)
                try:
                    os.kill(target.pid, signal.SIGKILL)
                except OSError:
                    pass
            elif action.startswith(("slowworker", "lagshard")):
                target = live[self._chaos_slows % len(live)]
                self._chaos_slows += 1
                logger.warning("fleet chaos: SIGSTOP %s for %.1fs "
                               "(pid %s)", target.worker_id,
                               self.slowworker_s, target.pid)
                try:
                    os.kill(target.pid, signal.SIGSTOP)
                    target.slow_until = (time.monotonic()
                                         + self.slowworker_s)
                except OSError:
                    pass

    # -- the supervision loop ----------------------------------------------
    def tick(self) -> None:
        """One supervision cycle (public: tests drive it directly)."""
        if self.attach:
            # Probe-only: a replica must never kill/eject/restart
            # processes the primary owns — health observation is the
            # whole job. (Its own forward failures still accumulate in
            # the shared pool entry and gate ITS routing via ready.)
            for worker in self.workers_snapshot():
                self._probe(worker)
            return
        self._apply_chaos()
        now = time.monotonic()
        for worker in self.workers_snapshot():
            if worker.slow_until is not None and now >= worker.slow_until:
                try:
                    os.kill(worker.pid, signal.SIGCONT)
                except (OSError, TypeError):
                    pass
                worker.slow_until = None
            if not worker.alive():
                if worker.proc is not None and worker.restart_at is None:
                    rc = worker.proc.poll()
                    self.pool.set_health(worker.worker_id, alive=False,
                                         ready=False)
                    obs_events.emit("fleet", action="death",
                                    worker=worker.worker_id, rc=rc)
                    self._schedule_restart(worker, f"exited rc={rc}")
                    worker.proc = None
                    # Flight dump AT the death (ISSUE 10): the event
                    # tail — health probes, the death, the scheduled
                    # restart — is the postmortem, captured now rather
                    # than reconstructed. No-op without an installed
                    # event log.
                    obs_events.dump_flight(
                        reason=f"worker_death:{worker.worker_id}:"
                               f"rc={rc}")
                if worker.restart_at is not None \
                        and now >= worker.restart_at:
                    self._spawn(worker)
                continue
            self._probe(worker)
            entry = next((w for w in self.pool.workers()
                          if w.worker_id == worker.worker_id), None)
            if entry is not None \
                    and entry.consecutive_failures >= self.eject_after:
                self._ejections.inc()
                logger.warning(
                    "fleet: ejecting %s after %d consecutive failures "
                    "(last: %s)", worker.worker_id,
                    entry.consecutive_failures, entry.last_error)
                obs_events.emit("fleet", action="eject",
                                worker=worker.worker_id,
                                failures=entry.consecutive_failures,
                                last_error=entry.last_error)
                self.pool.set_health(worker.worker_id, alive=False,
                                     ready=False)
                self._kill(worker)
                self._schedule_restart(worker, "ejected")
                worker.proc = None
                obs_events.dump_flight(
                    reason=f"worker_eject:{worker.worker_id}")

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — supervision must survive
                # any single bad tick (a worker dying mid-probe, a
                # filesystem hiccup on a port file).
                logger.exception("fleet: supervision tick failed")

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ServingFleet":
        if self._thread is not None:
            raise RuntimeError("fleet already started")
        if not self.attach:
            for worker in self.workers:
                self._spawn(worker)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ntxent-fleet-monitor")
        self._thread.start()
        return self

    def wait_ready(self, n: int | None = None,
                   timeout_s: float = 120.0) -> bool:
        """Block until ``n`` workers (default: all) pass /readyz."""
        want = len(self.workers_snapshot()) if n is None else int(n)
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if sum(1 for w in self.pool.workers() if w.ready) >= want:
                return True
            time.sleep(0.1)
        return False

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(self.poll_s * 4 + 5.0)
            self._thread = None
        workers = self.workers_snapshot()
        for worker in workers:
            if worker.proc is not None and worker.proc.poll() is None:
                worker.proc.terminate()
        deadline = time.monotonic() + 5.0
        for worker in workers:
            if worker.proc is None:
                continue
            try:
                worker.proc.wait(timeout=max(0.1, deadline
                                             - time.monotonic()))
            except subprocess.TimeoutExpired:
                self._kill(worker)
