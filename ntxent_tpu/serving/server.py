"""Thread-based embedding service loop: /embed, /healthz, /metrics.

Stdlib ``http.server`` only — the serving stack adds no dependencies the
container doesn't already have (the same no-new-hard-deps rule the rest
of the framework follows). ``ThreadingHTTPServer`` gives
one-thread-per-connection, which is exactly the shape ``MicroBatcher``
wants: every handler thread blocks in ``submit()`` while the single
worker thread coalesces their requests into device calls.

Supervision reuses the PR 1 resilience layer verbatim rather than
growing a parallel one:

* ``serve_forever`` runs attempts under ``resilience.Supervisor`` — the
  same restart-with-backoff harness the trainer uses. Each attempt gets
  a fresh ``MicroBatcher`` wired to the supervisor's per-attempt
  ``StallWatchdog``;
* the batcher beats the watchdog every worker iteration (idle included),
  so sustained silence isolates one cause: a wedged device call. The
  watchdog then dumps all thread stacks and escalates through the
  supervisor's existing stall path (stop the attempt, restart with a
  fresh batcher and backoff) while the HTTP listener itself stays up and
  answers 503 between attempts;
* ``/healthz`` is the readiness/liveness surface: 200 once warm and
  serving, 503 while stalled, restarting, or draining.

Wire format (JSON in, JSON out; see README "Serving"):

* ``POST /embed``   body ``{"inputs": [[...], ...]}`` — one request of
  ``(n,) + example_shape`` rows (a single example may omit the leading
  dim); optional ``"timeout_ms"``. Replies ``{"embeddings": [...],
  "dim": D, "rows": n}``; 429 + Retry-After on backpressure, 504 on
  deadline, 400 on malformed input, 413 over the body/row caps, 503
  while not serving.
* ``GET /healthz``  ``{"status": "serving"|"stalled"|"unavailable"}``.
* ``GET /metrics``  the full ``ServingMetrics.to_dict()`` JSON.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import urlparse

import numpy as np

from ..obs import trace as _trace
from ..obs.exporters import PROMETHEUS_CONTENT_TYPE, choose_format
from ..resilience.retry import RetryPolicy
from ..resilience.supervisor import Supervisor
from .batcher import (
    BatcherClosed,
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
)
from .engine import InferenceEngine
from .limits import MAX_BODY_BYTES

logger = logging.getLogger(__name__)

__all__ = ["EmbeddingServer"]

# Deadline cap: a client asking for a multi-minute wait would hold a
# handler thread (and its queue slot's worth of patience) hostage.
MAX_TIMEOUT_S = 60.0
MAX_REQUEST_ROWS_BUCKETS = 8  # rows cap = this many max-size buckets


@dataclass
class _AttemptState:
    """Adapter for Supervisor's ``int(state.step) >= num_steps`` check:
    step 1 = operator-requested shutdown (complete), 0 = fault exit
    (restart)."""

    step: int


class EmbeddingServer:
    """HTTP front end over InferenceEngine + MicroBatcher, supervised.

    ``start()`` binds the listener and returns (tests; embedding the
    server in another loop); ``serve_forever()`` additionally runs the
    supervised attempt loop in the calling thread until ``shutdown()``.
    """

    def __init__(
        self,
        engine: InferenceEngine,
        host: str = "127.0.0.1",
        port: int = 8080,
        max_batch: int | None = None,
        max_delay_s: float = 0.005,
        queue_size: int = 64,
        retry_policy: RetryPolicy | None = None,
        stall_timeout_s: float | None = None,
        max_restarts: int = 0,
        default_timeout_s: float = 10.0,
        max_body_bytes: int = MAX_BODY_BYTES,
        max_request_rows: int | None = None,
    ):
        self.engine = engine
        self.metrics = engine.metrics
        self.host, self.port = host, int(port)
        self._batcher_kwargs = dict(
            max_batch=max_batch, max_delay_s=max_delay_s,
            queue_size=queue_size, retry_policy=retry_policy)
        self.stall_timeout_s = stall_timeout_s
        self.max_restarts = int(max_restarts)
        self.default_timeout_s = float(default_timeout_s)
        self.max_body_bytes = int(max_body_bytes)
        self.max_request_rows = int(
            max_request_rows if max_request_rows is not None
            else MAX_REQUEST_ROWS_BUCKETS * engine.max_bucket)
        self.batcher: MicroBatcher | None = None
        self._watchdog = None
        self._shutdown = threading.Event()
        self._terminated_clean = False
        self._httpd: ThreadingHTTPServer | None = None
        self._http_thread: threading.Thread | None = None
        # Readiness is distinct from liveness (/readyz vs /healthz): a
        # worker whose ladder is still compiling is ALIVE but must not
        # receive router traffic — /embed answers 503 + Retry-After and
        # /readyz stays red until end_warmup(). Servers that never call
        # begin_warmup() (direct construction, tests) are ready as soon
        # as they serve.
        self._warming = threading.Event()
        self.warmup_retry_after_s = 2.0
        # Checkpoint hot-reload seam (serving/worker.py): when set, the
        # handler exposes its current step on /healthz//readyz and
        # routes POST /rollback to it.
        self.reloader = None

    # -- status ----------------------------------------------------------
    @property
    def serving(self) -> bool:
        return (self.batcher is not None and not self.batcher.closed
                and not self._shutdown.is_set())

    @property
    def ready(self) -> bool:
        return self.serving and not self._warming.is_set()

    def begin_warmup(self) -> None:
        """Mark the ladder cold: /readyz 503s and /embed sheds with
        Retry-After until ``end_warmup()`` (cli wires this around
        ``engine.warmup()`` when the listener binds first)."""
        self._warming.set()

    def end_warmup(self) -> None:
        self._warming.clear()

    def checkpoint_step(self) -> int | None:
        if self.reloader is not None:
            return self.reloader.current_step
        step = self.metrics.checkpoint_step
        return step if step >= 0 else None

    def status(self) -> str:
        dog = self._watchdog
        if dog is not None and dog.stalled.is_set():
            return "stalled"
        return "serving" if self.serving else "unavailable"

    # -- lifecycle -------------------------------------------------------
    def start(self) -> "EmbeddingServer":
        """Bind the listener and spin up one (unsupervised) batcher."""
        if self._httpd is not None:
            raise RuntimeError("server already started")
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), _make_handler(self))
        self.port = self._httpd.server_address[1]  # resolve port 0
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="ntxent-serve-http")
        self._http_thread.start()
        if self.batcher is None:
            self.batcher = MicroBatcher(self.engine,
                                        **self._batcher_kwargs)
        logger.info("serving on http://%s:%d (buckets %s)", self.host,
                    self.port, list(self.engine.buckets))
        return self

    def serve_forever(self) -> bool:
        """Supervised serve loop; returns True on clean shutdown.

        Runs attempts under ``resilience.Supervisor``: a stall escalation
        (or SIGTERM, when called from the main thread) ends the current
        attempt, its batcher drains, and a fresh one starts after
        backoff — up to ``max_restarts`` times. The HTTP listener spans
        attempts; requests between attempts get 503.
        """
        if self._httpd is None:
            self.start()
        # start() made an unsupervised batcher for the pre-loop window;
        # attempts own their batcher from here on.
        if self.batcher is not None:
            self.batcher.close()
            self.batcher = None

        def run_attempt(attempt, stop_fn, watchdog):
            self._watchdog = watchdog
            self.batcher = MicroBatcher(self.engine, watchdog=watchdog,
                                        **self._batcher_kwargs)
            try:
                while not stop_fn() and not self._shutdown.is_set():
                    time.sleep(0.05)
            finally:
                batcher, self.batcher = self.batcher, None
                batcher.close()
            stalled = watchdog is not None and watchdog.fired.is_set()
            if stop_fn() and not stalled and not self._shutdown.is_set():
                # stop_fn without a stall escalation = a real SIGTERM
                # (PreemptionGuard). For a server that means "terminate",
                # not "restart": latch shutdown. (The guard that saw the
                # signal reports preempted, which Supervisor never counts
                # as complete — _terminated_clean is what makes the exit
                # code right even with zero restart budget.)
                logger.warning("serving: termination signal — draining "
                               "and shutting down")
                self._shutdown.set()
            if self._shutdown.is_set() and not stalled:
                self._terminated_clean = True
            return _AttemptState(
                step=1 if self._shutdown.is_set() and not stalled else 0), []

        supervisor = Supervisor(
            run_attempt, num_steps=1, max_restarts=self.max_restarts,
            stall_timeout_s=self.stall_timeout_s)
        result = supervisor.run()
        self.close()
        # A SIGTERM'd attempt is 'preempted' to the Supervisor (never
        # complete), but for a server an operator-requested termination
        # IS the clean outcome.
        return result.completed or self._terminated_clean

    def shutdown(self) -> None:
        """Ask the serve loop to exit cleanly (thread-safe)."""
        self._shutdown.set()

    def close(self) -> None:
        self._shutdown.set()
        if self.batcher is not None:
            self.batcher.close()
            self.batcher = None
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
            self._http_thread = None


def _make_handler(server: EmbeddingServer):
    """Handler class closed over the EmbeddingServer (BaseHTTPRequestHandler
    instantiates per connection, so state must come from the closure)."""

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        # Route access logs through logging, not stderr writes.
        def log_message(self, fmt, *args):  # noqa: N802
            logger.debug("%s " + fmt, self.address_string(), *args)

        def _reply(self, code: int, payload: dict,
                   headers: dict | None = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802
            route = urlparse(self.path).path
            if route == "/healthz":
                status = server.status()
                self._reply(200 if status == "serving" else 503,
                            {"status": status,
                             "ready": server.ready,
                             "checkpoint_step": server.checkpoint_step()})
            elif route == "/readyz":
                # Readiness gate (distinct from liveness): the router
                # must never send traffic to a cold worker. Ready =
                # warmup complete AND the batcher accepting.
                if server.ready:
                    self._reply(200, {
                        "status": "ready",
                        "checkpoint_step": server.checkpoint_step()})
                else:
                    retry = server.warmup_retry_after_s
                    self._reply(503, {
                        "status": "warming" if server._warming.is_set()
                        else server.status(),
                        "retry_after_s": retry,
                        "checkpoint_step": server.checkpoint_step()},
                        {"Retry-After": f"{retry:.3f}"})
            elif route == "/metrics":
                # Content negotiation (ISSUE 3): JSON stays the default
                # (existing dashboards/smoke parse it); a Prometheus
                # scraper gets the SAME values from the same registry
                # via ?format=prometheus or its Accept header.
                # Vertical signals (ISSUE 18) refresh at scrape time —
                # RSS and compile-cache pressure are process state, so
                # the scrape is the natural sampling point and the
                # request hot path never pays for them.
                server.metrics.update_vertical(
                    compile_cache_entries=getattr(
                        server.engine, "compile_cache_size", None))
                fmt = choose_format(self.path,
                                    self.headers.get("Accept"),
                                    default="json")
                if fmt == "prometheus":
                    body = server.metrics.render_prometheus().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     PROMETHEUS_CONTENT_TYPE)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                elif fmt == "state":
                    # Raw-registry federation view (ISSUE 10): what the
                    # router's FleetAggregator scrapes — histogram
                    # windows included, so fleet percentiles pool the
                    # exact samples instead of averaging percentiles.
                    self._reply(200,
                                server.metrics.registry.dump_state())
                else:
                    self._reply(200, server.metrics.to_dict())
            else:
                self._reply(404, {"error": f"no route {self.path!r}"})

        def do_POST(self):  # noqa: N802
            # Request identity is minted AT INGEST (ISSUE 7): every POST
            # response echoes it as X-Request-Id, and the span layer
            # threads it queue -> batch-coalesce -> device-chunk ->
            # respond, so one slow request can be followed through the
            # whole stack in the exported trace (obs/trace.py). A
            # request arriving WITH an id keeps it (ISSUE 8): the fleet
            # router mints at its edge and forwards, so one id threads
            # cache -> route -> worker queue -> device chunk.
            rid = (self.headers.get("X-Request-Id")
                   or _trace.new_request_id())
            t_ingest = time.monotonic()
            status = {"code": None, "rows": None}

            def reply(code: int, payload: dict,
                      headers: dict | None = None) -> None:
                status["code"] = code
                merged = {"X-Request-Id": rid}
                # The step that ACTUALLY served this response (ISSUE 8):
                # the router's health-probe view lags a hot swap, so the
                # worker labels every reply itself — the label is what
                # gates cache inserts and canary accounting upstream.
                step = server.checkpoint_step()
                if step is not None:
                    merged["X-Checkpoint-Step"] = str(step)
                if headers:
                    merged.update(headers)
                self._reply(code, payload, merged)

            try:
                self._do_embed_post(reply, rid, status)
            finally:
                if self.path == "/embed" and status["code"] is not None:
                    _trace.emit_span(
                        "serve.request",
                        (time.monotonic() - t_ingest) * 1e3,
                        request_id=rid, status=status["code"],
                        rows=status["rows"])

        def _do_rollback(self, reply, body: bytes) -> None:
            """Control surface for the router's canary breach (ISSUE 8):
            revert to the previously served weights and blocklist the
            named step so the watcher never re-adopts it."""
            if server.reloader is None:
                reply(404, {"error": "no checkpoint reloader on this "
                                     "server (start with --watch-ckpt)"})
                return
            try:
                req = json.loads(body or b"{}")
                step = req.get("step")
                step = int(step) if step is not None else None
            except (ValueError, TypeError) as e:
                reply(400, {"error": f"bad request: {e}"})
                return
            rolled = server.reloader.rollback(step)
            reply(200, {"rolled_back": rolled,
                        "checkpoint_step": server.reloader.current_step,
                        "blocked_steps":
                            sorted(server.reloader.blocked_steps)})

        def _do_embed_post(self, reply, rid, status) -> None:
            # Drain the body BEFORE any early reply: with keep-alive
            # (protocol_version 1.1) an unread body would be parsed as
            # the next request on the connection — every 404/503 would
            # poison the client's connection pool.
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = 0
            if length > server.max_body_bytes:
                # Too big to even read: closing the connection is what
                # keeps the unread body from desynchronizing keep-alive.
                self.close_connection = True
                reply(413, {"error": f"body of {length} bytes "
                                     f"exceeds the "
                                     f"{server.max_body_bytes}-byte "
                                     "cap"},
                      {"Connection": "close"})
                return
            body = self.rfile.read(length) if length > 0 else b""
            if self.path == "/rollback":
                self._do_rollback(reply, body)
                return
            if self.path != "/embed":
                reply(404, {"error": f"no route {self.path!r}"})
                return
            if server._warming.is_set():
                # Cold ladder: shed with the same Retry-After semantics
                # as backpressure — a client (or router) retries once
                # the ladder is compiled instead of paying the compile.
                retry = server.warmup_retry_after_s
                reply(503, {"error": "warming up (ladder compiling)",
                            "retry_after_s": retry},
                      {"Retry-After": f"{retry:.3f}"})
                return
            batcher = server.batcher
            if batcher is None or batcher.closed:
                reply(503, {"error": "not serving (restarting or "
                                     "draining)"})
                return
            try:
                req = json.loads(body or b"{}")
                x = np.asarray(req["inputs"], dtype=np.float32)
                if x.shape == server.engine.example_shape:
                    x = x[None]  # single example without the batch dim
                if x.ndim != 1 + len(server.engine.example_shape):
                    # Wrong rank (a scalar, a flat list, ...) must land
                    # in the 400 handler below — the row-cap check would
                    # otherwise IndexError on shape () and drop the
                    # connection with no response at all.
                    raise ValueError(
                        f"inputs must be shaped (n,) + "
                        f"{server.engine.example_shape}, got {x.shape}")
                timeout_s = min(
                    float(req.get("timeout_ms",
                                  server.default_timeout_s * 1e3)) / 1e3,
                    MAX_TIMEOUT_S)
            except (KeyError, TypeError, ValueError) as e:
                reply(400, {"error": f"bad request: {e}"})
                return
            status["rows"] = int(x.shape[0])
            if x.shape[0] > server.max_request_rows:
                # One request may chunk through the ladder, but not hog
                # the single device worker indefinitely: deadlines are
                # only checked at dispatch, so a huge request would
                # head-of-line-block everyone past any 429.
                reply(413, {"error": f"{x.shape[0]} rows exceed "
                                     "the per-request cap of "
                                     f"{server.max_request_rows}; "
                                     "split the batch client-side"})
                return
            try:
                out = batcher.submit(x, timeout_s=timeout_s,
                                     request_id=rid)
            except QueueFullError as e:
                reply(429, {"error": str(e),
                            "retry_after_s": e.retry_after_s},
                      {"Retry-After": f"{e.retry_after_s:.3f}"})
            except DeadlineExceededError as e:
                reply(504, {"error": str(e)})
            except ValueError as e:  # wrong trailing shape
                reply(400, {"error": str(e)})
            except BatcherClosed:
                reply(503, {"error": "not serving (draining)"})
            except Exception as e:  # noqa: BLE001 — device-call failure
                logger.exception("serving: /embed failed")
                reply(500, {"error": f"{type(e).__name__}: {e}"})
            else:
                reply(200, {"embeddings": out.tolist(),
                            "dim": int(out.shape[-1]),
                            "rows": int(out.shape[0])})

    return Handler
