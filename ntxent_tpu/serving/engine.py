"""Shape-bucketed AOT inference engine: the XLA-static-shape answer to
variable-size embedding requests.

XLA compiles one executable per input shape, so a service that ran the
encoder at every arriving batch size would recompile on nearly every
request — seconds of latency, unbounded executable cache growth. TPU
serving systems solve this with a fixed ladder of compiled shapes and
padding (the same static-shape discipline Ragged Paged Attention builds
its whole kernel around, PAPERS.md arxiv 2604.15464). ``InferenceEngine``
does exactly that for the SimCLR encoder+projection forward:

* a **bucket ladder** of batch sizes (default 1/4/16/64/128); requests
  pad up to the nearest bucket, oversized requests split into
  max-bucket chunks plus one tail bucket. With ``adaptive=True`` the
  ladder is LEARNED from live traffic (ISSUE 9 / ROADMAP item 1): an
  online decayed request-size histogram feeds a DP optimizer
  (serving/ladder.py) that picks rungs minimizing expected padded rows
  under a ladder-size budget; a background worker AOT-compiles the new
  ladder off the hot path and publishes it atomically the way
  ``swap_variables`` publishes weight swaps — in-flight chunks keep
  their (bucket, executable) snapshot, off-ladder executables are
  evicted, and request-visible compile counters stay flat (background
  compiles land in ``serving_ladder_compiles_total``). The configured
  ladder is the cold-start prior and its largest rung never moves: it
  is the chunking cap the batcher/row limits were provisioned against;
* executables are **AOT-lowered per bucket** through the same
  typed-exception fallback path the trainer uses
  (``training.trainer.aot_compile_with_flops`` — PR 1): where the backend
  refuses AOT, the engine degrades to per-call jit dispatch, observably,
  instead of dying;
* the compiled cache is keyed by ``(bucket, dtype, model_hash)`` so a
  weight reload (``update_variables``) can never serve a stale
  executable closed over old constants — and a QUANTIZED rung
  (``dtype="int8"``, ISSUE 12) is just another key: the executable
  takes an int8 payload + per-example scales (quantized host-side,
  dequantized in-graph), compresses the host->device transfer ~4x,
  and composes unchanged with the adaptive ladder and the fleet's
  shadow-drift gate (``--serve-dtype int8``);
* ``warmup()`` compiles the whole ladder up front, bounding
  first-request latency to one device call.

The engine is deliberately synchronous and thread-safe-for-one-caller:
request coalescing, queuing, and backpressure live one layer up in
``serving.batcher.MicroBatcher``.
"""

from __future__ import annotations

import hashlib
import logging
import threading
import time
from collections.abc import Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis.graph.recompile import RecompileDiffer
from ..obs import events as _events
from ..obs import trace as _trace
from .ladder import SizeHistogram, expected_padded_rows, optimize_ladder
from .metrics import ServingMetrics

logger = logging.getLogger(__name__)

__all__ = ["DEFAULT_BUCKETS", "InferenceEngine"]

DEFAULT_BUCKETS: tuple[int, ...] = (1, 4, 16, 64, 128)


def _structure_hash(variables) -> str:
    """Structural fingerprint of a variables pytree: treedef + leaf
    shapes/dtypes. Two pytrees that agree here are interchangeable
    ARGUMENTS to the same compiled executable (weights are passed in,
    not closed over) — which is exactly what makes the fleet's
    zero-downtime weight swap compile-free."""
    leaves, treedef = jax.tree_util.tree_flatten(variables)
    h = hashlib.sha1(str(treedef).encode())
    for leaf in leaves:
        h.update(f"{getattr(leaf, 'shape', ())}:"
                 f"{getattr(leaf, 'dtype', type(leaf))};".encode())
    return h.hexdigest()[:16]


def _model_hash(variables, version: int) -> str:
    """Cheap cache-key fingerprint of a variables pytree.

    Covers treedef + leaf shapes/dtypes (a different architecture can
    never collide into a cached executable) plus an explicit reload
    version — ``update_variables`` value swaps keep the same structure,
    so the counter is what invalidates their cache entries.
    """
    h = hashlib.sha1(_structure_hash(variables).encode())
    h.update(f"v{version}".encode())
    return h.hexdigest()[:16]


class InferenceEngine:
    """Bucketed, AOT-compiled forward pass over fixed per-example shape.

    ``apply_fn(variables, x) -> (B, D)`` is the pure forward (e.g.
    ``lambda v, x: model.apply(v, x, train=False, method="features")``).
    ``example_shape`` is one example's trailing shape, e.g. ``(H, W, C)``.
    """

    def __init__(
        self,
        apply_fn: Callable,
        variables,
        example_shape: Sequence[int],
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        dtype=jnp.float32,
        metrics: ServingMetrics | None = None,
        retry_policy=None,
        adaptive: bool = False,
        ladder_max_buckets: int = 6,
        ladder_min_requests: int = 200,
        ladder_decay: float = 0.999,
        ladder_interval_s: float = 0.0,
    ):
        buckets = tuple(sorted(set(int(b) for b in buckets)))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"buckets must be positive ints, got {buckets}")
        self.buckets = buckets
        self.initial_buckets = buckets  # the adaptive ladder's prior
        self.max_bucket = buckets[-1]
        self.example_shape = tuple(int(d) for d in example_shape)
        self.dtype = jnp.dtype(dtype)
        self.metrics = metrics or ServingMetrics()
        # resilience.RetryPolicy for transient device faults, applied PER
        # CHUNK (not per embed) so a retry never re-runs chunks that
        # already completed and metrics stay single-counted.
        self.retry_policy = retry_policy
        self.variables = variables
        self._version = 0
        self._hash = _model_hash(variables, self._version)
        # int8 rung (ISSUE 12): executables take a QUANTIZED chunk —
        # int8 payload + per-example f32 scales, quantized host-side in
        # _embed_chunk and dequantized in-graph before the forward. A
        # quantized executable is just another (bucket, "int8",
        # model_hash) cache entry, so the whole ladder machinery
        # (adaptive re-AOT, atomic swap, weight swaps) applies
        # unchanged; the host->device transfer moves ~4x fewer bytes.
        self.quantized = self.dtype == jnp.dtype(jnp.int8)
        if self.quantized:
            def _apply_dequant(v, q, scale):
                return apply_fn(v, q.astype(jnp.float32) * scale)

            self._jit_fn = jax.jit(_apply_dequant)
        else:
            self._jit_fn = jax.jit(apply_fn)
        self._apply_fn = apply_fn
        # (bucket, dtype_name, model_hash) -> executable. The dtype and
        # hash components look redundant for a single-model engine — they
        # exist so update_variables() invalidates by KEY MISS, never by a
        # racy clear a concurrent embed could be mid-lookup through.
        self._cache: dict[tuple, Callable] = {}
        self._lock = threading.Lock()
        # Recompile-cause differ (ISSUE 14): each compile's lowering
        # signature is recorded per cache key; a miss diffs against the
        # nearest prior so the `compile` event and the
        # serving_compiles_by_cause_total{reason} counter say WHY
        # (new_shape vs dtype vs weights_reload vs structure vs churn)
        # instead of bumping a bare count.
        self._recompile = RecompileDiffer()
        # Traffic-adaptive ladder (ISSUE 9). The histogram records
        # device-CHUNK row counts (an oversized request folds through
        # max-bucket chunking first) — exactly the sizes that pad.
        # ladder_interval_s > 0 runs the re-AOT worker as a daemon;
        # 0 leaves refresh to explicit refresh_ladder() calls
        # (tests/bench want deterministic swap points).
        self.adaptive = bool(adaptive)
        self.ladder_max_buckets = int(ladder_max_buckets)
        self.ladder_min_requests = int(ladder_min_requests)
        # Hysteresis: a proposal must beat the live ladder's expected
        # padding by this relative margin or the swap is skipped —
        # re-AOT churn on a flat improvement would pay compile time for
        # nothing.
        self.ladder_min_rel_improvement = 0.05
        self.ladder_generation = 0
        self.histogram = (SizeHistogram(decay=ladder_decay)
                          if self.adaptive else None)
        self._ladder_refresh_lock = threading.Lock()
        self._ladder_stop = threading.Event()
        self._ladder_thread: threading.Thread | None = None
        self.metrics.set_ladder(self.buckets, 0)
        if self.adaptive and ladder_max_buckets < 1:
            raise ValueError(f"ladder_max_buckets must be >= 1, got "
                             f"{ladder_max_buckets}")
        if self.adaptive and ladder_interval_s > 0:
            self._ladder_thread = threading.Thread(
                target=self._ladder_loop, args=(float(ladder_interval_s),),
                daemon=True, name="ntxent-ladder-reaot")
            self._ladder_thread.start()

    @property
    def compile_cache_size(self) -> int:
        """Live bucket-executable cache entries — the worker's
        vertical compile-cache pressure signal (ISSUE 18), read at
        /metrics scrape time."""
        with self._lock:
            return len(self._cache)

    # -- model lifecycle -------------------------------------------------
    def update_variables(self, variables) -> None:
        """Swap model weights (e.g. checkpoint reload on a live server).

        Bumps the cache-key version: old executables become unreachable
        (and are dropped) rather than served against new weights.
        """
        with self._lock:
            self.variables = variables
            self._version += 1
            self._hash = _model_hash(variables, self._version)
            self._cache.clear()

    def swap_variables(self, variables, warm: bool = True) -> str:
        """Zero-downtime weight swap (the fleet rollout path).

        Unlike ``update_variables`` (invalidate now, recompile on the
        next request), this never serves a cold bucket:

        * same structure (the overwhelmingly common case — a newer
          checkpoint of the same model): compiled executables take the
          weights as an ARGUMENT, so they remain valid for the new
          values. The swap is one reference assignment under the lock —
          no compile, no cache invalidation. Returns ``"reused"``.
        * changed structure: the full ladder is compiled against the new
          weights FIRST (requests keep flowing to the old set), then
          weights + cache key are published atomically. Returns
          ``"warmed"`` (or ``"cold"`` with ``warm=False``).

        In-flight ``embed`` calls snapshot (weights, executable) as a
        consistent pair, so a request that raced the swap runs entirely
        on the old model or entirely on the new one — never an old
        executable over new weights.
        """
        if _structure_hash(variables) == _structure_hash(self.variables):
            with self._lock:
                self.variables = variables
            self.metrics.model_swap("reused")
            logger.info("serving: swapped weights (structure unchanged — "
                        "compiled ladder reused)")
            return "reused"
        version = self._version + 1
        new_hash = _model_hash(variables, version)
        # Snapshot the ladder once: a concurrent adaptive-ladder swap
        # must not change the set being warmed mid-loop (a rung it adds
        # compiles lazily against the new hash on its own publish path).
        buckets = self.buckets
        if warm:
            for bucket in buckets:
                exe = self._executable(bucket, new_hash, variables)
                jax.block_until_ready(
                    exe(variables, *self._dummy_args(bucket)))
        with self._lock:
            self.variables = variables
            self._version = version
            self._hash = new_hash
            # Drop the previous structure's executables: they are
            # unreachable by key from here on, and each one pins device
            # allocations — a worker that swaps structures repeatedly
            # (or ping-pongs via rollback) must not grow the cache
            # without bound. In-flight chunks hold their own (weights,
            # exe) snapshot references, so eviction cannot yank an
            # executable out from under them.
            self._cache = {k: v for k, v in self._cache.items()
                           if k[2] == new_hash}
        self.metrics.model_swap("warmed" if warm else "cold")
        logger.info("serving: swapped weights (structure changed — "
                    "ladder %s)", "pre-warmed" if warm else "cold")
        return "warmed" if warm else "cold"

    def _snapshot(self) -> tuple:
        """(variables, cache hash) as a consistent pair — the unit a
        chunk must hold constant across a concurrent swap."""
        with self._lock:
            return self.variables, self._hash

    def _chunk_snapshot(self, n: int) -> tuple:
        """(variables, hash, bucket, exe-or-None) under ONE lock hold.

        The chunk's bucket must come from the same ladder generation as
        its executable lookup: resolving them in two lock acquisitions
        would let a ladder swap land in between — the chunk picks an
        old rung, the swap evicts that rung's executable, and the
        request pays a hot-path recompile (exactly the cost the
        background re-AOT exists to prevent). A ladder publishes only
        after every rung is compiled, so a consistent snapshot always
        finds its executable except on the cold no-warmup path."""
        with self._lock:
            bucket = next(b for b in self.buckets if b >= n)
            exe = self._cache.get((bucket, self.dtype.name, self._hash))
            return self.variables, self._hash, bucket, exe

    # -- executable argument marshalling ---------------------------------
    def _dummy_args(self, bucket: int) -> tuple:
        """Zero-filled executable arguments (after ``variables``) for
        one bucket — the AOT-lowering and warmup shapes."""
        if self.quantized:
            return (jnp.zeros((bucket,) + self.example_shape, jnp.int8),
                    jnp.ones((bucket,) + (1,) * len(self.example_shape),
                             jnp.float32))
        return (jnp.zeros((bucket,) + self.example_shape, self.dtype),)

    def _quantize_host(self, x: np.ndarray) -> tuple:
        """Per-example symmetric int8 quantization of a padded chunk,
        on the host (the device sees int8 + scales — the transfer is
        the wire this rung compresses). Symmetric [-127, 127], scale =
        amax(|example|)/127, all-zero (padding) rows quantize to zeros.
        """
        amax = np.abs(x.reshape(x.shape[0], -1)).max(axis=1)
        scale = (np.maximum(amax, 1e-30) / 127.0).reshape(
            (-1,) + (1,) * len(self.example_shape)).astype(np.float32)
        q = np.clip(np.rint(x / scale), -127, 127).astype(np.int8)
        return q, scale

    def _chunk_args(self, x: np.ndarray) -> tuple:
        if self.quantized:
            q, scale = self._quantize_host(np.asarray(x, np.float32))
            return (jnp.asarray(q), jnp.asarray(scale))
        return (jnp.asarray(x, self.dtype),)

    # -- bucket math -----------------------------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket >= n (n must fit the ladder)."""
        if n < 1:
            raise ValueError(f"need at least one row, got {n}")
        if n > self.max_bucket:
            raise ValueError(f"{n} rows exceed the largest bucket "
                             f"{self.max_bucket} (chunking is embed()'s "
                             "job)")
        for b in self.buckets:
            if n <= b:
                return b
        raise AssertionError("unreachable")  # pragma: no cover

    def _executable(self, bucket: int, model_hash: str | None = None,
                    variables=None, cached: Callable | None = None,
                    background: bool = False) -> Callable:
        """Resolve (compile if needed) the executable for ``bucket``.

        ``cached`` short-circuits with an executable the caller already
        resolved under the engine lock (``_chunk_snapshot``) — the
        chunk path passes it so a ladder swap's eviction landing
        between snapshot and resolution can never force a hot-path
        recompile of a rung that was compiled moments ago.
        """
        if cached is not None:
            self.metrics.compile_cache_hit()
            return cached
        if model_hash is None or variables is None:
            variables, model_hash = self._snapshot()
        key = (bucket, self.dtype.name, model_hash)
        with self._lock:
            exe = self._cache.get(key)
        if exe is not None:
            if not background:
                self.metrics.compile_cache_hit()
            return exe
        # Compile outside the lock (seconds-long); a concurrent miss on
        # the same key costs one duplicate compile, never a wrong result.
        args = self._dummy_args(bucket)
        from ..training.trainer import aot_compile_with_flops

        t0 = time.monotonic()
        _, compiled = aot_compile_with_flops(self._jit_fn, variables,
                                             *args)
        if compiled is None:
            # Typed-exception fallback already logged by the helper:
            # degrade to the jit wrapper. Prime its dispatch cache now so
            # the first real request still pays no compile.
            jax.block_until_ready(self._jit_fn(variables, *args))
            compiled = self._jit_fn
        duration_ms = (time.monotonic() - t0) * 1e3
        # The lowering signature this key stands for; diffing against
        # the nearest prior one names the compile's cause.
        structure = _structure_hash(variables)
        cause = self._recompile.observe(key, {
            "structure": structure,
            "dtype": self.dtype.name,
            "version": model_hash,
            "shape": (bucket,) + self.example_shape,
        })
        logger.info("serving: compiled bucket %d (%s) in %.2fs%s "
                    "[cause=%s]", bucket, self.dtype.name,
                    duration_ms / 1e3,
                    " [background]" if background else "", cause)
        # Background (ladder re-AOT) compiles are accounted separately:
        # serving_compiles_total is the REQUEST-visible compile bill,
        # and the ragged smoke asserts it stays flat across a swap.
        (self.metrics.ladder_compiled if background
         else self.metrics.compiled)(cause=cause)
        _events.emit("compile", bucket=int(bucket), dtype=self.dtype.name,
                     structure=structure[:8], cause=cause,
                     background=bool(background),
                     duration_ms=round(duration_ms, 3))
        with self._lock:
            exe = self._cache.setdefault(key, compiled)
        return exe

    # -- adaptive ladder (ISSUE 9) ---------------------------------------
    def refresh_ladder(self, force: bool = False) -> bool:
        """One observe -> optimize -> re-AOT -> swap cycle.

        Recomputes the optimal ladder from the decayed size histogram;
        when it differs from the live one (past the hysteresis margin),
        compiles every rung of the proposal OFF the request path, then
        publishes ladder + executables atomically under the engine
        lock. Returns True when a swap published. ``force=True`` skips
        the min-requests gate and the hysteresis margin (tests/bench
        want deterministic swap points) but still requires a non-empty
        histogram and a genuinely different proposal.

        Failure semantics: any compile error keeps the live ladder
        serving untouched (counted in
        ``serving_ladder_refresh_failures_total``); a weight swap that
        lands mid-compile abandons the publish — the next cycle
        re-optimizes against the new model hash.
        """
        if self.histogram is None:
            return False
        with self._ladder_refresh_lock:  # one re-AOT at a time
            if (not force
                    and self.histogram.observations
                    < self.ladder_min_requests):
                return False
            weights = self.histogram.weights()
            if not weights:
                return False
            proposal = optimize_ladder(weights, self.ladder_max_buckets,
                                       self.max_bucket,
                                       self.initial_buckets)
            current = self.buckets
            if proposal == current:
                return False
            if not force:
                cur_cost = expected_padded_rows(weights, current)
                new_cost = expected_padded_rows(weights, proposal)
                if not (cur_cost > 0.0
                        and new_cost <= cur_cost
                        * (1.0 - self.ladder_min_rel_improvement)):
                    return False
            variables, model_hash = self._snapshot()
            try:
                for bucket in proposal:
                    exe = self._executable(bucket, model_hash, variables,
                                           background=True)
                    jax.block_until_ready(
                        exe(variables, *self._dummy_args(bucket)))
            except Exception:  # noqa: BLE001 — a failed re-AOT must
                # never take down serving: the old ladder keeps working.
                logger.exception(
                    "serving: ladder re-AOT failed — keeping ladder %s",
                    list(current))
                self.metrics.ladder_refresh_failed()
                return False
            with self._lock:
                if self._hash != model_hash:
                    # A weight swap landed mid-compile: these
                    # executables belong to a retired model. Abandon;
                    # the next cycle re-optimizes against the new hash.
                    return False
                self.buckets = proposal
                self.ladder_generation += 1
                generation = self.ladder_generation
                keep = set(proposal)
                # Evict off-ladder executables for the live model: each
                # pins device allocations. In-flight chunks hold their
                # own (bucket, exe) snapshot references, so eviction
                # cannot yank an executable out from under them.
                self._cache = {k: v for k, v in self._cache.items()
                               if k[0] in keep or k[2] != model_hash}
            self.metrics.ladder_swap(proposal, generation)
            logger.info("serving: ladder swapped %s -> %s "
                        "(generation %d)", list(current), list(proposal),
                        generation)
            return True

    def _ladder_loop(self, interval_s: float) -> None:
        while not self._ladder_stop.wait(interval_s):
            try:
                self.refresh_ladder()
            except Exception:  # noqa: BLE001 — the re-AOT worker must
                # outlive any one bad cycle; serving never depends on it.
                logger.exception("serving: ladder refresh cycle failed")

    def close(self) -> None:
        """Stop the background re-AOT worker (no-op without one)."""
        self._ladder_stop.set()
        thread, self._ladder_thread = self._ladder_thread, None
        if thread is not None:
            thread.join(5.0)

    # -- public API ------------------------------------------------------
    def warmup(self) -> None:
        """Compile and execute every ladder bucket once, so no request
        ever pays first-compile latency (the /readyz readiness gate)."""
        variables, model_hash = self._snapshot()
        for bucket in self.buckets:
            exe = self._executable(bucket, model_hash, variables)
            jax.block_until_ready(
                exe(variables, *self._dummy_args(bucket)))
        logger.info("serving: warmup complete (%d buckets: %s)",
                    len(self.buckets), list(self.buckets))

    def _embed_chunk(self, x: np.ndarray) -> np.ndarray:
        n = x.shape[0]
        if n < 1 or n > self.max_bucket:
            raise ValueError(f"chunk of {n} rows outside (0, "
                             f"{self.max_bucket}] (chunking is embed()'s "
                             "job)")
        # One consistent (ladder rung, weights, executable) triple per
        # chunk: a weight OR ladder swap landing mid-request flips the
        # NEXT chunk, never mixes models (or pays a hot-path recompile
        # for an evicted rung) inside one call.
        variables, model_hash, bucket, cached = self._chunk_snapshot(n)
        pad = bucket - n
        if pad:
            x = np.concatenate(
                [x, np.zeros((pad,) + self.example_shape, x.dtype)])
        exe = self._executable(bucket, model_hash, variables, cached)
        args = self._chunk_args(x)

        def run_once():
            return jax.block_until_ready(exe(variables, *args))

        t0 = time.monotonic()
        # The chunk span nests under the batcher's serve.batch span
        # (same worker thread) in the exported trace — one slice per
        # padded executable call, bucket/pad in its args.
        with _trace.span("serve.device_chunk", bucket=int(bucket),
                         rows=int(n), pad=int(pad)):
            out = (self.retry_policy.call(run_once)
                   if self.retry_policy is not None else run_once())
        # device_ms spans retries + backoff when they happen: it is the
        # chunk's observed service time, which is what queue math needs.
        self.metrics.device_call(bucket, rows_real=n, rows_padded=pad,
                                 device_ms=(time.monotonic() - t0) * 1e3)
        return np.asarray(out)[:n]

    def embed(self, x: np.ndarray, n_requests: int = 1) -> np.ndarray:
        """Embeddings for ``x`` of shape ``(N,) + example_shape``.

        ``N`` may exceed the largest bucket: the batch splits into
        max-bucket chunks plus one bucketed tail (each chunk is its own
        device call and metrics record). ``n_requests`` is accounting
        only — how many coalesced user requests this one dispatch
        carries (the batch-fill-ratio numerator).
        """
        x = np.asarray(x)
        if x.shape[1:] != self.example_shape:
            raise ValueError(f"expected trailing shape {self.example_shape},"
                             f" got {x.shape[1:]}")
        if x.shape[0] < 1:
            raise ValueError("need at least one row")
        self.metrics.dispatch(n_requests)
        n = int(x.shape[0])
        # The size distribution is recorded per device CHUNK (the unit
        # that pads): an oversized request contributes its max-bucket
        # chunks plus the tail — the only part a better ladder can
        # still help. Counters feed /metrics; the decayed histogram
        # feeds the ladder optimizer.
        sizes = ([n] if n <= self.max_bucket else
                 [self.max_bucket] * (n // self.max_bucket)
                 + ([n % self.max_bucket] if n % self.max_bucket else []))
        for size in sizes:
            self.metrics.observe_request_size(size)
            if self.histogram is not None:
                self.histogram.observe(size)
        if n <= self.max_bucket:
            return self._embed_chunk(x)
        outs = []
        for start in range(0, n, self.max_bucket):
            outs.append(self._embed_chunk(x[start:start + self.max_bucket]))
        return np.concatenate(outs)
