"""SLO-driven fleet sizing: the closed loop over supervision + signals.

Every part of an autoscaler already existed loose in this repo — the
fleet spawns/supervises workers through port files and /readyz probes
(``fleet.ServingFleet``), the router federates per-worker telemetry
into one registry every tick (``obs.FleetAggregator``), and the SLO
engine reads burn rates and latency quantiles out of that merged view
(``obs/slo.py``). ``AutoscaleController`` closes the loop (ISSUE 16 /
ROADMAP item 4): it rides the aggregator's ``on_merge`` hook, extracts
the scaling signals from the SAME merged registry the SLO engine
judges, and drives pool size between ``min_workers`` and
``max_workers`` through policies with hysteresis (consecutive-tick
streaks) and per-direction cooldowns.

Scale-up is the existing supervision path: ``fleet.add_worker()``
spawns a fresh ordinal that publishes its port, warms its ladder, and
only enters routing once /readyz passes — the controller never routes,
it only asks for capacity.

Scale-down is **zero-5xx by construction**: the victim is marked
``draining`` in the ``WorkerPool`` (selection skips it instantly; its
in-flight requests keep completing), and only when its in-flight count
hits zero — or the drain deadline passes — does the controller retire
it through ``fleet.retire_worker`` (membership out first, THEN
SIGTERM, so the monitor never mistakes the exit for a crash). A client
can therefore never observe a connection reset from a scale-down: no
new request is ever routed to a worker that might disappear.

The decision core (``step_signals``) is a pure-ish state machine over
a signal snapshot — tests drive it with synthetic streams and pin the
hysteresis/cooldown boundaries without any fleet, HTTP, or clock.

Everything here is stdlib + obs — the router process imports it, so it
must stay JAX-free (the import-boundary lint enforces this).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import deque

from ..obs import events as obs_events
from ..obs.history import Forecaster, gauge_reduce
from ..obs.registry import MetricsRegistry
from ..obs.slo import counter_total, histogram_quantile

logger = logging.getLogger(__name__)

__all__ = ["AutoscaleController", "flash_crowd", "parse_tenant_quotas"]


def gauge_total(registry: MetricsRegistry, name: str) -> float:
    """Sum every label-set of a gauge in a merged registry (the
    federated ``serving_queue_depth{instance=...}`` view: one value
    per worker, their sum is the fleet's queued backlog)."""
    total = 0.0
    for entry in registry.dump_state()["metrics"]:
        if entry["name"] == name and entry["kind"] == "gauge":
            total += float(entry.get("value", 0.0))
    return total


def parse_tenant_quotas(spec: str) -> dict[str, tuple[float,
                                                      float | None]]:
    """Parse the CLI's ``--tenant-quota`` grammar:
    ``name=rate[:burst],name=rate...`` (rate in rows/s; burst defaults
    to one second of rate). The tenant named ``default`` pins the
    quota bare requests get."""
    quotas: dict[str, tuple[float, float | None]] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, value = part.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(f"bad tenant quota {part!r} "
                             "(want name=rate[:burst])")
        rate_s, _, burst_s = value.partition(":")
        try:
            rate = float(rate_s)
            burst = float(burst_s) if burst_s else None
        except ValueError:
            raise ValueError(f"bad tenant quota {part!r}: rate/burst "
                             "must be numbers") from None
        if rate <= 0 or (burst is not None and burst <= 0):
            raise ValueError(f"bad tenant quota {part!r}: rate/burst "
                             "must be > 0")
        quotas[name] = (rate, burst)
    return quotas


class AutoscaleController:
    """Closed-loop pool sizing over ``ServingFleet`` + ``WorkerPool``.

    Wire ``controller.observe`` onto ``FleetAggregator.on_merge``;
    every federation tick then (1) extracts the signal snapshot from
    the merged registry, (2) runs the scale policy, (3) acts through
    the fleet's supervision surface, and (4) advances any in-progress
    drains. All four run on the aggregator thread — the controller
    needs no thread of its own.

    Scale-up pressure (ANY source counts, per tick):
    ``queue_depth / routable >= up_queue_depth`` · ``inflight /
    routable >= up_inflight`` · ``p99 >= up_p99_ms`` (when configured)
    · availability burn rate ``>= up_burn`` (shed/error fraction over
    ``burn_window_s`` against the ``slo_target`` budget; tenant-quota
    429s are EXCLUDED — a tenant over its own quota must not buy the
    fleet more capacity). ``up_ticks`` consecutive pressure ticks +
    an expired up-cooldown adds ONE worker. A pool under
    ``min_workers`` (a forced drain, a worker that ran out of restart
    budget) repairs immediately, streaks and cooldowns notwithstanding.

    Scale-down: ``idle_ticks`` consecutive ticks of zero queue, no
    burn, and enough headroom that one fewer worker stays under half
    the up-pressure in-flight bound + an expired down-cooldown marks
    ONE victim draining (highest ordinal first — the elastic workers
    retire in LIFO order, the seed workers stay put).

    Predictive scale-up (ISSUE 18): with ``predict_horizon_s`` set,
    the controller feeds Holt-Winters forecasters (obs/history.py)
    the request-rate and fleet queue-depth series every tick and adds
    one more pressure source — ``forecast`` — that trips when the
    PROJECTED value at ``now + predict_horizon_s`` would breach the
    queue bound (or, with ``predict_capacity`` req/s-per-worker set,
    the fleet's rated throughput). The forecast only ever proposes:
    it rides the same streak, cooldown, and ``max_workers`` gates as
    every reactive source, and scale-DOWN stays purely reactive — a
    forecast can buy lead time, never shed capacity. ``up_rss_bytes``
    (off by default) adds the worker vertical memory signal the same
    way: federated max RSS at/over the bound is pressure.
    """

    def __init__(self, fleet, pool,
                 registry: MetricsRegistry | None = None,
                 min_workers: int = 1,
                 max_workers: int = 4,
                 up_queue_depth: float = 8.0,
                 up_inflight: float = 4.0,
                 up_p99_ms: float | None = None,
                 up_burn: float | None = 1.0,
                 up_ticks: int = 2,
                 idle_ticks: int = 6,
                 up_cooldown_s: float = 15.0,
                 down_cooldown_s: float = 30.0,
                 drain_deadline_s: float = 30.0,
                 burn_window_s: float = 30.0,
                 slo_target: float = 0.999,
                 predict_horizon_s: float | None = None,
                 predict_capacity: float | None = None,
                 predict_season_s: float | None = None,
                 up_rss_bytes: float | None = None,
                 history=None,
                 clock=time.monotonic):
        if min_workers < 1:
            raise ValueError(f"min_workers must be >= 1, got "
                             f"{min_workers}")
        if max_workers < min_workers:
            raise ValueError(f"max_workers {max_workers} < min_workers "
                             f"{min_workers}")
        self.fleet = fleet
        self.pool = pool
        self.registry = registry if registry is not None \
            else (pool.registry if pool is not None
                  else MetricsRegistry())
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.up_queue_depth = float(up_queue_depth)
        self.up_inflight = float(up_inflight)
        self.up_p99_ms = (float(up_p99_ms) if up_p99_ms is not None
                          else None)
        self.up_burn = float(up_burn) if up_burn is not None else None
        self.up_ticks = int(up_ticks)
        self.idle_ticks = int(idle_ticks)
        self.up_cooldown_s = float(up_cooldown_s)
        self.down_cooldown_s = float(down_cooldown_s)
        self.drain_deadline_s = float(drain_deadline_s)
        self.burn_window_s = float(burn_window_s)
        self.budget = 1.0 - float(slo_target)
        if predict_horizon_s is not None and predict_horizon_s <= 0:
            raise ValueError(f"predict_horizon_s must be > 0, got "
                             f"{predict_horizon_s}")
        if predict_capacity is not None and predict_capacity <= 0:
            raise ValueError(f"predict_capacity must be > 0, got "
                             f"{predict_capacity}")
        if up_rss_bytes is not None and up_rss_bytes <= 0:
            raise ValueError(f"up_rss_bytes must be > 0, got "
                             f"{up_rss_bytes}")
        self.predict_horizon_s = (float(predict_horizon_s)
                                  if predict_horizon_s is not None
                                  else None)
        self.predict_capacity = (float(predict_capacity)
                                 if predict_capacity is not None
                                 else None)
        self.up_rss_bytes = (float(up_rss_bytes)
                             if up_rss_bytes is not None else None)
        # The metrics-history store (obs/history.py), when attached:
        # forecasts are recorded back into it as *_forecast series so
        # /metrics/history can show prediction against reality.
        self.history = history
        # Forecast hard bounds: a wild model may propose at most 10x
        # the capacity the fleet could ever field — beyond that the
        # clamp holds, and the action gates (streaks, cooldowns,
        # max_workers) still apply to whatever survives.
        if self.predict_horizon_s is not None:
            self._rate_forecaster = Forecaster(
                season_s=predict_season_s,
                bound_max=(self.predict_capacity * max_workers * 10.0
                           if self.predict_capacity is not None
                           else None))
            self._queue_forecaster = Forecaster(
                season_s=predict_season_s,
                bound_max=float(up_queue_depth) * max_workers * 10.0)
        else:
            self._rate_forecaster = None
            self._queue_forecaster = None
        # no_routable is a REPAIR signal (all workers wedged), so it
        # only arms once the fleet has ever fielded a routable worker —
        # a cold boot's not-ready-yet seed must not scale the pool to
        # max before the first worker even finishes warming.
        self._seen_routable = False
        self.clock = clock
        self._lock = threading.Lock()
        # (now, total, bad) samples for the windowed burn rate — the
        # same ring idiom SLOEngine uses for its availability burn.
        self._burn_ring: deque = deque()
        self._up_streak = 0
        self._idle_streak = 0
        self._last_up_at: float | None = None
        self._last_down_at: float | None = None
        # worker_id -> {"since": t, "deadline": t, "reason": str}
        self._draining: dict[str, dict] = {}
        self.ticks = 0
        # Last signal snapshot (ISSUE 17): the retrieval tier's
        # ``heavy_gate`` reads fleet idleness from here instead of
        # guessing from a fixed worker count.
        self.last_signals: dict | None = None
        r = self.registry
        self._pool_size = r.gauge(
            "fleet_pool_size",
            "workers the autoscaler currently counts as capacity "
            "(ready or booting; draining excluded)")
        self._drain_ms = r.histogram(
            "fleet_drain_ms",
            "scale-down drain duration: draining mark to retirement")
        self._scale_counters: dict[tuple[str, str], object] = {}

    # -- metrics -----------------------------------------------------------
    def _count_scale(self, direction: str, reason: str) -> None:
        key = (direction, reason)
        counter = self._scale_counters.get(key)
        if counter is None:
            counter = self._scale_counters[key] = self.registry.counter(
                f"fleet_scale_{direction}_total",
                f"autoscaler {direction}-scales by triggering signal",
                labels={"reason": reason})
        counter.inc()

    # -- signal extraction -------------------------------------------------
    def signals(self, merged: MetricsRegistry) -> dict:
        """One signal snapshot from a freshly merged fleet registry +
        the pool's live routing state."""
        now = self.clock()
        total = counter_total(merged, "fleet_requests_total")
        bad = counter_total(merged, "fleet_rejected_total",
                            exclude={"reason": "tenant_quota"})
        ring = self._burn_ring
        # Instantaneous request rate from the previous tick's sample —
        # read BEFORE this tick joins the ring. The forecasters smooth
        # over it, so tick-to-tick jitter is fine.
        rate = None
        if ring:
            prev_t, prev_total, _ = ring[-1]
            if now > prev_t:
                rate = max(0.0, (total - prev_total) / (now - prev_t))
        ring.append((now, total, bad))
        while ring and now - ring[0][0] > self.burn_window_s:
            ring.popleft()
        burn = None
        if len(ring) >= 2:
            t0, total0, bad0 = ring[0]
            d_total = total - total0
            d_bad = bad - bad0
            if d_total > 0 and now - t0 >= self.burn_window_s * 0.25:
                burn = (d_bad / d_total) / self.budget
        p99, samples = histogram_quantile(merged, "fleet_latency_ms",
                                          0.99, labels={"stage": "total"})
        workers = self.pool.workers()
        draining_ids = set(self._draining)
        routable = [w for w in workers
                    if w.ready and w.worker_id not in draining_ids]
        queue_depth = gauge_total(merged, "serving_queue_depth")
        rss = (gauge_reduce(merged, "serving_worker_rss_bytes", "max")
               if self.up_rss_bytes is not None else None)
        forecast_rate = forecast_queue = None
        if self.predict_horizon_s is not None:
            if rate is not None:
                self._rate_forecaster.observe(now, rate)
            self._queue_forecaster.observe(now, queue_depth)
            forecast_rate = self._rate_forecaster.forecast(
                self.predict_horizon_s)
            forecast_queue = self._queue_forecaster.forecast(
                self.predict_horizon_s)
            if self.history is not None:
                if forecast_rate is not None:
                    self.history.record("fleet_request_rate_forecast",
                                        forecast_rate)
                if forecast_queue is not None:
                    self.history.record("serving_queue_depth_forecast",
                                        forecast_queue)
        return {
            "queue_depth": queue_depth,
            "inflight": float(sum(w.inflight for w in routable)),
            "routable": len(routable),
            "size": self.pool_size(),
            "p99_ms": p99 if samples else None,
            "burn": burn,
            "rate": rate,
            "rss_bytes": rss,
            "forecast_rate": forecast_rate,
            "forecast_queue_depth": forecast_queue,
        }

    def pool_size(self) -> int:
        """Capacity the controller reasons about: fleet membership
        (ready or booting) minus in-progress drains."""
        members = {w.worker_id for w in self.fleet.workers_snapshot()}
        return len(members - set(self._draining))

    # -- the decision core (pure over a signal snapshot) -------------------
    def step_signals(self, signals: dict,
                     now: float | None = None) -> tuple[str, str]:
        """Advance the policy state machine one tick. Returns
        ``(action, reason)`` with action in ``{"up", "down", "hold"}``
        — the caller acts; this only decides (tests pin the
        hysteresis/cooldown boundaries on synthetic streams)."""
        now = self.clock() if now is None else now
        size = int(signals["size"])
        routable = int(signals["routable"])
        if size < self.min_workers:
            # Below the floor (forced drain, restart budget exhausted):
            # repair NOW — hysteresis exists to damp oscillation, not
            # to slow-walk a capacity hole.
            self._up_streak = 0
            self._idle_streak = 0
            self._last_up_at = now
            return "up", "below_min"
        per_worker = max(1, routable)
        if routable > 0:
            self._seen_routable = True
        pressure: str | None = None
        if (routable == 0 and self._seen_routable
                and size < self.max_workers):
            pressure = "no_routable"
        elif signals["queue_depth"] / per_worker >= self.up_queue_depth:
            pressure = "queue_depth"
        elif signals["inflight"] / per_worker >= self.up_inflight:
            pressure = "inflight"
        elif (self.up_p99_ms is not None
              and signals.get("p99_ms") is not None
              and signals["p99_ms"] >= self.up_p99_ms):
            pressure = "p99"
        elif (self.up_burn is not None
              and signals.get("burn") is not None
              and signals["burn"] >= self.up_burn):
            pressure = "burn"
        elif (self.up_rss_bytes is not None
              and signals.get("rss_bytes") is not None
              and signals["rss_bytes"] >= self.up_rss_bytes):
            pressure = "rss"
        elif (self.predict_horizon_s is not None
              and signals.get("forecast_queue_depth") is not None
              and signals["forecast_queue_depth"] / per_worker
              >= self.up_queue_depth):
            pressure = "forecast"
        elif (self.predict_horizon_s is not None
              and self.predict_capacity is not None
              and signals.get("forecast_rate") is not None
              and signals["forecast_rate"]
              >= self.predict_capacity * per_worker):
            pressure = "forecast"
        if pressure is not None:
            self._idle_streak = 0
            self._up_streak += 1
            if size >= self.max_workers:
                return "hold", f"{pressure}:at_max"
            if self._up_streak < self.up_ticks:
                return "hold", f"{pressure}:streak"
            if self._last_up_at is not None \
                    and now - self._last_up_at < self.up_cooldown_s:
                return "hold", f"{pressure}:cooldown"
            self._up_streak = 0
            self._last_up_at = now
            return "up", pressure
        self._up_streak = 0
        idle = (signals["queue_depth"] <= 0.0
                and (signals.get("burn") is None
                     or signals["burn"] < 1.0)
                and routable > 1
                and signals["inflight"] / (routable - 1)
                <= self.up_inflight * 0.5)
        if not idle or size <= self.min_workers:
            self._idle_streak = 0
            return "hold", "steady"
        self._idle_streak += 1
        if self._idle_streak < self.idle_ticks:
            return "hold", "idle:streak"
        if self._last_down_at is not None \
                and now - self._last_down_at < self.down_cooldown_s:
            return "hold", "idle:cooldown"
        if self._last_up_at is not None \
                and now - self._last_up_at < self.down_cooldown_s:
            # A freshly added worker must get a full window to absorb
            # load before the controller reads the resulting calm as
            # over-provisioning.
            return "hold", "idle:recent_up"
        self._idle_streak = 0
        self._last_down_at = now
        return "down", "idle"

    # -- acting ------------------------------------------------------------
    def observe(self, merged: MetricsRegistry) -> dict:
        """The ``FleetAggregator.on_merge`` hook: one full control
        tick. Returns the signal snapshot (handy for tests/debugging);
        never raises — a controller bug must not poison federation."""
        with self._lock:
            try:
                self.ticks += 1
                now = self.clock()
                signals = self.signals(merged)
                action, reason = self.step_signals(signals, now)
                if action == "up":
                    self._scale_up(reason, signals)
                elif action == "down":
                    self._start_drain(reason, signals, now)
                self._advance_drains(now)
                self._pool_size.set(self.pool_size())
                self.last_signals = signals
                return signals
            except Exception:  # noqa: BLE001 — the federation tick
                # must survive any controller bug.
                logger.exception("autoscale: control tick failed")
                return {}

    def maintenance_ok(self) -> bool:
        """Is the fleet idle enough for heavy background work? The
        retrieval tier's ``heavy_gate`` (segment compaction, docstore
        log compaction — big sequential IO + CPU) calls this per
        maintenance tick. Idle here is the scale-down predicate MINUS
        the ``routable > 1`` term: a quiet one-worker fleet can't
        shed capacity but can absolutely afford a compaction. Before
        federation produces a first snapshot there is no evidence of
        load, so maintenance proceeds (True) — deferring on ignorance
        would starve single-process rigs forever."""
        s = self.last_signals
        if not s:
            return True
        per_worker = max(1, int(s.get("routable", 0)))
        return (float(s.get("queue_depth", 0.0)) <= 0.0
                and (s.get("burn") is None
                     or float(s["burn"]) < 1.0)
                and float(s.get("inflight", 0.0)) / per_worker
                <= self.up_inflight * 0.5)

    def _scale_up(self, reason: str, signals: dict) -> None:
        worker = self.fleet.add_worker()
        if worker is None:
            return
        self._count_scale("up", reason)
        if reason == "forecast":
            # The predictive trigger gets its own typed event: the
            # smoke harness and post-mortems tell lead-time capacity
            # apart from reactive repairs by this record alone.
            obs_events.emit("forecast",
                            horizon_s=self.predict_horizon_s,
                            forecast_rate=signals.get("forecast_rate"),
                            forecast_queue_depth=signals.get(
                                "forecast_queue_depth"),
                            rate=signals.get("rate"),
                            queue_depth=signals.get("queue_depth"))
        obs_events.emit("autoscale", action="scale_up", reason=reason,
                        worker=worker.worker_id,
                        size=self.pool_size(), **_sig_fields(signals))
        logger.info("autoscale: +1 worker %s (%s)", worker.worker_id,
                    reason)

    def _pick_victim(self) -> str | None:
        draining_ids = set(self._draining)
        candidates = [w for w in self.pool.workers()
                      if w.ready and w.worker_id not in draining_ids]
        if not candidates:
            return None
        # Highest ordinal = the most recently added elastic worker;
        # ties in readiness broken toward the LEAST loaded (cheapest
        # drain). worker_id sorts "w10" after "w9" via the numeric tail.
        def key(w):
            try:
                ordinal = int(w.worker_id.lstrip("w"))
            except ValueError:
                ordinal = -1
            return (ordinal, -w.inflight)
        return max(candidates, key=key).worker_id

    def _start_drain(self, reason: str, signals: dict,
                     now: float) -> bool:
        victim = self._pick_victim()
        if victim is None:
            return False
        if not self.pool.set_draining(victim, True):
            return False
        self._draining[victim] = {
            "since": now,
            "deadline": now + self.drain_deadline_s,
            "reason": reason,
        }
        self._count_scale("down", reason)
        obs_events.emit("autoscale", action="drain_start", reason=reason,
                        worker=victim, size=self.pool_size(),
                        **_sig_fields(signals))
        logger.info("autoscale: draining %s (%s)", victim, reason)
        return True

    def _advance_drains(self, now: float) -> None:
        for worker_id in list(self._draining):
            state = self._draining[worker_id]
            inflight = self.pool.inflight_of(worker_id)
            if inflight == 0:
                self._finish_drain(worker_id, state, now, timed_out=False)
            elif now >= state["deadline"]:
                # Deadline kill path: the victim is wedged or a client
                # holds a request forever. Retiring now can surface at
                # most the requests still on it — bounded, logged, and
                # the deadline is the operator's explicit choice.
                logger.warning("autoscale: drain of %s timed out with "
                               "%d in flight — retiring anyway",
                               worker_id, inflight)
                self._finish_drain(worker_id, state, now, timed_out=True)

    def _finish_drain(self, worker_id: str, state: dict, now: float,
                      timed_out: bool) -> None:
        drain_ms = (now - state["since"]) * 1e3
        self._drain_ms.observe(drain_ms)
        self.fleet.retire_worker(worker_id)
        self._draining.pop(worker_id, None)
        obs_events.emit("autoscale",
                        action="drain_deadline" if timed_out
                        else "drain_done",
                        worker=worker_id, reason=state["reason"],
                        drain_ms=round(drain_ms, 3),
                        size=self.pool_size())
        logger.info("autoscale: retired %s after %.0fms drain%s",
                    worker_id, drain_ms,
                    " (deadline)" if timed_out else "")

    def force_drain(self, reason: str = "forced") -> str | None:
        """Start a drain-down NOW, outside the idle policy (the
        ``drainworker@T`` chaos action, operator intervention). Skips
        hysteresis and cooldowns but never drains the last routable
        worker; the next control tick repairs the pool if it fell
        under ``min_workers``. Returns the victim id (None = no
        eligible victim)."""
        with self._lock:
            now = self.clock()
            draining_ids = set(self._draining)
            routable = [w for w in self.pool.workers()
                        if w.ready and w.worker_id not in draining_ids]
            if len(routable) < 2:
                logger.warning("autoscale: force_drain(%s) skipped — "
                               "%d routable worker(s)", reason,
                               len(routable))
                return None
            victim = self._pick_victim()
            if victim is None or not self._start_drain(
                    reason, {"queue_depth": None, "inflight": None,
                             "routable": len(routable),
                             "size": self.pool_size()}, now):
                return None
            return victim

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "ticks": self.ticks,
                "size": self.pool_size(),
                "min_workers": self.min_workers,
                "max_workers": self.max_workers,
                "up_streak": self._up_streak,
                "idle_streak": self._idle_streak,
                "draining": {w: {"reason": s["reason"]}
                             for w, s in self._draining.items()},
            }


def _sig_fields(signals: dict) -> dict:
    """The signal snapshot as flat event fields (rounded; None kept —
    an autoscale event must record what the controller actually saw,
    including 'no data')."""
    out = {}
    for key in ("queue_depth", "inflight", "routable", "p99_ms", "burn",
                "rate", "forecast_rate", "forecast_queue_depth",
                "rss_bytes"):
        v = signals.get(key)
        out[f"sig_{key}"] = round(v, 4) if isinstance(v, float) else v
    return out


def flash_crowd(url: str, body: bytes, duration_s: float = 2.0,
                concurrency: int = 8, tenant: str | None = None,
                timeout_s: float = 10.0) -> dict:
    """Blast one request body at a router for ``duration_s`` from
    ``concurrency`` closed-loop threads — the ``spike@T`` chaos
    action's payload (a deliberately rude burst; the OPEN-loop replay
    discipline lives in scripts/loadgen.py). Returns status counts.
    Blocking — chaos callers run it on a thread."""
    counts: dict[str, int] = {}
    lock = threading.Lock()
    deadline = time.monotonic() + float(duration_s)
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers["X-Tenant"] = tenant

    def _one() -> str:
        req = urllib.request.Request(url.rstrip("/") + "/embed",
                                     data=body, method="POST",
                                     headers=headers)
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return str(resp.status)
        except urllib.error.HTTPError as e:
            return str(e.code)
        except (urllib.error.URLError, OSError):
            return "unreachable"

    def _worker() -> None:
        while time.monotonic() < deadline:
            outcome = _one()
            with lock:
                counts[outcome] = counts.get(outcome, 0) + 1

    threads = [threading.Thread(target=_worker, daemon=True,
                                name=f"ntxent-spike-{i}")
               for i in range(int(concurrency))]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=duration_s + timeout_s + 5.0)
    logger.info("flash crowd done: %s", json.dumps(counts, sort_keys=True))
    return counts
