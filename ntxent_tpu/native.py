"""ctypes bridge to the native C++ NT-Xent core (native/).

The binding role the reference gave pybind11 (src/binding*.cpp), done with
ctypes against a C ABI so no torch/pybind build dependency exists. Provides
``forward_cpu``/``backward_cpu`` (the cross-language golden reference used by
tests/test_native.py) and ``build_native()`` to compile the library with
cmake+ninja on first use."""

from __future__ import annotations

import ctypes
import logging
import subprocess
from pathlib import Path

import numpy as np

__all__ = ["build_native", "load_library", "forward_cpu", "backward_cpu",
           "native_available"]

_NATIVE_DIR = Path(__file__).resolve().parent.parent / "native"
_BUILD_DIR = _NATIVE_DIR / "build"
_FFI_FAIL_STAMP = _BUILD_DIR / ".ffi_build_failed"
_LIB = None


def _sources_mtime() -> float:
    files = list((_NATIVE_DIR / "src").glob("*.cpp")) + \
        [_NATIVE_DIR / "CMakeLists.txt"]
    return max((f.stat().st_mtime for f in files if f.exists()), default=0.0)


def _run_logged(cmd: list[str]) -> None:
    proc = subprocess.run(cmd, cwd=_BUILD_DIR, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(
            f"native build step failed: {' '.join(cmd)}\n"
            f"--- stdout ---\n{proc.stdout}\n--- stderr ---\n{proc.stderr}")


def build_native(force: bool = False) -> Path:
    """Compile the native library (cmake + ninja/make). Returns the .so path.

    Rebuilds automatically when any native source is newer than the library.
    """
    try:  # the XLA FFI target needs jaxlib's bundled headers
        import jax.ffi

        ffi_include: str | None = jax.ffi.include_dir()
    except Exception:  # pragma: no cover - jax is a hard dep in practice
        ffi_include = None
    lib = _find_lib()
    src_mtime = _sources_mtime()
    fresh = lib is not None and not force \
        and lib.stat().st_mtime >= src_mtime
    # A stamp recording an FFI build failure (e.g. incompatible jaxlib
    # headers) counts as "fresh" so processes don't re-run the failing build
    # forever; editing any native source invalidates it.
    ffi_failed = _FFI_FAIL_STAMP.exists() \
        and _FFI_FAIL_STAMP.stat().st_mtime >= src_mtime
    ffi_lib = find_ffi_lib()
    ffi_fresh = ffi_include is None or ffi_failed or (
        ffi_lib is not None and not force
        and ffi_lib.stat().st_mtime >= src_mtime)
    if fresh and ffi_fresh:
        return lib
    _BUILD_DIR.mkdir(exist_ok=True)
    gen = ["-G", "Ninja"] if _have("ninja") else []
    defs = [] if ffi_include is None \
        else [f"-DXLA_FFI_INCLUDE_DIR={ffi_include}"]
    _run_logged(["cmake", *gen, *defs, ".."])
    _run_logged(["cmake", "--build", ".", "-j"])
    if ffi_include is not None:
        # Separate best-effort invocation: an FFI header/API incompatibility
        # must not take down the core ctypes library built above.
        try:
            _run_logged(["cmake", "--build", ".", "-j",
                         "--target", "ntxent_xla_ffi"])
            _FFI_FAIL_STAMP.unlink(missing_ok=True)
        except RuntimeError as e:
            logging.getLogger(__name__).warning(
                "XLA FFI library build failed (core library unaffected): %s", e)
            _FFI_FAIL_STAMP.write_text(str(e))
    lib = _find_lib()
    if lib is None:
        raise RuntimeError(f"native build produced no library in {_BUILD_DIR}")
    return lib


def _have(tool: str) -> bool:
    from shutil import which

    return which(tool) is not None


def _find_lib() -> Path | None:
    for name in ("libntxent_cpu.so", "libntxent_cpu.dylib"):
        p = _BUILD_DIR / name
        if p.exists():
            return p
    return None


def find_ffi_lib() -> Path | None:
    """Path of the XLA FFI custom-call library, if built (see ffi.py)."""
    for name in ("libntxent_xla_ffi.so", "libntxent_xla_ffi.dylib"):
        p = _BUILD_DIR / name
        if p.exists():
            return p
    return None


def native_available() -> bool:
    return _find_lib() is not None or _have("cmake")


def load_library(build_if_missing: bool = True) -> ctypes.CDLL:
    global _LIB
    if _LIB is not None:
        return _LIB
    if build_if_missing:
        lib_path = build_native()  # no-op when fresh; rebuilds when stale
    else:
        lib_path = _find_lib()
        if lib_path is None:
            raise FileNotFoundError("native library not built; call "
                                    "build_native() or run cmake in native/")
    lib = ctypes.CDLL(str(lib_path))
    lib.ntxent_forward_cpu.restype = ctypes.c_int
    lib.ntxent_forward_cpu.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_int64,
        ctypes.c_float, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.ntxent_backward_cpu.restype = ctypes.c_int
    lib.ntxent_backward_cpu.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_int64, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ctypes.POINTER(ctypes.c_float),
    ]
    lib.ntxent_native_threads.restype = ctypes.c_int
    _LIB = lib
    return lib


def _as_float_ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def forward_cpu(z: np.ndarray, temperature: float = 0.07,
                return_lse: bool = False):
    """Native canonical NT-Xent forward. z: (2N, D) float32."""
    lib = load_library()
    z = np.ascontiguousarray(z, dtype=np.float32)
    two_n, dim = z.shape
    loss = ctypes.c_float(-1.0)
    lse = np.empty(two_n, np.float32) if return_lse else None
    rc = lib.ntxent_forward_cpu(
        _as_float_ptr(z), two_n, dim, ctypes.c_float(temperature),
        ctypes.byref(loss),
        _as_float_ptr(lse) if lse is not None else None,
    )
    if rc != 0:
        raise ValueError(f"ntxent_forward_cpu failed (rc={rc}); check shapes "
                         f"({two_n}x{dim}) and temperature {temperature}")
    return (float(loss.value), lse) if return_lse else float(loss.value)


def backward_cpu(z: np.ndarray, temperature: float = 0.07,
                 grad_output: float = 1.0,
                 lse: np.ndarray | None = None) -> np.ndarray:
    """Native exact gradient of the mean loss w.r.t. z."""
    lib = load_library()
    z = np.ascontiguousarray(z, dtype=np.float32)
    two_n, dim = z.shape
    grad = np.empty_like(z)
    rc = lib.ntxent_backward_cpu(
        _as_float_ptr(z),
        _as_float_ptr(np.ascontiguousarray(lse, np.float32))
        if lse is not None else None,
        two_n, dim, ctypes.c_float(temperature),
        ctypes.c_float(grad_output), _as_float_ptr(grad),
    )
    if rc != 0:
        raise ValueError(f"ntxent_backward_cpu failed (rc={rc})")
    return grad
