"""IVF-flat nearest-neighbor search over embedding rows (numpy only).

The classic inverted-file layout the DLRM embedding-bag analysis
(PAPERS.md) assumes underneath its lookup traffic: k-means centroids
partition the vector set into lists, a query scores only the ``nprobe``
nearest lists, and within a list the scan is exact ("flat" — no
product quantization, embeddings here are small enough that the win is
list pruning, not code compression). Scores are INNER PRODUCT: the
fleet serves L2-normalized SimCLR/CLIP embeddings, so dot == cosine
and "largest score" is "nearest neighbor".

Two properties the index tier builds on:

* ``search`` WIDENS to every list when the probed lists hold fewer
  than ``k`` candidates, so a query never comes back short while the
  index has rows to give;
* everything is deterministic under a fixed seed (k-means++ init off a
  ``RandomState``), so the bench's recall@10 record is reproducible.
"""

from __future__ import annotations

import numpy as np

__all__ = ["kmeans", "brute_force_topk", "IVFIndex"]


def _as2d(x: np.ndarray) -> np.ndarray:
    x = np.asarray(x, np.float32)
    return x[None] if x.ndim == 1 else x


def brute_force_topk(queries: np.ndarray, ids: np.ndarray,
                     vectors: np.ndarray,
                     k: int) -> tuple[np.ndarray, np.ndarray]:
    """Exact inner-product top-k: ``(ids [Q,k], scores [Q,k])``,
    score-descending, padded with id -1 / score -inf when fewer than
    ``k`` rows exist."""
    q = _as2d(queries)
    nq, n = q.shape[0], int(vectors.shape[0])
    kk = min(k, n)
    out_ids = np.full((nq, k), -1, np.int64)
    out_scores = np.full((nq, k), -np.inf, np.float32)
    if n == 0 or kk == 0:
        return out_ids, out_scores
    scores = q @ np.asarray(vectors, np.float32).T  # [Q, n]
    top = np.argpartition(scores, -kk, axis=1)[:, -kk:]
    row = np.arange(nq)[:, None]
    order = np.argsort(scores[row, top], axis=1)[:, ::-1]
    top = top[row, order]
    out_ids[:, :kk] = np.asarray(ids, np.int64)[top]
    out_scores[:, :kk] = scores[row, top]
    return out_ids, out_scores


def kmeans(vectors: np.ndarray, k: int, iters: int = 10,
           seed: int = 0) -> np.ndarray:
    """Lloyd's k-means with k-means++ seeding; returns ``[k, dim]``
    centroids. Deterministic for a fixed seed; an empty cluster is
    re-seeded from the point farthest from its centroid."""
    x = _as2d(vectors)
    n = x.shape[0]
    k = max(1, min(int(k), n))
    rng = np.random.RandomState(seed)
    # k-means++: spread the initial centroids by D^2 sampling.
    centroids = np.empty((k, x.shape[1]), np.float32)
    centroids[0] = x[rng.randint(n)]
    d2 = np.full(n, np.inf, np.float64)
    for i in range(1, k):
        diff = x - centroids[i - 1]
        d2 = np.minimum(d2, np.einsum("nd,nd->n", diff, diff))
        total = float(d2.sum())
        if total <= 0.0:
            centroids[i:] = x[rng.randint(n, size=k - i)]
            break
        centroids[i] = x[rng.choice(n, p=d2 / total)]
    for _ in range(max(1, int(iters))):
        assign = _nearest(x, centroids)
        for c in range(k):
            members = x[assign == c]
            if members.shape[0]:
                centroids[c] = members.mean(axis=0)
            else:
                far = int(np.argmin((x @ centroids[c])))
                centroids[c] = x[far]
    return centroids


def _nearest(x: np.ndarray, centroids: np.ndarray) -> np.ndarray:
    """Index of the max-inner-product centroid per row."""
    return np.argmax(x @ centroids.T, axis=1)


class IVFIndex:
    """Inverted lists over trained centroids; grows incrementally.

    Each list is a ``segments.MutableSegment`` — ONE implementation of
    the geometric-growth parallel buffers and the lock-free
    count-before-buffers ``view()`` discipline, shared with the store's
    insert tail (a per-list duplicate of that subtle code would drift).
    Appends amortize to O(1)/row; the worst single append stall is one
    1.5x copy of THIS list, never a whole-index consolidation (which
    measured as a 100 ms search p99 spike when lists were block-chains
    consolidated in bulk)."""

    def __init__(self, centroids: np.ndarray):
        from .segments import MutableSegment

        self.centroids = np.asarray(centroids, np.float32)
        dim = self.centroids.shape[1]
        # chunk_rows=64: a barely-populated list must not pre-allocate
        # the store tail's 1024-row default times n_lists.
        self._lists = [MutableSegment(dim, chunk_rows=64)
                       for _ in range(self.centroids.shape[0])]

    @property
    def n_lists(self) -> int:
        return self.centroids.shape[0]

    def add(self, ids: np.ndarray, vectors: np.ndarray) -> None:
        ids = np.asarray(ids, np.int64)
        vecs = _as2d(vectors)
        assign = _nearest(vecs, self.centroids)
        for c in np.unique(assign):
            mask = assign == c
            self._lists[c].append(ids[mask], vecs[mask])

    def _list(self, c: int) -> tuple[np.ndarray, np.ndarray]:
        return self._lists[c].view()

    def search(self, queries: np.ndarray, k: int,
               nprobe: int) -> tuple[np.ndarray, np.ndarray]:
        """ANN top-k over the ``nprobe`` nearest lists per query.

        Scores are computed PER LIST (one small matmul each) and only
        the score/id arrays are merged — candidate VECTORS are never
        copied out of their lists, which is what keeps a probe cheaper
        than the brute-force scan it prunes."""
        q = _as2d(queries)
        nprobe = max(1, min(int(nprobe), self.n_lists))
        cs = q @ self.centroids.T  # [Q, k_lists]
        probe = np.argpartition(cs, -nprobe, axis=1)[:, -nprobe:]
        out_ids = np.full((q.shape[0], k), -1, np.int64)
        out_scores = np.full((q.shape[0], k), -np.inf, np.float32)
        for i in range(q.shape[0]):
            lists = [self._list(int(c)) for c in probe[i]]
            cand_n = sum(ids.shape[0] for ids, _ in lists)
            if cand_n < k and nprobe < self.n_lists:
                # Short lists must not short the answer: widen to the
                # full index (still exact within what exists).
                lists = [self._list(c) for c in range(self.n_lists)]
            cand_ids = [ids for ids, _ in lists if ids.shape[0]]
            cand_scores = [v @ q[i] for ids, v in lists
                           if ids.shape[0]]
            if not cand_ids:
                continue
            ids_cat = np.concatenate(cand_ids)
            scores_cat = np.concatenate(cand_scores)
            kk = min(k, ids_cat.shape[0])
            top = np.argpartition(scores_cat, -kk)[-kk:]
            top = top[np.argsort(scores_cat[top])[::-1]]
            out_ids[i, :kk] = ids_cat[top]
            out_scores[i, :kk] = scores_cat[top]
        return out_ids, out_scores
