"""Product quantization: compact codes + ADC search tables (ISSUE 17).

The DLRM embedding-bag analysis (PAPERS.md) puts large-scale retrieval
in the memory-bandwidth-bound regime: the scan cost is bytes touched
per row, not FLOPs. PQ attacks the bytes. A trained ``PQCodec`` splits
the embedding into ``m`` subspaces and quantizes each against its own
``ksub``-entry codebook, so a ``dim``-float row (4*dim bytes) becomes
``m`` uint8 codes — a 4*dim/m memory cut (32x at dim=64, m=8).

Search never decodes. **ADC** (asymmetric distance computation)
precomputes, per query, the inner product of each query subvector with
every codebook entry — an ``[m, ksub]`` lookup table — and a row's
approximate score is ``sum_j table[j, code[j]]``: m byte-gathers plus
m adds per row, the gather+scan loop scan.py fuses across queries.
Because the approximation only has to RANK candidates (the top
``rerank`` survivors are re-scored exactly from the raw mmap'd
vectors), modest codebooks keep recall@10 >= 0.95.

Optional **OPQ**: an orthonormal rotation learned by alternating
codebook refits with a Procrustes solve, so the subspace split aligns
with the data's principal structure instead of the arbitrary
coordinate order. Rotation is transparent to callers — ``encode``
rotates in, ``decode`` rotates back, ``adc_tables`` rotates the query
— and scores stay inner products (dot(q, R^T y) == dot(Rq, y)).

Training state (codebooks + rotation) persists per index version via
the same stage-fsync-rename idiom as the segments, so a restart
reopens a trained codec instead of re-clustering.

Numpy + stdlib only: the import-boundary lint and the fleet tripwire
pin that this module can never reach jax.
"""

from __future__ import annotations

import json
import os
import uuid
from pathlib import Path

import numpy as np

from .segments import _fsync_path

__all__ = ["PQCodec", "kmeans_l2"]

_META = "codec.json"
_BOOKS = "codebooks.f32"
_ROT = "rotation.f32"


def kmeans_l2(x: np.ndarray, k: int, iters: int = 12,
              seed: int = 0) -> np.ndarray:
    """Euclidean Lloyd's k-means with D^2 (k-means++) seeding.

    The IVF tier's ``kmeans`` assigns by max inner product (right for
    unit-norm embeddings); PQ subvectors are NOT unit-norm — slices of
    a unit vector — so codebook training must minimize actual L2
    reconstruction error or the ADC ranking degrades. Deterministic
    under a fixed seed; an empty cluster is re-seeded from the point
    farthest from its own centroid (same repair as the IVF trainer).
    """
    x = np.asarray(x, np.float32)
    if x.ndim == 1:
        x = x[None]
    n = x.shape[0]
    k = max(1, min(int(k), n))
    rng = np.random.RandomState(seed)
    centroids = np.empty((k, x.shape[1]), np.float32)
    centroids[0] = x[rng.randint(n)]
    d2 = np.full(n, np.inf, np.float64)
    for i in range(1, k):
        diff = x - centroids[i - 1]
        d2 = np.minimum(d2, np.einsum("nd,nd->n", diff, diff))
        total = float(d2.sum())
        if total <= 0.0:
            centroids[i:] = x[rng.randint(n, size=k - i)]
            break
        centroids[i] = x[rng.choice(n, p=d2 / total)]
    xsq = np.einsum("nd,nd->n", x, x)
    for _ in range(max(1, int(iters))):
        assign = _assign_l2(x, centroids, xsq)
        for c in range(k):
            members = x[assign == c]
            if members.shape[0]:
                centroids[c] = members.mean(axis=0)
            else:
                diff = x - centroids[c]
                far = int(np.argmax(np.einsum("nd,nd->n", diff, diff)))
                centroids[c] = x[far]
    return centroids


def _assign_l2(x: np.ndarray, centroids: np.ndarray,
               xsq: np.ndarray | None = None) -> np.ndarray:
    """argmin_c ||x - c||^2 via the expanded form (never materializes
    per-pair difference tensors)."""
    # ||x||^2 is constant per row for the argmin — only needed by
    # callers that want true distances; the assignment drops it.
    d = -2.0 * (x @ centroids.T)
    d += np.einsum("kd,kd->k", centroids, centroids)[None, :]
    return np.argmin(d, axis=1)


class PQCodec:
    """Product quantizer over ``dim`` floats: ``m`` subspaces of
    ``dsub = dim/m`` floats, each coded against ``ksub`` centroids.

    ``m`` is clamped to the largest divisor of ``dim`` not exceeding
    the request — subspaces must tile the vector exactly. ``gen``
    counts trainings: sealed segments stamp the generation their codes
    were produced under, so a retrain invalidates stale codes instead
    of silently mixing codebooks.
    """

    def __init__(self, dim: int, m: int = 8, ksub: int = 256,
                 seed: int = 0):
        self.dim = int(dim)
        m = max(1, min(int(m), self.dim))
        while self.dim % m:
            m -= 1
        self.m = m
        self.dsub = self.dim // self.m
        self.ksub = max(2, min(int(ksub), 256))  # codes are uint8
        self.seed = int(seed)
        self.gen = 0
        # [m, ksub, dsub] once trained.
        self.codebooks: np.ndarray | None = None
        # Optional OPQ rotation [dim, dim] (orthonormal); None = identity.
        self.rotation: np.ndarray | None = None

    # -- training ------------------------------------------------------------
    @property
    def trained(self) -> bool:
        return self.codebooks is not None

    @property
    def bytes_per_row(self) -> int:
        """Code bytes the scan touches per stored row."""
        return self.m

    def train(self, x: np.ndarray, kmeans_iters: int = 12,
              opq_iters: int = 0) -> "PQCodec":
        """Fit codebooks (and, with ``opq_iters > 0``, the OPQ
        rotation) on a sample of rows. Deterministic per seed."""
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None]
        if x.shape[1] != self.dim:
            raise ValueError(f"expected dim {self.dim}, got {x.shape[1]}")
        rot = None
        xr = x
        for it in range(max(0, int(opq_iters))):
            books = self._fit_books(xr, kmeans_iters)
            recon = self._decode_rotated(self._encode_rotated(xr, books),
                                         books)
            # Procrustes: the orthonormal R minimizing ||xR - recon||_F
            # is U @ Vt of x^T recon.
            u, _, vt = np.linalg.svd(x.T @ recon)
            rot = np.ascontiguousarray((u @ vt), np.float32)
            xr = x @ rot
        self.codebooks = self._fit_books(xr, kmeans_iters)
        self.rotation = rot
        self.gen += 1
        return self

    def _fit_books(self, xr: np.ndarray, iters: int) -> np.ndarray:
        books = np.zeros((self.m, self.ksub, self.dsub), np.float32)
        for j in range(self.m):
            sub = xr[:, j * self.dsub:(j + 1) * self.dsub]
            got = kmeans_l2(sub, self.ksub, iters=iters,
                            seed=self.seed + j)
            books[j, : got.shape[0]] = got
            if got.shape[0] < self.ksub:
                # Fewer training rows than codes: duplicate the fitted
                # entries so unused code slots never win an argmin by
                # sitting at the origin.
                books[j, got.shape[0]:] = got[
                    np.arange(self.ksub - got.shape[0]) % got.shape[0]]
        return books

    # -- coding --------------------------------------------------------------
    def _rotate(self, x: np.ndarray) -> np.ndarray:
        return x if self.rotation is None else x @ self.rotation

    def _encode_rotated(self, xr: np.ndarray,
                        books: np.ndarray) -> np.ndarray:
        n = xr.shape[0]
        codes = np.empty((n, self.m), np.uint8)
        for j in range(books.shape[0]):
            sub = xr[:, j * self.dsub:(j + 1) * self.dsub]
            codes[:, j] = _assign_l2(sub, books[j]).astype(np.uint8)
        return codes

    def _decode_rotated(self, codes: np.ndarray,
                        books: np.ndarray) -> np.ndarray:
        out = np.empty((codes.shape[0], self.dim), np.float32)
        for j in range(books.shape[0]):
            out[:, j * self.dsub:(j + 1) * self.dsub] = \
                books[j][codes[:, j]]
        return out

    def encode(self, x: np.ndarray) -> np.ndarray:
        """Rows -> uint8 codes ``[n, m]``."""
        if self.codebooks is None:
            raise RuntimeError("codec not trained")
        x = np.asarray(x, np.float32)
        if x.ndim == 1:
            x = x[None]
        return self._encode_rotated(self._rotate(x), self.codebooks)

    def decode(self, codes: np.ndarray) -> np.ndarray:
        """Codes -> approximate rows ``[n, dim]`` (rotated back)."""
        if self.codebooks is None:
            raise RuntimeError("codec not trained")
        codes = np.asarray(codes, np.uint8)
        if codes.ndim == 1:
            codes = codes[None]
        out = self._decode_rotated(codes, self.codebooks)
        return out if self.rotation is None else out @ self.rotation.T

    def adc_tables(self, queries: np.ndarray) -> np.ndarray:
        """Per-query ADC lookup tables ``[Q, m, ksub]``: entry
        ``[q, j, c]`` is the inner product of query q's j-th subvector
        with codebook entry c — a coded row's approximate score is the
        sum of m table lookups, never a decode."""
        if self.codebooks is None:
            raise RuntimeError("codec not trained")
        q = np.asarray(queries, np.float32)
        if q.ndim == 1:
            q = q[None]
        qr = self._rotate(q)
        # [Q, m, dsub] x [m, ksub, dsub] -> [Q, m, ksub]
        qs = qr.reshape(q.shape[0], self.m, self.dsub)
        return np.einsum("qjd,jkd->qjk", qs, self.codebooks,
                         optimize=True).astype(np.float32, copy=False)

    # -- wire ----------------------------------------------------------------
    def to_wire(self) -> dict:
        """JSON-safe codec state (base64 blobs) — the shard plane
        pushes a centrally trained codec to its workers over HTTP."""
        if self.codebooks is None:
            raise RuntimeError("codec not trained")
        import base64

        wire = {"dim": self.dim, "m": self.m, "ksub": self.ksub,
                "seed": self.seed, "gen": self.gen,
                "books": base64.b64encode(
                    np.ascontiguousarray(self.codebooks)
                    .tobytes()).decode("ascii")}
        if self.rotation is not None:
            wire["rotation"] = base64.b64encode(
                np.ascontiguousarray(self.rotation)
                .tobytes()).decode("ascii")
        return wire

    @classmethod
    def from_wire(cls, wire: dict) -> "PQCodec":
        import base64

        codec = cls(int(wire["dim"]), m=int(wire["m"]),
                    ksub=int(wire["ksub"]),
                    seed=int(wire.get("seed", 0)))
        codec.codebooks = np.frombuffer(
            base64.b64decode(wire["books"]), np.float32).reshape(
                codec.m, codec.ksub, codec.dsub).copy()
        if wire.get("rotation"):
            codec.rotation = np.frombuffer(
                base64.b64decode(wire["rotation"]),
                np.float32).reshape(codec.dim, codec.dim).copy()
        codec.gen = int(wire.get("gen", 1))
        return codec

    # -- durability ----------------------------------------------------------
    def save(self, parent) -> Path:
        """Persist codebooks+rotation under ``parent/codec`` with the
        segment tier's stage-fsync-rename idiom (a crash leaves either
        the old codec or the new one, never a torn mix)."""
        if self.codebooks is None:
            raise RuntimeError("codec not trained")
        parent = Path(parent)
        parent.mkdir(parents=True, exist_ok=True)
        tmp = parent / f".tmp-codec-{uuid.uuid4().hex[:8]}"
        tmp.mkdir(parents=True)
        blobs = [(_BOOKS, np.ascontiguousarray(self.codebooks))]
        if self.rotation is not None:
            blobs.append((_ROT, np.ascontiguousarray(self.rotation)))
        for fname, arr in blobs:
            with open(tmp / fname, "wb") as f:
                f.write(arr.tobytes())
                f.flush()
                os.fsync(f.fileno())
        meta = {"dim": self.dim, "m": self.m, "ksub": self.ksub,
                "seed": self.seed, "gen": self.gen,
                "rotated": self.rotation is not None}
        with open(tmp / _META, "w") as f:
            json.dump(meta, f)
            f.flush()
            os.fsync(f.fileno())
        _fsync_path(tmp)
        final = parent / "codec"
        if final.exists():
            # rename() cannot replace a non-empty directory: retire the
            # old codec aside first (same two-step the checkpoint tier
            # uses); readers hold arrays, not paths, so this is safe.
            import shutil
            old = parent / f".old-codec-{uuid.uuid4().hex[:8]}"
            os.rename(final, old)
            os.rename(tmp, final)
            shutil.rmtree(old, ignore_errors=True)
        else:
            os.rename(tmp, final)
        _fsync_path(parent)
        return final

    @classmethod
    def load(cls, parent) -> "PQCodec | None":
        """Reopen a persisted codec; None when absent or unreadable
        (an unreadable snapshot falls back to retraining — never an
        exception out of an index open)."""
        path = Path(parent) / "codec"
        try:
            meta = json.loads((path / _META).read_text())
            codec = cls(int(meta["dim"]), m=int(meta["m"]),
                        ksub=int(meta["ksub"]),
                        seed=int(meta.get("seed", 0)))
            if codec.m != int(meta["m"]):
                return None
            raw = np.fromfile(path / _BOOKS, dtype=np.float32)
            codec.codebooks = raw.reshape(codec.m, codec.ksub,
                                          codec.dsub).copy()
            if meta.get("rotated"):
                rot = np.fromfile(path / _ROT, dtype=np.float32)
                codec.rotation = rot.reshape(codec.dim,
                                             codec.dim).copy()
            codec.gen = int(meta.get("gen", 1))
            return codec
        except (OSError, ValueError, KeyError, TypeError):
            return None
